"""Runtime: cluster-spec env injection and rendezvous helpers."""

from .env import build_cluster_env, replica_rank  # noqa: F401
