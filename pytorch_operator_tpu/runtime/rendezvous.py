"""Worker-side rendezvous and status reporting.

Reference mapping (SURVEY.md §5 "Distributed communication backend"):

- ``dist.init_process_group('nccl', init_method='env://')`` reading
  MASTER_ADDR/PORT/RANK/WORLD_SIZE → :func:`initialize_from_env` reading the
  TPUJOB_* env the supervisor injected and calling
  ``jax.distributed.initialize(coordinator, num_processes, process_id)``.
- The reference's worker initContainer DNS-gate (``until nslookup
  $MASTER_ADDR``) → jax.distributed's built-in connect retry; we add an
  outer retry loop for coordinator-not-yet-listening races.
- DDP allreduce hooks over NCCL → XLA collectives over ICI/DCN, expressed
  via jax.sharding / shard_map in the workload (parallel/).

Workloads also report events (first step, per-step metrics) to
``$TPUJOB_STATUS_DIR`` as JSONL; the supervisor folds these into job status
(schedule-to-first-step latency, BASELINE.json:2).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Optional


@dataclass
class WorldInfo:
    num_processes: int
    process_id: int
    coordinator: str
    replica_type: str
    replica_index: int
    restart_count: int
    job_key: str

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def world_from_env() -> WorldInfo:
    """Read the supervisor-injected cluster spec (SetClusterSpec analog)."""
    return WorldInfo(
        num_processes=int(os.environ.get("TPUJOB_NUM_PROCESSES", "1")),
        process_id=int(os.environ.get("TPUJOB_PROCESS_ID", "0")),
        coordinator=os.environ.get("TPUJOB_COORDINATOR_ADDRESS", "127.0.0.1:23456"),
        replica_type=os.environ.get("TPUJOB_REPLICA_TYPE", "Master"),
        replica_index=int(os.environ.get("TPUJOB_REPLICA_INDEX", "0")),
        restart_count=int(os.environ.get("TPUJOB_RESTART_COUNT", "0")),
        job_key=os.environ.get("TPUJOB_KEY", "default/local"),
    )


def fault_stall_if_armed() -> float:
    """The ``stall_rendezvous`` injection site: sleep (and report) the
    seconds an armed fault plan asks for, returning them. A no-op
    (0.0, no imports beyond the light faults package) without a plan.

    Public because workloads that never reach jax.distributed (e.g. the
    single-process ``exit_with`` chaos casualty) call it directly to
    model a slow join on the same code path."""
    from .. import faults

    seconds = faults.rendezvous_stall_seconds()
    if seconds > 0:
        report("fault_stall", seconds=seconds, site="rendezvous")
        time.sleep(seconds)
    return seconds


def join_backoff(timeout_s: float, base_s: float, seed: int):
    """The rendezvous retry schedule: exponential + deterministic jitter
    (seeded per process id so a gang's workers decorrelate instead of
    herding on the coordinator every fixed 1 s), capped well inside the
    join timeout so late attempts still fit."""
    from ..backoff import Backoff

    return Backoff(
        base_s=base_s,
        cap_s=max(base_s, min(10.0, timeout_s / 4.0)),
        jitter=0.25,
        seed=seed,
    )


def initialize_from_env(
    timeout_s: float = 60.0, retry_interval_s: float = 1.0
) -> WorldInfo:
    """Join the jax.distributed world described by the environment.

    Single-process worlds skip initialization entirely (single-process SPMD
    across local devices). Multi-process worlds call
    ``jax.distributed.initialize`` with retries — the connect-retry gate
    that replaces the reference's initContainer DNS loop, now on a
    jittered exponential backoff (``retry_interval_s`` is the base
    delay); the outer ``timeout_s`` contract is unchanged.
    """
    from .backend import setup_backend
    from .. import obs

    t_join = time.time()
    fault_stall_if_armed()
    setup_backend()
    world = world_from_env()
    if world.num_processes <= 1:
        return world

    import jax

    from ..backoff import retry_call

    def join():
        jax.distributed.initialize(
            coordinator_address=world.coordinator,
            num_processes=world.num_processes,
            process_id=world.process_id,
        )

    try:
        with obs.span(
            "rendezvous_join", cat="rendezvous",
            coordinator=world.coordinator, world=world.num_processes,
        ):
            retry_call(
                join,
                backoff=join_backoff(
                    timeout_s, retry_interval_s, world.process_id
                ),
                timeout_s=timeout_s,
            )
        # Join latency rides the status channel into the supervisor's
        # /metrics histogram (the supervisor cannot time a join it does
        # not perform).
        report("rendezvous_join", seconds=time.time() - t_join)
        return world
    except Exception as e:  # pragma: no cover - env-dependent errors
        raise TimeoutError(
            f"rendezvous with coordinator {world.coordinator} failed after "
            f"{timeout_s}s: {e}"
        ) from e


# ---- status reporting (workload → supervisor) ----


def _status_path() -> Optional[Path]:
    d = os.environ.get("TPUJOB_STATUS_DIR")
    if not d:
        return None
    rtype = os.environ.get("TPUJOB_REPLICA_TYPE", "Master").lower()
    idx = os.environ.get("TPUJOB_REPLICA_INDEX", "0")
    return Path(d) / f"{rtype}-{idx}.jsonl"


def report(event: str, **fields) -> None:
    """Append a status record; no-op when not running under the supervisor."""
    path = _status_path()
    if path is None:
        return
    rec = {"event": event, "ts": time.time(), **fields}
    try:
        with path.open("a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass


def progress_enabled() -> bool:
    """Is anyone listening? Workloads gate their heartbeat on this so a
    standalone benchmark run (no supervisor, no status dir) pays zero
    telemetry fences and stays A/B-comparable with older numbers."""
    return _status_path() is not None


# The last supervisor clock-probe seq this process echoed (each probe
# is answered exactly once — a re-echo would hand the estimator a
# stale round trip whose [probe, observe] interval spans seconds).
_probe_echoed_seq: Optional[int] = None


def _maybe_echo_probe() -> None:
    """Echo the supervisor's round-trip clock probe (obs/clock.py):
    read ``clock_probe.json`` from the status dir and, for a probe not
    yet answered, append a ``clock_probe`` record whose own ``ts`` is
    this replica's send time — the (probe write, echo send, echo
    observe) triple lets the offset estimator cancel the one-way delay
    bias. Piggybacks on the heartbeat cadence: one stat+read per beat,
    nothing without a supervisor."""
    global _probe_echoed_seq
    d = os.environ.get("TPUJOB_STATUS_DIR")
    if not d:
        return
    from ..obs.clock import read_probe

    probe = read_probe(d)
    if probe is None or probe["seq"] == _probe_echoed_seq:
        return
    _probe_echoed_seq = probe["seq"]
    report("clock_probe", probe_ts=probe["probe_ts"], seq=probe["seq"])


def report_first_step(step: int = 0) -> None:
    report("first_step", step=step)


def report_metrics(step: int, **metrics) -> None:
    report("metrics", step=step, **metrics)


def report_progress(
    step: int,
    *,
    loss: Optional[float] = None,
    steps_per_sec: Optional[float] = None,
    throughput: Optional[float] = None,
    unit: Optional[str] = None,
    step_time_ms: Optional[float] = None,
    feed_stall_ms: Optional[float] = None,
) -> None:
    """Live training heartbeat (step/loss/throughput) for the operator
    surface: the supervisor folds the newest record into per-job
    /metrics gauges and ``tpujob describe``'s "Training" block
    (controller/progress.py). Emit every ~10s, not every step — each
    record is a host write and the caller usually pays a device fence
    to know the loss."""
    # ``drop_heartbeat`` injection site: an armed fault plan can
    # suppress heartbeats to trip the supervisor's hung-world detector
    # (controller/reconciler.py). No-op without a plan.
    from .. import faults

    if faults.heartbeat_dropped():
        return
    fields = {}
    if loss is not None:
        fields["loss"] = round(float(loss), 6)
    if steps_per_sec is not None:
        fields["steps_per_sec"] = round(float(steps_per_sec), 4)
    if throughput is not None:
        fields["throughput"] = round(float(throughput), 4)
    if unit is not None:
        fields["unit"] = unit
    if step_time_ms is not None:
        fields["step_time_ms"] = round(float(step_time_ms), 3)
    if feed_stall_ms is not None:
        fields["feed_stall_ms"] = round(float(feed_stall_ms), 3)
    report("progress", step=step, **fields)
    # Round-trip clock probe: answered on the heartbeat cadence, AFTER
    # the beat (the supervisor probes jobs it just saw beating).
    _maybe_echo_probe()


def report_serve(
    requests: int,
    *,
    slots: int,
    slots_free: int,
    queued: int = 0,
    pending: int = 0,
    ttft_ms_p50: Optional[float] = None,
    ttft_ms_p99: Optional[float] = None,
    tpot_ms_p50: Optional[float] = None,
    tpot_ms_p99: Optional[float] = None,
) -> None:
    """Serve-plane load beat: slot occupancy, queue depth, and latency
    percentiles for this engine replica. The supervisor's router
    (serving/router.py) reads the newest record per replica from the
    heartbeat fold — zero extra I/O — to score least-loaded dispatch,
    and the queue_growth / batch_size_collapse detectors judge the same
    stream. Emit on the serve loop's report cadence, like progress."""
    fields: dict = {
        "slots": int(slots),
        "slots_free": int(slots_free),
        "queued": int(queued),
        "pending": int(pending),
    }
    for k, v in (
        ("ttft_ms_p50", ttft_ms_p50),
        ("ttft_ms_p99", ttft_ms_p99),
        ("tpot_ms_p50", tpot_ms_p50),
        ("tpot_ms_p99", tpot_ms_p99),
    ):
        if v is not None:
            fields[k] = round(float(v), 3)
    report("serve", requests=int(requests), **fields)


def report_checkpoint_committed(
    step: int,
    commit_s: float,
    queue_depth: int = 0,
    oldest_age_s: float = 0.0,
    stage_depth: int = 0,
) -> None:
    """Async-checkpoint commit telemetry for the operator surface: the
    supervisor folds the newest record into the per-job checkpoint-step
    /queue-depth/oldest-inflight-age/stage-depth gauges and observes
    the commit duration into ``tpujob_checkpoint_commit_seconds`` —
    checkpoint lag in ``tpujob top`` is ``job_step -
    job_checkpoint_step``. ``stage_depth`` counts submitted saves whose
    device→host gather has not finished (the staged writer's snapshot
    stage — a growing value means gathers cannot keep up with the save
    cadence)."""
    report(
        "checkpoint_committed",
        step=step,
        commit_ms=round(1000.0 * commit_s, 3),
        queue_depth=int(queue_depth),
        oldest_age_s=round(oldest_age_s, 3),
        stage_depth=int(stage_depth),
    )
