"""Worker-side rendezvous and status reporting.

Reference mapping (SURVEY.md §5 "Distributed communication backend"):

- ``dist.init_process_group('nccl', init_method='env://')`` reading
  MASTER_ADDR/PORT/RANK/WORLD_SIZE → :func:`initialize_from_env` reading the
  TPUJOB_* env the supervisor injected and calling
  ``jax.distributed.initialize(coordinator, num_processes, process_id)``.
- The reference's worker initContainer DNS-gate (``until nslookup
  $MASTER_ADDR``) → jax.distributed's built-in connect retry; we add an
  outer retry loop for coordinator-not-yet-listening races.
- DDP allreduce hooks over NCCL → XLA collectives over ICI/DCN, expressed
  via jax.sharding / shard_map in the workload (parallel/).

Workloads also report events (first step, per-step metrics) to
``$TPUJOB_STATUS_DIR`` as JSONL; the supervisor folds these into job status
(schedule-to-first-step latency, BASELINE.json:2).
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Optional


@dataclass
class WorldInfo:
    num_processes: int
    process_id: int
    coordinator: str
    replica_type: str
    replica_index: int
    restart_count: int
    job_key: str
    # Elastic resize epoch (controller/elastic.py): the generation of the
    # world this process belongs to. A resize record with a NEWER
    # generation in the status dir means the world moved on — poll_resize
    # yields either the process's place in the new world or its eviction.
    resize_generation: int = 0

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def world_from_env() -> WorldInfo:
    """Read the supervisor-injected cluster spec (SetClusterSpec analog)."""
    return WorldInfo(
        num_processes=int(os.environ.get("TPUJOB_NUM_PROCESSES", "1")),
        process_id=int(os.environ.get("TPUJOB_PROCESS_ID", "0")),
        coordinator=os.environ.get("TPUJOB_COORDINATOR_ADDRESS", "127.0.0.1:23456"),
        replica_type=os.environ.get("TPUJOB_REPLICA_TYPE", "Master"),
        replica_index=int(os.environ.get("TPUJOB_REPLICA_INDEX", "0")),
        restart_count=int(os.environ.get("TPUJOB_RESTART_COUNT", "0")),
        job_key=os.environ.get("TPUJOB_KEY", "default/local"),
        resize_generation=int(os.environ.get("TPUJOB_RESIZE_GENERATION", "0")),
    )


def fault_stall_if_armed() -> float:
    """The ``stall_rendezvous`` injection site: sleep (and report) the
    seconds an armed fault plan asks for, returning them. A no-op
    (0.0, no imports beyond the light faults package) without a plan.

    Public because workloads that never reach jax.distributed (e.g. the
    single-process ``exit_with`` chaos casualty) call it directly to
    model a slow join on the same code path."""
    from .. import faults

    seconds = faults.rendezvous_stall_seconds()
    if seconds > 0:
        report("fault_stall", seconds=seconds, site="rendezvous")
        time.sleep(seconds)
    return seconds


def join_backoff(timeout_s: float, base_s: float, seed: int):
    """The rendezvous retry schedule: exponential + deterministic jitter
    (seeded per process id so a gang's workers decorrelate instead of
    herding on the coordinator every fixed 1 s), capped well inside the
    join timeout so late attempts still fit."""
    from ..backoff import Backoff

    return Backoff(
        base_s=base_s,
        cap_s=max(base_s, min(10.0, timeout_s / 4.0)),
        jitter=0.25,
        seed=seed,
    )


# ---- elastic resize (controller/elastic.py is the writer) ----


@dataclass
class ResizeSignal:
    """One observed resize-record advance: either this process's place in
    the new world, or its eviction from it."""

    generation: int
    evicted: bool
    world: Optional[WorldInfo]  # None when evicted
    restore_step: Optional[int]  # last sidecar-verified step at resize time
    record: dict


def _member_id(world: WorldInfo) -> str:
    return f"{world.replica_type.lower()}-{world.replica_index}"


def read_resize_record() -> Optional[dict]:
    d = os.environ.get("TPUJOB_STATUS_DIR")
    if not d:
        return None
    try:
        with open(Path(d) / "resize.json") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def poll_resize(world: WorldInfo) -> Optional[ResizeSignal]:
    """Step-loop resize check: has the supervisor advanced the world past
    this process's generation? One stat+read per call, nothing without a
    status dir. Returns None while the world is current; otherwise a
    signal carrying the new membership — or the eviction fence: a
    process absent from the record's rank map has NO place in the new
    world and must exit rather than join (the stale-generation-straggler
    guard)."""
    rec = read_resize_record()
    if rec is None:
        return None
    try:
        gen = int(rec.get("generation", 0))
    except (TypeError, ValueError):
        return None
    if gen <= world.resize_generation:
        return None
    ranks = rec.get("ranks") or {}
    restore = rec.get("restore_step")
    restore = int(restore) if restore is not None else None
    rank = ranks.get(_member_id(world))
    if rank is None:
        return ResizeSignal(gen, True, None, restore, rec)
    from dataclasses import replace

    new_world = replace(
        world,
        num_processes=int(rec.get("world_size", len(ranks))),
        process_id=int(rank),
        coordinator=str(rec.get("coordinator", world.coordinator)),
        resize_generation=gen,
    )
    return ResizeSignal(gen, False, new_world, restore, rec)


def adopt_resize(sig: ResizeSignal) -> WorldInfo:
    """Become a member of the resized world (jax-free path: the caller's
    step loop keeps running with the returned WorldInfo). Reports the
    re-join on the status channel — `tpujob why`'s resize history and
    the bench's duplicate-rank check both read these records."""
    report(
        "resize_join",
        generation=sig.generation,
        rank=sig.world.process_id,
        world_size=sig.world.num_processes,
    )
    return sig.world


def exit_for_resize(sig: ResizeSignal) -> None:
    """Terminal resize outcomes. Evicted: report and exit 0 — this
    process has no rank in the new world (fenced out). Member of a REAL
    jax.distributed world: re-exec in place — same pid, same log file,
    no scheduler round trip — with the environment rewritten to the new
    generation's coordinates; the fresh ``main()`` re-joins at the new
    coordinator and restores from the last verified checkpoint. (In-
    process jax.distributed re-initialization is not reliably supported;
    exec is the surgical alternative to a gang teardown.)"""
    import sys

    if sig.evicted:
        report("resize_evicted", generation=sig.generation)
        print(
            f"[rendezvous] evicted by resize generation {sig.generation}; "
            "exiting.",
            flush=True,
        )
        sys.stdout.flush()
        sys.stderr.flush()
        raise SystemExit(0)
    w = sig.world
    host, _, port = w.coordinator.rpartition(":")
    os.environ.update(
        {
            "TPUJOB_NUM_PROCESSES": str(w.num_processes),
            "TPUJOB_PROCESS_ID": str(w.process_id),
            "TPUJOB_COORDINATOR_ADDRESS": w.coordinator,
            "TPUJOB_RESIZE_GENERATION": str(w.resize_generation),
            "WORLD_SIZE": str(w.num_processes),
            "RANK": str(w.process_id),
            "MASTER_ADDR": host or "127.0.0.1",
            "MASTER_PORT": port,
            "TPU_WORKER_ID": str(w.process_id),
            "TPU_WORKER_HOSTNAMES": ",".join(
                [host or "127.0.0.1"] * w.num_processes
            ),
        }
    )
    report(
        "resize_join",
        generation=sig.generation,
        rank=w.process_id,
        world_size=w.num_processes,
        via="exec",
    )
    print(
        f"[rendezvous] re-joining resized world: generation "
        f"{sig.generation}, rank {w.process_id}/{w.num_processes} "
        f"at {w.coordinator} (in-place exec)",
        flush=True,
    )
    sys.stdout.flush()
    sys.stderr.flush()
    argv = getattr(sys, "orig_argv", None)
    if argv and len(argv) > 1:
        os.execv(sys.executable, [sys.executable] + list(argv[1:]))
    os.execv(sys.executable, [sys.executable] + sys.argv)


def initialize_from_env(
    timeout_s: float = 60.0, retry_interval_s: float = 1.0
) -> WorldInfo:
    """Join the jax.distributed world described by the environment.

    Single-process worlds skip initialization entirely (single-process SPMD
    across local devices). Multi-process worlds call
    ``jax.distributed.initialize`` with retries — the connect-retry gate
    that replaces the reference's initContainer DNS loop, now on a
    jittered exponential backoff (``retry_interval_s`` is the base
    delay); the outer ``timeout_s`` contract is unchanged.
    """
    from .backend import setup_backend
    from .. import obs

    t_join = time.time()
    fault_stall_if_armed()
    setup_backend()
    world = world_from_env()
    # Resize fence: an environment stamped with an older generation than
    # the status dir's resize record describes a world that no longer
    # exists. A straggler still named in the new member map adopts its
    # new coordinates BEFORE the first join (a promoted spare or a
    # replica recreated mid-failover lands here); one absent from the
    # map is fenced out and exits cleanly — it must not camp on the old
    # coordinator port waiting for a gang that will never assemble.
    sig = poll_resize(world)
    if sig is not None:
        if sig.evicted:
            exit_for_resize(sig)
        world = adopt_resize(sig)
    if world.num_processes <= 1:
        return world

    import jax

    from ..backoff import retry_call

    def join():
        jax.distributed.initialize(
            coordinator_address=world.coordinator,
            num_processes=world.num_processes,
            process_id=world.process_id,
        )

    try:
        with obs.span(
            "rendezvous_join", cat="rendezvous",
            coordinator=world.coordinator, world=world.num_processes,
        ):
            retry_call(
                join,
                backoff=join_backoff(
                    timeout_s, retry_interval_s, world.process_id
                ),
                timeout_s=timeout_s,
            )
        # Join latency rides the status channel into the supervisor's
        # /metrics histogram (the supervisor cannot time a join it does
        # not perform).
        report("rendezvous_join", seconds=time.time() - t_join)
        return world
    except Exception as e:  # pragma: no cover - env-dependent errors
        raise TimeoutError(
            f"rendezvous with coordinator {world.coordinator} failed after "
            f"{timeout_s}s: {e}"
        ) from e


def finalize(world: WorldInfo, exit_code: int = 0) -> None:
    """Leave a multi-process world deterministically after the workload
    finished: coordination-service barrier, leader grace, hard
    ``os._exit``.

    The hard exit is the point. jax's implicit atexit teardown races
    its own gloo/coordination threads and intermittently segfaults a
    replica that COMPLETED all its work — and a 139 is retryable, so
    every such exit burns a restart and re-runs a finished life. A
    replica that reached finalize owes nothing to interpreter teardown;
    flush and leave. Single-process worlds (nothing was initialized)
    return normally so in-process callers (unit tests) survive.

    The barrier is the coordination service's key-value barrier (pure
    RPC), NOT a jax collective — multi-process collectives are backend-
    dependent (unimplemented on CPU) and ``jax.distributed.shutdown``
    itself is part of the teardown being avoided. After the barrier,
    every peer is provably done; non-leaders exit immediately, and the
    leader lingers one beat so the coordination service it hosts stays
    up while they leave (a leader that vanishes first turns its peers'
    clean exits into "leader task died" aborts).

    A barrier failure is swallowed: it means a PEER died, and that is
    the supervisor's problem — this replica's work is done and its exit
    code must say so.
    """
    if world.num_processes <= 1:
        return
    sys.stdout.flush()
    sys.stderr.flush()
    try:
        from jax._src import distributed

        client = distributed.global_state.client
        if client is not None:
            try:
                client.wait_at_barrier("tpujob_finalize", 10_000)
            except Exception:
                # invariant: waived — finalize barrier is best-effort; peers may already be gone at exit
                pass
            if world.process_id == 0:
                time.sleep(1.0)
    except Exception:
        # invariant: waived — nothing may stop the resize exit code from reaching the supervisor via os._exit
        pass
    os._exit(exit_code)


# ---- status reporting (workload → supervisor) ----


def _status_path() -> Optional[Path]:
    d = os.environ.get("TPUJOB_STATUS_DIR")
    if not d:
        return None
    rtype = os.environ.get("TPUJOB_REPLICA_TYPE", "Master").lower()
    idx = os.environ.get("TPUJOB_REPLICA_INDEX", "0")
    return Path(d) / f"{rtype}-{idx}.jsonl"


def report(event: str, **fields) -> None:
    """Append a status record; no-op when not running under the supervisor."""
    path = _status_path()
    if path is None:
        return
    rec = {"event": event, "ts": time.time(), **fields}
    try:
        with path.open("a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass


def progress_enabled() -> bool:
    """Is anyone listening? Workloads gate their heartbeat on this so a
    standalone benchmark run (no supervisor, no status dir) pays zero
    telemetry fences and stays A/B-comparable with older numbers."""
    return _status_path() is not None


# The last supervisor clock-probe seq this process echoed (each probe
# is answered exactly once — a re-echo would hand the estimator a
# stale round trip whose [probe, observe] interval spans seconds).
_probe_echoed_seq: Optional[int] = None


def _maybe_echo_probe() -> None:
    """Echo the supervisor's round-trip clock probe (obs/clock.py):
    read ``clock_probe.json`` from the status dir and, for a probe not
    yet answered, append a ``clock_probe`` record whose own ``ts`` is
    this replica's send time — the (probe write, echo send, echo
    observe) triple lets the offset estimator cancel the one-way delay
    bias. Piggybacks on the heartbeat cadence: one stat+read per beat,
    nothing without a supervisor."""
    global _probe_echoed_seq
    d = os.environ.get("TPUJOB_STATUS_DIR")
    if not d:
        return
    from ..obs.clock import read_probe

    probe = read_probe(d)
    if probe is None or probe["seq"] == _probe_echoed_seq:
        return
    _probe_echoed_seq = probe["seq"]
    report("clock_probe", probe_ts=probe["probe_ts"], seq=probe["seq"])


def report_first_step(step: int = 0) -> None:
    report("first_step", step=step)


def report_metrics(step: int, **metrics) -> None:
    report("metrics", step=step, **metrics)


def report_progress(
    step: int,
    *,
    loss: Optional[float] = None,
    steps_per_sec: Optional[float] = None,
    throughput: Optional[float] = None,
    unit: Optional[str] = None,
    step_time_ms: Optional[float] = None,
    feed_stall_ms: Optional[float] = None,
) -> None:
    """Live training heartbeat (step/loss/throughput) for the operator
    surface: the supervisor folds the newest record into per-job
    /metrics gauges and ``tpujob describe``'s "Training" block
    (controller/progress.py). Emit every ~10s, not every step — each
    record is a host write and the caller usually pays a device fence
    to know the loss."""
    # ``drop_heartbeat`` injection site: an armed fault plan can
    # suppress heartbeats to trip the supervisor's hung-world detector
    # (controller/reconciler.py). No-op without a plan.
    from .. import faults

    if faults.heartbeat_dropped():
        return
    fields = {}
    if loss is not None:
        fields["loss"] = round(float(loss), 6)
    if steps_per_sec is not None:
        fields["steps_per_sec"] = round(float(steps_per_sec), 4)
    if throughput is not None:
        fields["throughput"] = round(float(throughput), 4)
    if unit is not None:
        fields["unit"] = unit
    if step_time_ms is not None:
        fields["step_time_ms"] = round(float(step_time_ms), 3)
    if feed_stall_ms is not None:
        fields["feed_stall_ms"] = round(float(feed_stall_ms), 3)
    report("progress", step=step, **fields)
    # Round-trip clock probe: answered on the heartbeat cadence, AFTER
    # the beat (the supervisor probes jobs it just saw beating).
    _maybe_echo_probe()


def report_serve(
    requests: int,
    *,
    slots: int,
    slots_free: int,
    queued: int = 0,
    pending: int = 0,
    ttft_ms_p50: Optional[float] = None,
    ttft_ms_p99: Optional[float] = None,
    tpot_ms_p50: Optional[float] = None,
    tpot_ms_p99: Optional[float] = None,
    block_ms: Optional[float] = None,
) -> None:
    """Serve-plane load beat: slot occupancy, queue depth, and latency
    percentiles for this engine replica. The supervisor's router
    (serving/router.py) reads the newest record per replica from the
    heartbeat fold — zero extra I/O — to score least-loaded dispatch,
    and the queue_growth / batch_size_collapse detectors judge the same
    stream. Emit on the serve loop's report cadence, like progress."""
    fields: dict = {
        "slots": int(slots),
        "slots_free": int(slots_free),
        "queued": int(queued),
        "pending": int(pending),
    }
    for k, v in (
        ("ttft_ms_p50", ttft_ms_p50),
        ("ttft_ms_p99", ttft_ms_p99),
        ("tpot_ms_p50", tpot_ms_p50),
        ("tpot_ms_p99", tpot_ms_p99),
        # Decode-block phase: ms until the engine's current decode
        # block completes and a batch slot can actually be filled —
        # the router's continuous-batching dispatch tie-breaker.
        ("block_ms", block_ms),
    ):
        if v is not None:
            fields[k] = round(float(v), 3)
    report("serve", requests=int(requests), **fields)


def report_checkpoint_committed(
    step: int,
    commit_s: float,
    queue_depth: int = 0,
    oldest_age_s: float = 0.0,
    stage_depth: int = 0,
) -> None:
    """Async-checkpoint commit telemetry for the operator surface: the
    supervisor folds the newest record into the per-job checkpoint-step
    /queue-depth/oldest-inflight-age/stage-depth gauges and observes
    the commit duration into ``tpujob_checkpoint_commit_seconds`` —
    checkpoint lag in ``tpujob top`` is ``job_step -
    job_checkpoint_step``. ``stage_depth`` counts submitted saves whose
    device→host gather has not finished (the staged writer's snapshot
    stage — a growing value means gathers cannot keep up with the save
    cadence)."""
    report(
        "checkpoint_committed",
        step=step,
        commit_ms=round(1000.0 * commit_s, 3),
        queue_depth=int(queue_depth),
        oldest_age_s=round(oldest_age_s, 3),
        stage_depth=int(stage_depth),
    )
