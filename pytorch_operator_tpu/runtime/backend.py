"""Backend/platform selection — the clean seam between the single-process
TPU path and the multi-process CPU (test) path (SURVEY.md §7 "Hard parts").

Why this exists: a plain ``JAX_PLATFORMS`` env var is not reliable in every
deployment (site customizations may pre-import jax and pin a platform — the
axon TPU plugin in this environment does exactly that), so the supervisor
injects ``TPUJOB_PLATFORM`` and workloads call :func:`setup_backend` which
applies the platform via ``jax.config.update`` — the route that always wins
as long as no backend has been instantiated yet.
"""

from __future__ import annotations

import os
from typing import Optional


def setup_backend(platform: Optional[str] = None) -> str:
    """Force the JAX platform and (for CPU) enable cross-process collectives.

    Must be called before any JAX computation/device query. Returns the
    selected platform string ("tpu", "cpu", or "" for default).
    """
    import jax

    platform = platform or os.environ.get("TPUJOB_PLATFORM", "")
    if platform:
        jax.config.update("jax_platforms", platform)
    if (
        platform == "cpu"
        and int(os.environ.get("TPUJOB_NUM_PROCESSES", "1")) > 1
    ):
        # Gloo gives the CPU backend real inter-process collectives — the
        # stand-in for ICI/DCN when testing multi-host topologies locally
        # (SURVEY.md §4: multi-host without a pod). Only for multi-process
        # worlds: gloo needs the distributed client jax.distributed.
        # initialize creates, and building a single-process CPU backend
        # with gloo configured but no client hard-fails at first use
        # (observed on this jaxlib), taking every single-process jax
        # test/workload down with it.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        # The persistent compilation cache is poison for this combination:
        # an XLA:CPU executable with gloo collective thunks deserializes
        # into something that heap-corrupts on execution (observed on this
        # jaxlib: every cache-HIT life of a restarted gang segfaults in
        # the jitted step within seconds, while every cold-compile life is
        # fine). The cache's win is the TPU cold-compile skip; CPU test
        # worlds compile in ~3s, so trade that for not crashing.
        jax.config.update("jax_enable_compilation_cache", False)
    return platform
