"""Cluster-spec environment injection.

Reference: ``SetClusterSpec`` in ``pkg/controller.v1/pytorch/pod.go``
(SURVEY.md §2 "Pod management"): inject MASTER_ADDR/MASTER_PORT/WORLD_SIZE/
RANK/PYTHONUNBUFFERED so c10d's ``env://`` rendezvous works; rank 0 is the
Master, worker i gets rank i+1.

TPU-native replacement (BASELINE.json:5): the same topology is expressed for
PJRT/jax.distributed — ``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES`` plus a
coordinator address for ``jax.distributed.initialize``. The legacy
MASTER_ADDR set is injected too, for parity and for torch-based workloads.

The init-container DNS gate of the reference (workers loop ``nslookup
$MASTER_ADDR``) is replaced by jax.distributed's built-in
connect-with-timeout retry (see runtime/rendezvous.py).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..api.types import ReplicaType, TPUJob


def replica_rank(rtype: ReplicaType, index: int) -> int:
    """Master → 0; Worker i → i+1 (reference rank assignment)."""
    return 0 if rtype == ReplicaType.MASTER else index + 1


def build_cluster_env(
    job: TPUJob,
    rtype: ReplicaType,
    index: int,
    *,
    num_processes: Optional[int] = None,
    coordinator_host: str = "127.0.0.1",
    status_dir: Optional[str] = None,
    checkpoint_dir: Optional[str] = None,
    compile_cache_dir: Optional[str] = None,
    trace_dir: Optional[str] = None,
    spool_dir: Optional[str] = None,
    rank: Optional[int] = None,
    coordinator_port: Optional[int] = None,
    resize_generation: Optional[int] = None,
) -> Dict[str, str]:
    """Build the injected environment for one replica process.

    ``num_processes`` overrides the spec's total (elastic re-rendezvous with
    a different world size); defaults to spec.total_replicas().
    ``rank``/``coordinator_port`` override the index-derived rank and the
    spec's port — a replica joining a RESIZED world (controller/elastic.py)
    takes its rank from the resize record's compacted map (survivor
    indices stay sparse, ranks must be dense) and the generation's own
    coordinator port. ``resize_generation`` stamps the world epoch this
    replica belongs to; the rendezvous layer fences it against newer
    resize records.
    """
    total = num_processes if num_processes is not None else job.spec.total_replicas()
    rank = replica_rank(rtype, index) if rank is None else rank
    port = coordinator_port if coordinator_port is not None else (job.spec.port or 23456)
    coordinator = f"{coordinator_host}:{port}"
    key = f"{job.metadata.namespace}/{job.metadata.name}"

    env: Dict[str, str] = {
        # ---- reference-parity set (c10d env:// rendezvous) ----
        "MASTER_ADDR": coordinator_host,
        "MASTER_PORT": str(port),
        "WORLD_SIZE": str(total),
        "RANK": str(rank),
        "PYTHONUNBUFFERED": "1",
        # ---- TPU-native set (PJRT / jax.distributed) ----
        "TPU_WORKER_ID": str(rank),
        "TPU_WORKER_HOSTNAMES": ",".join([coordinator_host] * total),
        "TPUJOB_COORDINATOR_ADDRESS": coordinator,
        "TPUJOB_NUM_PROCESSES": str(total),
        "TPUJOB_PROCESS_ID": str(rank),
        # ---- job identity / bookkeeping ----
        "TPUJOB_NAME": job.metadata.name,
        "TPUJOB_NAMESPACE": job.metadata.namespace,
        "TPUJOB_KEY": key,
        "TPUJOB_REPLICA_TYPE": rtype.value,
        "TPUJOB_REPLICA_INDEX": str(index),
        "TPUJOB_RESTART_COUNT": str(job.status.restart_count),
        "TPUJOB_RESIZE_GENERATION": str(
            job.status.resize_generation
            if resize_generation is None
            else resize_generation
        ),
    }

    resources = job.spec.replica_specs[rtype].template.resources
    if resources.cpu_devices > 0:
        # Test/CI backend: virtual CPU devices (SURVEY.md §4). TPUJOB_PLATFORM
        # is applied by workloads via runtime.backend.setup_backend — a plain
        # JAX_PLATFORMS env var can be overridden by site customizations that
        # pre-import jax (the axon plugin here does).
        env["TPUJOB_PLATFORM"] = "cpu"
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={resources.cpu_devices}"
        )
    elif resources.tpu_chips > 0:
        env["PJRT_DEVICE"] = "TPU"

    if status_dir is not None:
        env["TPUJOB_STATUS_DIR"] = status_dir
    if checkpoint_dir is not None:
        env["TPUJOB_CHECKPOINT_DIR"] = checkpoint_dir
    # Flight-recorder knob (obs/trace.py): with a per-job trace dir the
    # replica's step loop / device feed / rendezvous / async-checkpoint
    # spans land where `tpujob trace <job>` merges them. Explicitly
    # cleared otherwise — a supervisor tracing ITSELF must not leak its
    # own (root) trace dir into replicas via inherited environment.
    if trace_dir is not None:
        env["TPUJOB_TRACE_DIR"] = trace_dir
        # Ring sizing / flush cadence are spec knobs, not fixed
        # constants (obs/trace.py reads these once at tracer creation).
        ob = job.spec.observability
        if ob is not None:
            if ob.trace_ring_bytes > 0:
                env["TPUJOB_TRACE_RING_BYTES"] = str(ob.trace_ring_bytes)
            if ob.trace_flush_every > 0:
                env["TPUJOB_TRACE_FLUSH_EVERY"] = str(ob.trace_flush_every)
    else:
        env["TPUJOB_TRACE_DIR"] = ""
    # Live health-engine policy (spec.observability.alerts): evaluated
    # by the SUPERVISOR, but threaded into replicas like the trace
    # knobs so replica-side tooling (an in-container `tpujob why`, a
    # sidecar evaluating the same rules) resolves the identical bar.
    ob = job.spec.observability
    if ob is not None and ob.alerts is not None:
        import json as _json

        env["TPUJOB_ALERTS"] = _json.dumps(
            ob.alerts.to_dict(), sort_keys=True
        )
    # Serve plane (spec.serving): each serving replica gets its OWN
    # spool directory — the router's dispatch target for this replica —
    # so `workloads/serve.py --spool` needs no per-replica args
    # plumbing. The SLO block rides along as JSON for replica-side
    # tooling parity, like TPUJOB_ALERTS.
    if spool_dir is not None:
        env["TPUJOB_SPOOL_DIR"] = spool_dir
    sv = job.spec.serving
    if sv is not None:
        import json as _json

        env["TPUJOB_SERVING"] = _json.dumps(sv.to_dict(), sort_keys=True)
        # The transport tier rides its own var so the engine loop can
        # gate ring-attach on one string compare, no JSON parse.
        env["TPUJOB_SERVE_TRANSPORT"] = sv.transport
    # Auto-remediation policy (spec.remediation): acted on by the
    # SUPERVISOR, threaded into replicas like TPUJOB_ALERTS so
    # replica-side tooling resolves the identical policy.
    rm = job.spec.remediation
    if rm is not None:
        import json as _json

        env["TPUJOB_REMEDIATION"] = _json.dumps(
            rm.to_dict(), sort_keys=True
        )
    # A committed raise_ckpt_cadence remediation stamps this annotation;
    # workloads multiply their checkpoint frequency by it so the "write
    # more often" decision survives restarts (it rides the spec, not a
    # live signal).
    from ..controller.remediation import CKPT_CADENCE_ANNOTATION

    cadence = job.metadata.annotations.get(CKPT_CADENCE_ANNOTATION)
    if cadence:
        env["TPUJOB_CKPT_CADENCE_FACTOR"] = str(cadence)
    # Data-plane policy (spec.data_plane): workloads read these as the
    # defaults for --async-checkpoint / --prefetch, so host-I/O overlap
    # is a SPEC property, not per-workload args plumbing.
    dp = job.spec.data_plane
    if dp is not None:
        if dp.async_checkpoint:
            env["TPUJOB_ASYNC_CHECKPOINT"] = "1"
        if dp.prefetch > 0:
            env["TPUJOB_PREFETCH"] = str(dp.prefetch)
        if dp.prefetch_depth_max > 0:
            env["TPUJOB_PREFETCH_DEPTH_MAX"] = str(dp.prefetch_depth_max)
        if dp.autotune:
            env["TPUJOB_FEED_AUTOTUNE"] = "1"
        if dp.prefetch_workers > 0:
            env["TPUJOB_PREFETCH_WORKERS"] = str(dp.prefetch_workers)
    # Persistent XLA compilation cache, shared across the state dir: a
    # resubmitted/restarted job skips its ~30s cold compile, which is most
    # of schedule-to-first-step on TPU (BASELINE.md). Template env wins —
    # injected env overrides template env at spawn, so only set it when
    # the user didn't.
    template_env = job.spec.replica_specs[rtype].template.env
    if (
        compile_cache_dir is not None
        and "JAX_COMPILATION_CACHE_DIR" not in template_env
    ):
        env["JAX_COMPILATION_CACHE_DIR"] = compile_cache_dir
    if (
        # A cache is in effect — injected above OR user-supplied...
        compile_cache_dir is not None
        or "JAX_COMPILATION_CACHE_DIR" in template_env
    ) and "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in template_env:
        # ...so persist EVERY compiled program, not just those whose
        # pure-XLA compile time clears jax's default 1s threshold: on a
        # tunneled backend the remote-compile round trip costs ~1.5-2s
        # regardless of program size (measured round 4: a 256x256
        # matmul's "compile" is 1.94s remote vs 0.33s cache fetch), and
        # that round trip is NOT counted as compile time by the
        # threshold — the programs that benefit most from the cache are
        # exactly the ones it would skip. A template that pins its own
        # threshold wins, like the cache dir itself.
        env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"

    return env
