"""``tpujob top`` — one-screen live fleet table.

Answers the operator's glance questions without a dashboard: per job,
where is it (step), how fast (steps/s), how SMOOTH (p50/p99 step time —
the tail counters can't see), how far behind are its checkpoints
(lag = newest step - newest committed step), and is the device feed
keeping ahead (feed stall).

Sources, all file-based so it works with or without a daemon:

- the persisted job store (which jobs exist, their phase);
- each job's status dir heartbeats (step, steps/s, feed stall) and
  ``checkpoint_committed`` records (checkpoint lag) — read one-shot via
  controller/progress.py;
- the daemon's ``metrics.prom`` (written every pass) for the step-time
  histogram quantiles; absent (no daemon), the p50/p99 columns show
  ``-`` and the heartbeat-derived columns still render.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Dict, List, Optional

from .metrics import histogram_quantile, parse_prometheus_text

STEP_HIST = "tpujob_step_time_seconds"


def _hist_quantiles(
    metrics: Dict, name: str, job: str
) -> Optional[tuple]:
    """(p50_s, p99_s) for one job's series of histogram ``name`` parsed
    from exposition text, or None."""
    rows = metrics.get(f"{name}_bucket")
    if not rows:
        return None
    cum = sorted(
        (
            (float("inf") if le == "+Inf" else float(le), int(v))
            for labels, v in rows
            if labels.get("job") == job
            for le in [labels.get("le", "+Inf")]
        ),
        key=lambda x: x[0],
    )
    if not cum or cum[-1][1] == 0:
        return None
    p50 = histogram_quantile(cum, 0.50)
    p99 = histogram_quantile(cum, 0.99)
    if p50 is None:
        return None
    return p50, p99


def gather_rows(state_dir, now: Optional[float] = None) -> List[dict]:
    """One snapshot of the fleet: a dict per unfinished job (finished
    jobs are noise on a live screen), newest-first by heartbeat."""
    from ..controller.progress import job_status_dir, read_latest_event
    from ..controller.store import JobStore, job_key

    state = Path(state_dir)
    now = time.time() if now is None else now
    metrics: Dict = {}
    prom = state / "metrics.prom"
    if prom.exists():
        try:
            metrics = parse_prometheus_text(prom.read_text())
        except OSError:
            pass
    store = JobStore(persist_dir=state / "jobs")
    rows: List[dict] = []
    for job in store.list():
        if job.is_finished():
            continue
        key = job_key(job)
        d = job_status_dir(state / "status", key)
        hb = read_latest_event(d, "progress") or {}
        ck = read_latest_event(d, "checkpoint_committed") or {}
        q = _hist_quantiles(metrics, STEP_HIST, key)
        step = hb.get("step")
        ck_step = ck.get("step")
        rows.append(
            {
                "job": key,
                "step": step,
                "steps_per_sec": hb.get("steps_per_sec"),
                "p50_ms": 1000 * q[0] if q else None,
                "p99_ms": 1000 * q[1] if q else None,
                "ckpt_lag": (
                    int(step - ck_step)
                    if step is not None and ck_step is not None
                    else None
                ),
                "feed_stall_ms": hb.get("feed_stall_ms"),
                "age_s": (now - hb["ts"]) if hb.get("ts") else None,
                "restarts": job.status.restart_count,
            }
        )
    # Stable, predictable ordering for a refreshing screen: reporting
    # jobs first (freshest heartbeat up top), silent jobs after, each
    # group alphabetical.
    rows.sort(
        key=lambda r: (r["age_s"] is None, r["age_s"] or 0.0, r["job"])
    )
    return rows


def _fmt(v, spec: str = "", dash: str = "-") -> str:
    if v is None:
        return dash
    return format(v, spec) if spec else str(v)


def render_table(rows: List[dict], now: Optional[float] = None) -> str:
    """The one-screen table. Columns stay stable so watch-mode diffs
    visually; '-' means "not reported", never 0."""
    header = (
        "JOB", "STEP", "STEPS/S", "P50(ms)", "P99(ms)",
        "CKPT LAG", "FEED(ms)", "HB AGE", "RESTARTS",
    )
    table = [header]
    for r in rows:
        table.append(
            (
                r["job"],
                _fmt(None if r["step"] is None else int(r["step"])),
                _fmt(r["steps_per_sec"], ".2f"),
                _fmt(r["p50_ms"], ".1f"),
                _fmt(r["p99_ms"], ".1f"),
                _fmt(r["ckpt_lag"]),
                _fmt(r["feed_stall_ms"], ".2f"),
                _fmt(None if r["age_s"] is None else f"{r['age_s']:.0f}s"),
                str(r["restarts"]),
            )
        )
    widths = [max(len(row[i]) for row in table) for i in range(len(header))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
             for row in table]
    if not rows:
        lines.append("(no active jobs)")
    return "\n".join(lines)


def render(state_dir, now: Optional[float] = None) -> str:
    return render_table(gather_rows(state_dir, now), now)
