"""``tpujob top`` — one-screen live fleet table.

Answers the operator's glance questions without a dashboard: per job,
where is it (step), how fast (steps/s), how SMOOTH (p50/p99 step time —
the tail counters can't see), how far behind are its checkpoints
(lag = newest step - newest committed step), and is the device feed
keeping ahead (feed stall).

Sources, all file-based so it works with or without a daemon:

- the persisted job store (which jobs exist, their phase);
- each job's status dir heartbeats (step, steps/s, feed stall) and
  ``checkpoint_committed`` records (checkpoint lag) — read one-shot via
  controller/progress.py;
- the daemon's ``metrics.prom`` (written every pass) for the step-time
  histogram quantiles; absent (no daemon), the p50/p99 columns show
  ``-`` and the heartbeat-derived columns still render.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Dict, List, Optional

from .metrics import histogram_quantile, parse_exemplars, parse_prometheus_text
from ..controller.remediation import load_remediation_log
from .watch import fold_alert_log, load_alert_log

STEP_HIST = "tpujob_step_time_seconds"
SERVE_TTFT_HIST = "tpujob_serve_ttft_seconds"
SERVE_QUEUE_GAUGE = "tpujob_job_serve_queue_depth"
SLO_BURN_GAUGE = "tpujob_slo_burn_rate"

# The table's columns: (header, row key) in display order — one list so
# the renderer, the sort-key cycling (`tpujob top` 's' key), and tests
# cannot drift. Row keys index the dicts gather_rows returns.
COLUMNS = (
    ("JOB", "job"),
    ("SHARD", "shard"),
    ("WORLD", "world"),
    ("STEP", "step"),
    ("STEPS/S", "steps_per_sec"),
    ("P50(ms)", "p50_ms"),
    ("P99(ms)", "p99_ms"),
    ("CKPT LAG", "ckpt_lag"),
    ("FEED(ms)", "feed_stall_ms"),
    ("SRV Q", "serve_q"),
    ("TTFT99", "ttft_p99_ms"),
    ("BURN", "burn"),
    ("HB AGE", "age_s"),
    ("ALERTS", "alerts"),
    ("REMED", "remed"),
    ("RESTARTS", "restarts"),
    ("P99 SPAN", "p99_span"),
)

# ANSI for the firing-row highlight (only applied when the renderer is
# asked to color — a TTY repaint loop; piped output and the /top HTTP
# route stay plain text).
_RED = "\x1b[31m"
_RESET = "\x1b[0m"


def _hist_quantiles(
    metrics: Dict, name: str, job: str
) -> Optional[tuple]:
    """(p50_s, p99_s) for one job's series of histogram ``name`` parsed
    from exposition text, or None."""
    rows = metrics.get(f"{name}_bucket")
    if not rows:
        return None
    cum = sorted(
        (
            (float("inf") if le == "+Inf" else float(le), int(v))
            for labels, v in rows
            if labels.get("job") == job
            for le in [labels.get("le", "+Inf")]
        ),
        key=lambda x: x[0],
    )
    if not cum or cum[-1][1] == 0:
        return None
    p50 = histogram_quantile(cum, 0.50)
    p99 = histogram_quantile(cum, 0.99)
    if p50 is None:
        return None
    return p50, p99


def _gauge(metrics: Dict, name: str, job: str) -> Optional[float]:
    """One job's gauge value from the merged exposition text, or None
    (no daemon, or the job has no such series)."""
    for labels, v in metrics.get(name, ()):
        if labels.get("job") == job:
            try:
                return float(v)
            except (TypeError, ValueError):
                return None
    return None


def gather_rows(state_dir, now: Optional[float] = None) -> List[dict]:
    """One snapshot of the fleet: a dict per unfinished job (finished
    jobs are noise on a live screen), newest-first by heartbeat."""
    from ..api.defaults import ELASTIC_TARGET_ANNOTATION
    from ..controller.progress import job_status_dir, read_latest_event
    from ..controller.store import JobStore, job_key

    state = Path(state_dir)
    now = time.time() if now is None else now
    metrics: Dict = {}
    exemplars: Dict = {}
    # Union across daemons: one metrics.prom (unsharded) or one
    # metrics-<identity>.prom per sharded supervisor — each job's
    # series exist only in its owner's file, so merging is a union.
    for prom in sorted(state.glob("metrics*.prom")):
        try:
            text = prom.read_text()
        except OSError:
            continue
        for name, rows_ in parse_prometheus_text(text).items():
            metrics.setdefault(name, []).extend(rows_)
        for name, rows_ in parse_exemplars(text).items():
            exemplars.setdefault(name, []).extend(rows_)
    # Sharded control plane: which shard each job hashes to and who
    # holds its lease right now (the SHARD column; None when unsharded).
    from ..controller.leases import (
        read_shard_config,
        read_shard_owners,
        shard_of_key,
    )

    num_shards = read_shard_config(state)
    shard_owners = read_shard_owners(state) if num_shards else {}
    store = JobStore(persist_dir=state / "jobs")
    rows: List[dict] = []
    for job in store.list():
        if job.is_finished():
            continue
        key = job_key(job)
        shard = (
            shard_of_key(
                key, num_shards, job.spec.run_policy.scheduling_policy.shard
            )
            if num_shards
            else None
        )
        d = job_status_dir(state / "status", key)
        hb = read_latest_event(d, "progress") or {}
        ck = read_latest_event(d, "checkpoint_committed") or {}
        q = _hist_quantiles(metrics, STEP_HIST, key)
        # Serve plane: front-queue depth from the router's gauge (the
        # daemon writes it every pass), falling back to the newest
        # ``serve`` status record so a daemon-less snapshot still
        # answers; client-perceived TTFT p99 from the serve histogram
        # with the engines' self-reported percentile as fallback.
        # Elastic world state: current world size (the committed spec)
        # vs the grow-back target pinned in the elastic-target
        # annotation — `3→4` means shrunken, waiting on capacity.
        world = world_target = None
        if job.spec.elastic_policy is not None:
            world = job.spec.total_replicas()
            world_target = world
            tgt = job.metadata.annotations.get(ELASTIC_TARGET_ANNOTATION)
            if tgt:
                workers = sum(
                    rs.replicas or 0
                    for rt, rs in job.spec.replica_specs.items()
                    if rt.value.lower() == "worker"
                )
                try:
                    world_target = world - workers + int(tgt)
                except ValueError:
                    pass
        sv = read_latest_event(d, "serve") or {}
        serve_q = _gauge(metrics, SERVE_QUEUE_GAUGE, key)
        if serve_q is None:
            serve_q = sv.get("queue_depth")
        tq = _hist_quantiles(metrics, SERVE_TTFT_HIST, key)
        ttft_p99 = 1000 * tq[1] if tq else sv.get("ttft_ms_p99")
        # Error-budget burn: the router's fast-window burn gauge
        # (window label != the slow "5m" one), falling back to the
        # newest ``serve`` status record for daemon-less snapshots.
        burn = _burn_gauge(metrics, key)
        if burn is None:
            burn = sv.get("burn")
        step = hb.get("step")
        ck_step = ck.get("step")
        # Live health engine state (obs/watch.py alert log): the rules
        # currently FIRING for this job, folded from the on-disk
        # transition log so `tpujob top` answers with or without a
        # daemon (same contract as the heartbeat columns).
        firing = [
            r["rule"]
            for r in fold_alert_log(load_alert_log(state, key))
            if r.get("state") == "firing"
        ]
        # Auto-remediation (controller/remediation.py audit log): the
        # committed generation and the newest action, folded from disk
        # like the alert column — the REMED cell and the --diff action
        # lines both read this.
        remed_recs = (
            load_remediation_log(state, key)
            if job.spec.remediation is not None
            else []
        )
        last_remed = remed_recs[-1] if remed_recs else None
        rows.append(
            {
                "job": key,
                "shard": shard,
                "world": world,
                "world_target": world_target,
                "shard_owner": (
                    shard_owners.get(shard) if shard is not None else None
                ),
                "step": step,
                "steps_per_sec": hb.get("steps_per_sec"),
                "p50_ms": 1000 * q[0] if q else None,
                "p99_ms": 1000 * q[1] if q else None,
                "ckpt_lag": (
                    int(step - ck_step)
                    if step is not None and ck_step is not None
                    else None
                ),
                "feed_stall_ms": hb.get("feed_stall_ms"),
                "serve_q": serve_q,
                "ttft_p99_ms": ttft_p99,
                "burn": burn,
                "spills": sv.get("spills"),
                "age_s": (now - hb["ts"]) if hb.get("ts") else None,
                "alerts": len(firing) or None,
                "alert_rules": sorted(firing),
                "remed": (
                    None
                    if job.spec.remediation is None
                    else job.status.remediation_generation
                ),
                "remed_last": (
                    f"{last_remed.get('action', '?')}"
                    f"[{last_remed.get('outcome', '?')}]"
                    if last_remed
                    else None
                ),
                "remed_count": len(remed_recs) or None,
                "restarts": job.status.restart_count,
                # Exemplar linking: the latest span that landed in the
                # job's slowest populated step-time bucket — the jump
                # from a p99 cell to the exact trace span.
                "p99_span": _tail_exemplar(exemplars, STEP_HIST, key),
            }
        )
    # Stable, predictable ordering for a refreshing screen: reporting
    # jobs first (freshest heartbeat up top), silent jobs after, each
    # group alphabetical.
    rows.sort(
        key=lambda r: (r["age_s"] is None, r["age_s"] or 0.0, r["job"])
    )
    return rows


def _burn_gauge(metrics: Dict, job: str) -> Optional[float]:
    """The job's fast-window burn rate from the multi-window
    ``tpujob_slo_burn_rate{job,window}`` gauge: prefer the fast window
    (whatever width the spec chose — anything but the fixed slow
    \"5m\"), fall back to any window present."""
    fast = slow = None
    for labels, v in metrics.get(SLO_BURN_GAUGE, ()):
        if labels.get("job") != job:
            continue
        try:
            val = float(v)
        except (TypeError, ValueError):
            continue
        if labels.get("window") == "5m":
            slow = val
        else:
            fast = val
    return fast if fast is not None else slow


def _tail_exemplar(exemplars: Dict, name: str, job: str) -> Optional[str]:
    """The span id recorded in the job's highest exemplared bucket of
    histogram ``name`` (the worst step the recorder can still point
    at), or None."""
    rows = exemplars.get(f"{name}_bucket")
    if not rows:
        return None
    best = None
    for labels, span_id, value in rows:
        if labels.get("job") != job:
            continue
        if best is None or value > best[0]:
            best = (value, span_id)
    return best[1] if best else None


def sort_rows(rows: List[dict], sort_key: Optional[str], reverse: bool = True) -> List[dict]:
    """Order rows by one COLUMNS key, unreported (None) values always
    last regardless of direction; default ordering (sort_key None)
    keeps gather_rows' freshest-heartbeat-first contract."""
    if sort_key is None:
        return rows
    if sort_key == "job":
        return sorted(rows, key=lambda r: r["job"], reverse=reverse)

    def k(r):
        v = r.get(sort_key)
        return (v is None, (-v if reverse else v) if v is not None else 0.0)

    return sorted(rows, key=k)


def filter_rows(rows: List[dict], needle: Optional[str]) -> List[dict]:
    """Case-insensitive job-name substring filter ('/' key)."""
    if not needle:
        return rows
    n = needle.lower()
    return [r for r in rows if n in r["job"].lower()]


def _fmt(v, spec: str = "", dash: str = "-") -> str:
    if v is None:
        return dash
    return format(v, spec) if spec else str(v)


def _shard_cell(r: dict) -> str:
    """``<shard>@<owner>`` (owner truncated), ``<shard>@?`` for an
    orphaned shard mid-failover, ``-`` when the control plane is
    unsharded."""
    if r.get("shard") is None:
        return "-"
    owner = r.get("shard_owner")
    return f"{r['shard']}@{owner[:12] if owner else '?'}"


def _world_cell(r: dict) -> str:
    """``4`` at target, ``3→4`` while shrunken below the grow-back
    target, ``-`` for non-elastic jobs."""
    w = r.get("world")
    if w is None:
        return "-"
    t = r.get("world_target")
    return str(w) if t is None or t == w else f"{w}→{t}"


def _remed_cell(r: dict) -> str:
    """``<generation>:<last action>[<outcome>]`` for a remediation-armed
    job (``0`` = armed, never acted), ``-`` unarmed."""
    g = r.get("remed")
    if g is None:
        return "-"
    last = r.get("remed_last")
    return f"{g}:{last}" if last else str(g)


def _cells(r: dict) -> tuple:
    return (
        r["job"],
        _shard_cell(r),
        _world_cell(r),
        _fmt(None if r["step"] is None else int(r["step"])),
        _fmt(r["steps_per_sec"], ".2f"),
        _fmt(r["p50_ms"], ".1f"),
        _fmt(r["p99_ms"], ".1f"),
        _fmt(r["ckpt_lag"]),
        _fmt(r["feed_stall_ms"], ".2f"),
        _fmt(None if r.get("serve_q") is None else int(r["serve_q"])),
        _fmt(r.get("ttft_p99_ms"), ".1f"),
        _fmt(r.get("burn"), ".2f"),
        _fmt(None if r["age_s"] is None else f"{r['age_s']:.0f}s"),
        (
            f"{r['alerts']}:{','.join(r.get('alert_rules', []))}"
            if r.get("alerts")
            else "-"
        ),
        _remed_cell(r),
        str(r["restarts"]),
        _fmt(r.get("p99_span")),
    )


def render_table(
    rows: List[dict],
    now: Optional[float] = None,
    sort_key: Optional[str] = None,
    filter_str: Optional[str] = None,
    color: bool = False,
) -> str:
    """The one-screen table. Columns stay stable so watch-mode diffs
    visually; '-' means "not reported", never 0. ``sort_key`` marks the
    sorted column with '▾' (the interactive loop passes it; one-shot
    callers don't). ``color=True`` (TTY repaint loop) paints rows with
    firing alerts red — the width math runs BEFORE the escape codes so
    alignment survives."""
    header = tuple(
        h + " ▾" if key == sort_key else h for h, key in COLUMNS
    )
    table = [header]
    for r in rows:
        table.append(_cells(r))
    widths = [max(len(row[i]) for row in table) for i in range(len(header))]
    lines = []
    for i, row in enumerate(table):
        line = "  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
        if color and i > 0 and rows[i - 1].get("alerts"):
            line = f"{_RED}{line}{_RESET}"
        lines.append(line)
    if not rows:
        lines.append(
            f"(no jobs matching {filter_str!r})" if filter_str
            else "(no active jobs)"
        )
    if filter_str:
        lines.append(f"filter: {filter_str}")
    return "\n".join(lines)


def diff_rows(prev: List[dict], rows: List[dict]) -> List[str]:
    """``tpujob top --diff``: what CHANGED since the previous repaint,
    as human lines — new/gone jobs, step-rate moves, checkpoint-lag
    growth, heartbeat-age jumps, and alert transitions — instead of a
    full-table repaint. Pure (no I/O, no clock) so the delta semantics
    are unit-testable."""
    by_job_prev = {r["job"]: r for r in prev}
    by_job_cur = {r["job"]: r for r in rows}
    lines: List[str] = []
    for job in sorted(set(by_job_prev) | set(by_job_cur)):
        p, c = by_job_prev.get(job), by_job_cur.get(job)
        if p is None:
            lines.append(f"{job}: appeared (step {_fmt(c.get('step'))})")
            continue
        if c is None:
            lines.append(f"{job}: gone (finished or deleted)")
            continue
        changes: List[str] = []
        ps, cs = p.get("steps_per_sec"), c.get("steps_per_sec")
        if ps is not None and cs is not None and abs(cs - ps) > 0.05 * max(ps, 1e-9):
            arrow = "▼" if cs < ps else "▲"
            changes.append(f"steps/s {ps:.2f}→{cs:.2f} {arrow}")
        for key, label in (("ckpt_lag", "ckpt lag"), ("restarts", "restarts")):
            if p.get(key) != c.get(key) and c.get(key) is not None:
                changes.append(f"{label} {_fmt(p.get(key))}→{_fmt(c.get(key))}")
        # Elastic resize transitions: the committed world size moved
        # (shrink-in-place, spare promotion, or grow-back).
        pw, cw = p.get("world"), c.get("world")
        if pw is not None and cw is not None and pw != cw:
            direction = "shrunk" if cw < pw else "grew"
            changes.append(f"world {pw}→{cw} ({direction})")
        # Serve plane: ring spills are the lane falling back to the
        # file spool (backpressure) — any growth is worth a line; a
        # burn rate crossing 1.0 means the error budget started
        # draining faster than it accrues.
        psp, csp = p.get("spills"), c.get("spills")
        if csp is not None and psp is not None and csp > psp:
            changes.append(f"spills {_fmt(psp)}→{_fmt(csp)} (ring backpressure)")
        pb, cb = p.get("burn"), c.get("burn")
        if cb is not None and (pb or 0.0) < 1.0 <= cb:
            changes.append(f"SLO burn {pb if pb is not None else 0:.2f}→{cb:.2f} (budget draining)")
        elif pb is not None and cb is not None and pb >= 1.0 > cb:
            changes.append(f"SLO burn {pb:.2f}→{cb:.2f} (recovered)")
        pa, ca = p.get("age_s"), c.get("age_s")
        if pa is not None and ca is not None and ca > max(3 * pa, pa + 2.0):
            changes.append(f"hb age {pa:.0f}s→{ca:.0f}s (going silent?)")
        if (
            c.get("shard") is not None
            and p.get("shard_owner") != c.get("shard_owner")
        ):
            changes.append(
                f"shard {c['shard']} owner "
                f"{p.get('shard_owner') or '?'}→{c.get('shard_owner') or '?'}"
            )
        prev_alerts = set(p.get("alert_rules") or ())
        cur_alerts = set(c.get("alert_rules") or ())
        for rule in sorted(cur_alerts - prev_alerts):
            changes.append(f"ALERT firing: {rule}")
        for rule in sorted(prev_alerts - cur_alerts):
            changes.append(f"alert resolved: {rule}")
        # Remediation actions: a committed-generation move is an action
        # the fleet actually took; a record-count move without one is a
        # dry-run decision the operator should read before un-gating.
        pg, cg = p.get("remed"), c.get("remed")
        if cg is not None and pg is not None and cg > pg:
            changes.append(
                f"REMEDIATION {c.get('remed_last') or 'acted'} "
                f"(generation {pg}→{cg})"
            )
        elif (
            (c.get("remed_count") or 0) > (p.get("remed_count") or 0)
            and c.get("remed_last")
        ):
            changes.append(f"remediation dry-run: {c['remed_last']}")
        if changes:
            lines.append(f"{job}: " + "; ".join(changes))
    return lines


def render(
    state_dir,
    now: Optional[float] = None,
    sort_key: Optional[str] = None,
    reverse: bool = True,
    filter_str: Optional[str] = None,
    color: bool = False,
) -> str:
    rows = filter_rows(gather_rows(state_dir, now), filter_str)
    rows = sort_rows(rows, sort_key, reverse)
    return render_table(
        rows, now, sort_key=sort_key, filter_str=filter_str, color=color
    )
