"""Cross-host clock alignment from heartbeat observations.

The flight recorder timestamps spans with each process's own wall
clock. On one host those clocks agree; across hosts they can be skewed
by milliseconds to seconds (NTP droop, VM migration), which inverts
causality in a merged trace — a worker's ``rendezvous_join`` can appear
to START before the coordinator that admitted it was even launched.
``merge_trace_files(clock_offsets=...)`` has carried the correction
hook since the recorder shipped; this module computes the corrections.

The insight is that a clock reference already flows through the system
for free: every progress heartbeat carries the REPLICA's send timestamp
(``ts``, runtime/rendezvous.py:report), and the supervisor — whose
clock is the reference frame for events, kills, and its own spans —
observes each new beat at a known local time during the sync-pass fold.
Each (send_ts, observe_ts) pair bounds the replica's offset from one
side: ``observe = send + offset + delay`` with ``delay >= 0`` (status
write + poll latency, at most ~one poll interval), so

    observe - send = offset + delay,   delay ∈ [0, poll+jitter].

Estimator (:func:`estimate_offset`): drift first, via a Theil–Sen
median of pairwise slopes of ``observe - send`` against ``send`` —
robust to dropped heartbeats (gaps just widen the pair baseline) and to
delay jitter (the median ignores outlier pairs). Then the drift-
detrended residuals ``(observe - send) - drift·(send - t₀)`` are an
offset-plus-delay sample set; the offset is their ROBUST MIDPOINT —
the midpoint of the (q10, q50) residual band, which splits the
difference between "minimum residual" (right when the fastest poll had
zero delay, fragile to a single early outlier) and "median residual"
(biased upward by half the typical poll delay). The residual spread is
reported so consumers can judge the estimate; the e2e acceptance bound
is a residual under one heartbeat interval.

Write side: the supervisor appends one JSONL observation per NEW
per-replica heartbeat to ``<state>/clock/<ns>_<job>.jsonl``
(:class:`ClockLog`, size-capped like the span rings). Read side:
:func:`estimate_job_offsets` folds a log into per-replica estimates;
:func:`offsets_for_trace_files` maps them onto span-file paths (the
file name leads with the process name, ``<replica>-<pid>.trace.jsonl``)
for the merge hook. Everything here runs OFFLINE from recorded
artifacts — the step path gains zero calls.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Subdirectory of the supervisor state dir holding per-job observation
# logs (a sibling of jobs/, status/, events/, trace/).
CLOCK_DIR = "clock"

# Per-job observation-log cap: past it the file rotates once (.1 kept),
# mirroring the span rings — a month-long job cannot fill the disk with
# 40-byte clock pairs. ~1 MiB holds ~10k observations, far more than
# the estimator needs.
LOG_MAX_BYTES = 1 << 20

# Estimator floor: below this many pairs drift is forced to 0 (two
# noisy points define a garbage slope) and the offset falls back to the
# plain robust midpoint of the residuals.
MIN_PAIRS_FOR_DRIFT = 4

# Credibility clamp on the fitted drift: real quartz drifts tens of
# ppm, NTP-disciplined clocks far less. A short observation window
# turns delay jitter into a huge apparent slope (observed: 28000 "ppm"
# from a 0.5s window) — extrapolating that beyond the window would
# corrupt corrections, so implausible slopes collapse to pure offset.
MAX_CREDIBLE_DRIFT_PPM = 500.0

# ---- round-trip probes ----
#
# The one-way estimator above is biased by the status-write + poll
# delay: every (send, observe) pair satisfies observe - send = offset
# + delay with delay >= 0, so the recovered offset sits up to ~one
# poll interval above truth. A ROUND TRIP bounds the offset from both
# sides: the supervisor writes a probe file at its time T0, the
# replica reads it and echoes a ``clock_probe`` status record stamped
# with its own clock r, and the supervisor observes the echo at T1.
# The echo's true (supervisor-clock) send instant lies in [T0, T1], so
#     offset = true_send - r  ∈  [T0 - r, T1 - r],
# and the interval midpoint (T0 + T1)/2 - r is unbiased when the
# write→read and write→observe legs are comparably delayed — no
# systematic one-way bias left. estimate_offset prefers round-trip
# triples whenever the log holds enough of them.

# Probe file name inside a job's status dir (NOT *.jsonl — the tailer
# must never scan it as a replica record file).
PROBE_FILE = "clock_probe.json"

# Supervisor-side rewrite cadence; gated on the job having produced a
# NEW heartbeat that pass, so idle jobs are never probed (the
# zero-idle-I/O invariant of the sync pass holds).
PROBE_INTERVAL_S = 2.0

# Round-trip triples needed before the estimator trusts them over the
# (more numerous) one-way pairs.
MIN_ROUNDTRIP = 3


def write_probe(status_dir, now: float) -> Optional[int]:
    """Best-effort probe-file rewrite (supervisor side); returns the
    probe's ``seq`` (the writer remembers it and accepts only echoes of
    seqs it wrote — a stale echo observed by a restarted daemon would
    otherwise contribute a garbage round trip). tmp+replace so a torn
    probe is never readable — replicas would echo its garbage ts back
    into skew accounting before JSON parse failure could save them."""
    if status_dir is None:
        return None
    p = Path(status_dir) / PROBE_FILE
    seq = int(now * 1e6)
    try:
        tmp = p.with_suffix(p.suffix + ".tmp")
        tmp.write_text(json.dumps({"probe_ts": round(now, 6), "seq": seq}))
        tmp.replace(p)
    except OSError:
        return None
    return seq


def read_probe(status_dir) -> Optional[dict]:
    """The current probe, or None (no supervisor probing / torn
    write). Replica side: rendezvous.report_progress echoes it."""
    if status_dir is None:
        return None
    try:
        rec = json.loads((Path(status_dir) / PROBE_FILE).read_text())
        return {"probe_ts": float(rec["probe_ts"]), "seq": int(rec["seq"])}
    except (OSError, ValueError, TypeError, KeyError):
        return None


def job_clock_log(state_dir, key: str) -> Path:
    """THE per-job observation-log path (write and read side agree).
    A per-job DIRECTORY like status/checkpoints, so ``delete --purge``
    reclaims it through the same artifact-root sweep."""
    from ..controller.store import key_to_fs

    return Path(state_dir) / CLOCK_DIR / key_to_fs(key) / "observations.jsonl"


class ClockLog:
    """Append-only (send_ts, observe_ts) observation log for one job.

    Best-effort like the event sink: an unwritable disk drops
    observations, never the sync pass. The supervisor keeps one per
    active job and calls :meth:`observe` only on NEW beats, so the
    steady-state cost is zero writes per idle pass.
    """

    def __init__(self, path: Path, max_bytes: int = LOG_MAX_BYTES):
        self.path = Path(path)
        self.max_bytes = max_bytes
        self._size: Optional[int] = None  # lazily stat'ed once

    def observe(
        self,
        replica: str,
        send_ts: float,
        observe_ts: float,
        probe_ts: Optional[float] = None,
    ) -> None:
        rec = {"replica": replica, "send_ts": send_ts, "observe_ts": observe_ts}
        if probe_ts is not None:
            # Round-trip sample: the supervisor's probe-write time that
            # preceded this (echoed) send — see the module docstring.
            rec["probe_ts"] = probe_ts
        line = (json.dumps(rec) + "\n").encode()
        try:
            if self._size is None:
                try:
                    self._size = self.path.stat().st_size
                except OSError:
                    self._size = 0
            if self._size + len(line) > self.max_bytes:
                self.path.replace(self.path.with_suffix(".jsonl.1"))
                self._size = 0
            self.path.parent.mkdir(parents=True, exist_ok=True)
            with self.path.open("ab") as f:
                f.write(line)
            self._size += len(line)
        except OSError:
            pass


def load_observations(path) -> Dict[str, List[Tuple[float, ...]]]:
    """Parse an observation log (rotated generation included) into
    ``{replica: [(send_ts, observe_ts), ...]}``, oldest first —
    round-trip records load as ``(send_ts, observe_ts, probe_ts)``
    triples. Torn or foreign lines are skipped — the log is appended
    by a live daemon and read after kills, like every other recorded
    artifact."""
    p = Path(path)
    out: Dict[str, List[Tuple[float, ...]]] = {}
    for gen in (p.with_suffix(".jsonl.1"), p):
        try:
            data = gen.read_bytes()
        except OSError:
            continue
        for line in data.splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                replica = str(rec["replica"])
                pair: Tuple[float, ...] = (
                    float(rec["send_ts"]), float(rec["observe_ts"]),
                )
                if rec.get("probe_ts") is not None:
                    pair = pair + (float(rec["probe_ts"]),)
            except (ValueError, TypeError, KeyError):
                continue
            out.setdefault(replica, []).append(pair)
    return out


@dataclass
class OffsetEstimate:
    """One replica's clock relation to the supervisor's clock.

    ``offset_s``: seconds to ADD to the replica's timestamps to land
    them on the supervisor clock (supervisor ≈ replica + offset).
    ``drift_ppm``: relative clock rate error in parts-per-million.
    ``residual_s``: spread (q90 - q10) of the detrended delay samples —
    the estimate's uncertainty band; a skewed host is trustworthy when
    this sits well under the heartbeat interval.
    """

    offset_s: float
    drift_ppm: float
    n: int
    residual_s: float
    # Anchor of the drift term: offset_s is the correction AT t0 (the
    # earliest paired send_ts); offset_at extrapolates along the drift.
    t0: float = 0.0
    # Round-trip samples behind the estimate (0 = one-way only, the
    # delay-biased legacy path).
    rt_n: int = 0

    def offset_at(self, send_ts: float) -> float:
        """Correction for a timestamp recorded at ``send_ts`` (drift
        makes the correction time-dependent)."""
        return self.offset_s + (self.drift_ppm * 1e-6) * (send_ts - self.t0)

    def to_dict(self) -> dict:
        d = {
            "offset_s": round(self.offset_s, 6),
            "drift_ppm": round(self.drift_ppm, 3),
            "n": self.n,
            "residual_s": round(self.residual_s, 6),
        }
        if self.rt_n:
            d["rt_n"] = self.rt_n
        return d


def _quantile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank-with-interpolation quantile of pre-sorted values."""
    if not sorted_vals:
        return 0.0
    idx = q * (len(sorted_vals) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = idx - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def _theil_sen_slope(xs: Sequence[float], ys: Sequence[float]) -> float:
    """Median of pairwise slopes. O(n²) pairs are capped by striding so
    a 10k-observation log costs ~thousands of pairs, not 50M."""
    n = len(xs)
    stride = max(1, (n * (n - 1) // 2) // 4096)
    slopes: List[float] = []
    k = 0
    for i in range(n):
        for j in range(i + 1, n):
            k += 1
            if k % stride:
                continue
            dx = xs[j] - xs[i]
            if abs(dx) < 1e-9:
                continue
            slopes.append((ys[j] - ys[i]) / dx)
    if not slopes:
        return 0.0
    slopes.sort()
    return _quantile(slopes, 0.5)


def estimate_offset(
    pairs: Iterable[Tuple[float, float]], t0: Optional[float] = None
) -> Optional[OffsetEstimate]:
    """Estimate one replica's (offset, drift) from heartbeat pairs.

    ``pairs`` is ``[(send_ts_on_replica_clock, observe_ts_on_supervisor
    clock), ...]`` in any order — entries may also be round-trip
    triples ``(send_ts, observe_ts, probe_ts)`` (see the probe section
    of the module docstring); duplicates (a re-read beat) are
    harmless. Returns None with no pairs. ``t0`` anchors the drift term
    (defaults to the earliest send_ts) so ``offset_s`` is the
    correction AT the start of the recorded window.

    With at least :data:`MIN_ROUNDTRIP` triples present, the offset
    comes from the round-trip interval midpoints
    ``(probe_ts + observe_ts)/2 - send_ts`` — UNBIASED, unlike the
    one-way residual band which sits up to one poll delay above truth.
    """
    one_way: List[Tuple[float, float]] = []
    rt: List[Tuple[float, float, float]] = []
    for p in pairs:
        if len(p) >= 3 and p[2] is not None:
            rt.append((float(p[0]), float(p[1]), float(p[2])))
        else:
            one_way.append((float(p[0]), float(p[1])))
    rt = sorted(set(rt))
    ps = sorted(set(one_way))
    if not ps and not rt:
        return None
    all_sends = [s for s, _ in ps] + [s for s, _, _ in rt]
    t_ref = min(all_sends) if t0 is None else t0

    if len(rt) >= MIN_ROUNDTRIP:
        xs = [s - t_ref for s, _, _ in rt]
        # Interval midpoint per round trip: unbiased offset sample.
        ys = [0.5 * (pr + o) - s for s, o, pr in rt]
        drift = (
            _theil_sen_slope(xs, ys)
            if len(rt) >= MIN_PAIRS_FOR_DRIFT
            else 0.0
        )
        if abs(drift) * 1e6 > MAX_CREDIBLE_DRIFT_PPM:
            drift = 0.0
        resid = sorted(y - drift * x for x, y in zip(xs, ys))
        # Midpoints are already centered: the plain median is the
        # estimator (no low-band correction needed).
        offset = _quantile(resid, 0.50)
        spread = _quantile(resid, 0.90) - _quantile(resid, 0.10)
        return OffsetEstimate(
            offset_s=offset,
            drift_ppm=drift * 1e6,
            n=len(ps) + len(rt),
            residual_s=spread,
            t0=t_ref,
            rt_n=len(rt),
        )

    # One-way path (round trips, if any, contribute their upper-bound
    # pair like a regular observation).
    ps = sorted(set(ps + [(s, o) for s, o, _ in rt]))
    xs = [s - t_ref for s, _ in ps]
    ys = [o - s for s, o in ps]  # offset + delay samples
    drift = (
        _theil_sen_slope(xs, ys) if len(ps) >= MIN_PAIRS_FOR_DRIFT else 0.0
    )
    if abs(drift) * 1e6 > MAX_CREDIBLE_DRIFT_PPM:
        drift = 0.0
    resid = sorted(y - drift * x for x, y in zip(xs, ys))
    # Robust midpoint of the low band: halfway between the 10th and
    # 50th percentile residual — see the module docstring for why
    # neither min nor median alone.
    offset = 0.5 * (_quantile(resid, 0.10) + _quantile(resid, 0.50))
    spread = _quantile(resid, 0.90) - _quantile(resid, 0.10)
    return OffsetEstimate(
        offset_s=offset,
        drift_ppm=drift * 1e6,
        n=len(ps),
        residual_s=spread,
        t0=t_ref,
        rt_n=len(rt),
    )


def estimate_job_offsets(
    state_dir, key: str
) -> Dict[str, OffsetEstimate]:
    """Per-replica offset estimates for one job, from its recorded
    observation log. Empty when nothing was recorded (no supervisor
    daemon ran, or the job never heartbeat)."""
    obs = load_observations(job_clock_log(state_dir, key))
    out: Dict[str, OffsetEstimate] = {}
    for replica, pairs in obs.items():
        est = estimate_offset(pairs)
        if est is not None:
            out[replica] = est
    return out


def _trace_file_replica(path) -> Optional[str]:
    """``<process>-<pid>.trace.jsonl[.1]`` → ``<process>``, or None for
    files that do not follow the recorder's naming."""
    name = os.path.basename(str(path))
    for suffix in (".trace.jsonl.1", ".trace.jsonl"):
        if name.endswith(suffix):
            stem = name[: -len(suffix)]
            proc, sep, pid = stem.rpartition("-")
            if sep and pid.isdigit():
                return proc
            return stem or None
    return None


def offsets_for_trace_files(
    paths: Iterable, estimates: Dict[str, OffsetEstimate]
) -> Dict:
    """Map per-replica estimates onto span-file paths for
    ``merge_trace_files(clock_offsets=...)``. Files whose process name
    matches no estimate (the supervisor's own files — already in the
    reference frame — or replicas that never heartbeat) get no entry,
    i.e. a zero correction; so do estimates built from fewer than
    :data:`MIN_PAIRS_FOR_DRIFT` - 1 pairs (one delayed observation must
    not shear a whole file sideways)."""
    out: Dict = {}
    for p in paths:
        replica = _trace_file_replica(p)
        if replica is None:
            continue
        est = estimates.get(replica)
        if est is not None and est.offset_s and est.n >= 3:
            out[p] = est.offset_s
    return out
