"""The shared health-detector rules — ONE implementation, two engines.

The postmortem engine (``tpujob why``, obs/analyze.py) and the live
health engine (obs/watch.py, running inside the supervisor's steady
phase) must agree: an alert that fired live has to reproduce offline
from the recorded artifacts, and a finding ``why`` reports after a
death is exactly what the watch would have alerted on before it. The
only way those two stay in lockstep is to evaluate the identical code,
so the rules live here and both engines import them.

A rule is a function ``detect_*(view, th)`` over a :class:`TimelineView`
— the minimal read surface both engines can provide:

- offline, :class:`~pytorch_operator_tpu.obs.analyze.Timeline` is the
  full clock-aligned artifact join (every status record, event sink,
  merged spans);
- live, :class:`~pytorch_operator_tpu.obs.watch.LiveWindow` is a
  bounded rolling window of the records the supervisor's gauge fold
  already tailed (zero extra I/O) plus the in-memory event list.

The one deliberate asymmetry is the silence reference
(:meth:`TimelineView.silence_reference`): offline, a replica is silent
relative to the NEWEST beat in the gang (comparing to the recording's
end would flag every healthy finished job); live, it is silent relative
to the supervisor's wall clock — which is what lets a single-replica
hang alert fire while the job is still running, before the gang has any
other member to compare against.

Thresholds are a :class:`Thresholds` dataclass instead of module
constants so ``spec.observability.alerts.thresholds`` can override them
per job — the same values feed both engines (``tpujob why`` reads the
stored spec; the watch reads the live one).
"""

from __future__ import annotations

from dataclasses import dataclass, field, fields, replace
from typing import Callable, Dict, List, Mapping, Optional, Protocol, Tuple

# ---- thresholds ----


@dataclass(frozen=True)
class Thresholds:
    """Every tunable the detector rules consume. Defaults are the values
    the postmortem engine shipped with; ``spec.observability.alerts.
    thresholds`` overrides any subset per job (validation rejects
    unknown keys — see :data:`THRESHOLD_FIELDS`)."""

    # step_time_regression: recent median must exceed the baseline
    # median by this factor AND by an absolute floor (a 0.1ms -> 0.2ms
    # "doubling" is measurement noise, not a regression).
    regression_factor: float = 1.5
    regression_min_ms: float = 2.0
    regression_min_baseline: int = 6
    regression_min_recent: int = 3

    # feed_stall_dominance: median stall share of the step above this.
    feed_stall_share: float = 0.5
    feed_stall_min_ms: float = 1.0
    feed_min_samples: int = 4

    # checkpoint_lag: final (step - committed) beyond this many commit
    # cadences, or a writer queue that only grows over the last commits.
    ckpt_lag_cadences: float = 3.0
    ckpt_queue_growth_commits: int = 3

    # heartbeat_silence: a replica is silent when its last beat trails
    # the reference by this many median beat intervals (floored, so a
    # 10ms test cadence doesn't flag scheduler jitter).
    silence_factor: float = 3.0
    silence_min_s: float = 1.0

    # straggler: worst replica p50 step time vs the gang median p50.
    straggler_factor: float = 1.5
    straggler_min_samples: int = 4

    # noisy_neighbor: this many jobs regressing simultaneously on one
    # host attributes the regression to the host, not the jobs.
    noisy_neighbor_min_jobs: int = 2

    # queue_growth (serve plane): the router's front-queue depth never
    # falling over the last N samples AND rising by at least this much
    # net — arrivals are outpacing aggregate decode service.
    queue_growth_samples: int = 4
    queue_growth_min: float = 4.0

    # batch_size_collapse (serve plane): recent median busy-slot count
    # (slots - slots_free, summed across replicas per beat) under the
    # job's own earlier baseline by this factor; a tiny baseline is an
    # idle job, not a collapse.
    collapse_factor: float = 2.0
    collapse_min_baseline: float = 2.0
    collapse_min_samples: int = 3

    # world_resize_thrash (elastic): this many resize transitions
    # (scale-down / scale-up / spare promotion) inside one window means
    # the gang is oscillating between sizes instead of training — each
    # resize pays a restore-and-repartition, so thrash is pure waste.
    resize_thrash_count: int = 3
    resize_thrash_window_s: float = 120.0

    # slo_burn (serve plane): the fast-window error-budget burn rate
    # (bad fraction / allowed fraction; 1.0 = spending budget exactly
    # at the rate that exhausts it when sustained) at or above this
    # for the last N serve beats. Tail semantics so the live engine
    # resolves the alert once the burn decays.
    slo_burn_rate: float = 1.0
    slo_burn_samples: int = 3


DEFAULT_THRESHOLDS = Thresholds()

#: Valid override keys for ``spec.observability.alerts.thresholds``.
THRESHOLD_FIELDS = frozenset(f.name for f in fields(Thresholds))

_INT_FIELDS = frozenset(
    f.name for f in fields(Thresholds) if f.type in ("int", int)
)


def thresholds_from_overrides(
    overrides: Optional[Mapping[str, float]],
) -> Thresholds:
    """Defaults with any subset overridden. Unknown keys are ignored
    here (validation.py rejects them at submit time; recorded specs
    from a future version must not crash a postmortem)."""
    if not overrides:
        return DEFAULT_THRESHOLDS
    known = {}
    for k, v in overrides.items():
        if k not in THRESHOLD_FIELDS:
            continue
        try:
            known[k] = int(v) if k in _INT_FIELDS else float(v)
        except (TypeError, ValueError):
            continue
    return replace(DEFAULT_THRESHOLDS, **known) if known else DEFAULT_THRESHOLDS


# ---- small robust-stats helpers ----


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return 0.0
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _quantile(vals: List[float], q: float) -> float:
    s = sorted(vals)
    if not s:
        return 0.0
    idx = q * (len(s) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(s) - 1)
    return s[lo] * (1 - (idx - lo)) + s[hi] * (idx - lo)


# ---- findings ----


@dataclass
class Finding:
    """One detector hit. ``evidence`` entries are small dicts each
    naming their source (``status`` / ``event`` / ``span``), the
    ALIGNED timestamp, and enough coordinates to find the artifact
    (replica + record kind, event reason, or span name+args).
    ``replica`` names the implicated replica when the rule is
    replica-specific (silence victim, straggler) — the alert engine
    dedups on (job, rule, replica)."""

    rule: str
    severity: str  # "critical" | "warning" | "info"
    summary: str
    evidence: List[dict] = field(default_factory=list)
    metrics: Dict[str, float] = field(default_factory=dict)
    replica: Optional[str] = None

    def to_dict(self) -> dict:
        d = {
            "rule": self.rule,
            "severity": self.severity,
            "summary": self.summary,
            "evidence": self.evidence,
            "metrics": {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in self.metrics.items()
            },
        }
        if self.replica is not None:
            d["replica"] = self.replica
        return d


def ev_status(rec: dict, kind: str) -> dict:
    out = {
        "source": "status",
        "kind": kind,
        "replica": rec.get("replica", "?"),
        "ts": round(float(rec.get("aligned_ts", rec.get("ts", 0.0))), 6),
    }
    for f in ("step", "step_time_ms", "feed_stall_ms", "queue_depth",
              "commit_ms", "slots", "slots_free", "inflight",
              "ttft_ms_p99", "shed", "burn", "spills"):
        if rec.get(f) is not None:
            out[f] = rec[f]
    return out


def ev_event(rec: dict) -> dict:
    return {
        "source": "event",
        "reason": rec.get("reason", "?"),
        "type": rec.get("type", "?"),
        "ts": round(float(rec.get("timestamp", 0.0)), 6),
        "message": rec.get("message", ""),
    }


def ev_span(span: dict) -> dict:
    return {
        "source": "span",
        "name": span.get("name", "?"),
        "cat": span.get("cat", ""),
        "ts": round(span.get("ts", 0) / 1e6, 6),
        "dur_ms": round(span.get("dur", 0) / 1e3, 3),
        "args": span.get("args", {}),
    }


# ---- the view protocol both engines implement ----


class TimelineView(Protocol):
    """What a rule may read. obs/analyze.Timeline (full recorded
    history, clock-aligned) and obs/watch.LiveWindow (bounded rolling
    window, supervisor clock) both satisfy it."""

    window_s: Optional[float]
    #: {replica: [sanitized records with ``aligned_ts``], sorted}
    progress: Dict[str, List[dict]]
    #: {kind: [records across replicas]} for the non-progress kinds.
    records: Dict[str, List[dict]]

    def all_progress(self) -> List[dict]: ...

    def in_window(self, ts: float) -> bool: ...

    def beat_interval(self) -> float: ...

    def find_event(self, *reasons: str) -> Optional[dict]: ...

    def find_step_span(self, replica: str, step: int) -> Optional[dict]: ...

    def silence_reference(self) -> float: ...


# ---- detectors ----


def detect_step_time_regression(
    tl: TimelineView, th: Thresholds = DEFAULT_THRESHOLDS
) -> List[Finding]:
    """Recent step time vs the job's own earlier baseline. With a
    --window, "recent" is the window and the baseline is everything
    before it; without one, the newest quarter vs the rest."""
    samples = [
        r for r in tl.all_progress() if r.get("step_time_ms") is not None
    ]
    if tl.window_s is not None:
        recent = [r for r in samples if tl.in_window(r["aligned_ts"])]
        baseline = [r for r in samples if not tl.in_window(r["aligned_ts"])]
    else:
        cut = max(
            len(samples) - max(len(samples) // 4, th.regression_min_recent), 0
        )
        baseline, recent = samples[:cut], samples[cut:]
    if (
        len(baseline) < th.regression_min_baseline
        or len(recent) < th.regression_min_recent
    ):
        return []
    base_med = _median([float(r["step_time_ms"]) for r in baseline])
    rec_med = _median([float(r["step_time_ms"]) for r in recent])
    if (
        rec_med <= base_med * th.regression_factor
        or rec_med - base_med <= th.regression_min_ms
    ):
        return []
    worst = max(recent, key=lambda r: float(r["step_time_ms"]))
    evidence = [ev_status(worst, "progress")]
    if worst.get("step") is not None:
        span = tl.find_step_span(worst["replica"], int(worst["step"]))
        if span is not None:
            evidence.append(ev_span(span))
    evidence.append(ev_status(baseline[-1], "progress"))
    return [
        Finding(
            rule="step_time_regression",
            severity="warning",
            summary=(
                f"step time regressed: recent median "
                f"{rec_med:.1f}ms vs baseline {base_med:.1f}ms "
                f"({rec_med / max(base_med, 1e-9):.1f}x)"
            ),
            evidence=evidence,
            metrics={
                "baseline_ms": base_med,
                "recent_ms": rec_med,
                "factor": rec_med / max(base_med, 1e-9),
                "baseline_n": len(baseline),
                "recent_n": len(recent),
            },
        )
    ]


def detect_feed_stall(
    tl: TimelineView, th: Thresholds = DEFAULT_THRESHOLDS
) -> List[Finding]:
    samples = [
        r
        for r in tl.all_progress()
        if r.get("feed_stall_ms") is not None
        and r.get("step_time_ms") is not None
        and tl.in_window(r["aligned_ts"])
    ]
    if len(samples) < th.feed_min_samples:
        return []
    stall_med = _median([float(r["feed_stall_ms"]) for r in samples])
    step_med = _median([float(r["step_time_ms"]) for r in samples])
    if step_med <= 0 or stall_med < th.feed_stall_min_ms:
        return []
    share = stall_med / step_med
    if share <= th.feed_stall_share:
        return []
    worst = max(samples, key=lambda r: float(r["feed_stall_ms"]))
    return [
        Finding(
            rule="feed_stall_dominance",
            severity="warning",
            summary=(
                f"input feed dominates the step: median stall "
                f"{stall_med:.1f}ms is {100 * share:.0f}% of the "
                f"{step_med:.1f}ms step — the job is input-bound"
            ),
            evidence=[ev_status(worst, "progress")],
            metrics={
                "stall_ms": stall_med,
                "step_ms": step_med,
                "share": share,
                "n": len(samples),
            },
        )
    ]


def detect_checkpoint_lag(
    tl: TimelineView, th: Thresholds = DEFAULT_THRESHOLDS
) -> List[Finding]:
    commits = [
        r
        for r in tl.records.get("checkpoint_committed", [])
        if r.get("step") is not None
    ]
    if not commits:
        return []
    findings: List[Finding] = []
    steps = sorted(float(c["step"]) for c in commits)
    cadence = _median([b - a for a, b in zip(steps, steps[1:])]) or 1.0
    prog = [r for r in tl.all_progress() if r.get("step") is not None]
    last_step = float(prog[-1]["step"]) if prog else None
    last_commit = commits[-1]
    if last_step is not None:
        lag = last_step - float(last_commit["step"])
        if lag > max(th.ckpt_lag_cadences * cadence, th.ckpt_lag_cadences):
            findings.append(
                Finding(
                    rule="checkpoint_lag",
                    severity="warning",
                    summary=(
                        f"checkpoints trail training by {lag:.0f} steps "
                        f"(last commit step {last_commit['step']:.0f} vs "
                        f"trained step {last_step:.0f}; commit cadence "
                        f"~{cadence:.0f} steps) — a kill now loses that "
                        "progress"
                    ),
                    evidence=[
                        ev_status(last_commit, "checkpoint_committed"),
                        ev_status(prog[-1], "progress"),
                    ],
                    metrics={
                        "lag_steps": lag,
                        "cadence_steps": cadence,
                        "last_commit_step": float(last_commit["step"]),
                        "last_trained_step": last_step,
                    },
                )
            )
    depths = [
        float(c["queue_depth"])
        for c in commits
        if c.get("queue_depth") is not None
    ]
    tail = depths[-th.ckpt_queue_growth_commits:]
    if (
        len(tail) >= th.ckpt_queue_growth_commits
        and all(b > a for a, b in zip(tail, tail[1:]))
        and tail[-1] >= 2
    ):
        findings.append(
            Finding(
                rule="checkpoint_lag",
                severity="warning",
                summary=(
                    f"async checkpoint queue growing without draining "
                    f"(depth {tail[0]:.0f} -> {tail[-1]:.0f} over the "
                    f"last {len(tail)} commits) — commits are slower "
                    "than the save cadence"
                ),
                evidence=[ev_status(last_commit, "checkpoint_committed")],
                metrics={"queue_depth": tail[-1]},
            )
        )
    return findings


def detect_heartbeat_silence(
    tl: TimelineView, th: Thresholds = DEFAULT_THRESHOLDS
) -> List[Finding]:
    """The hung-replica detector. Two triggers: a recorded hang/deadline
    kill (name the replica whose beats stopped first, with evidence
    timestamped BEFORE the kill), or a replica silent relative to the
    view's silence reference — the gang's newest beat offline, the
    supervisor's wall clock live (see the module docstring)."""
    last_beats = {
        replica: rs[-1] for replica, rs in tl.progress.items() if rs
    }
    if not last_beats:
        return []
    gap = tl.beat_interval()
    threshold = max(th.silence_factor * gap, th.silence_min_s)
    findings: List[Finding] = []

    kill = tl.find_event("TPUJobHung", "DeadlineExceeded")
    if kill is not None:
        kill_ts = float(kill.get("timestamp", 0.0))
        # The hung replica: oldest last-beat in the gang (with
        # drop_heartbeat or a wedged collective, the victim stops first;
        # a fully-wedged world makes every replica a victim — name the
        # earliest-silent one).
        victim, rec = min(
            last_beats.items(), key=lambda kv: kv[1]["aligned_ts"]
        )
        silence = kill_ts - rec["aligned_ts"]
        evidence = [ev_status(rec, "progress"), ev_event(kill)]
        if rec.get("step") is not None:
            span = tl.find_step_span(victim, int(rec["step"]))
            if span is not None:
                evidence.insert(1, ev_span(span))
        findings.append(
            Finding(
                rule="heartbeat_silence",
                severity="critical",
                summary=(
                    f"replica {victim} went silent {silence:.1f}s before "
                    f"the {kill.get('reason')} kill (last beat at step "
                    f"{rec.get('step', '?')})"
                ),
                evidence=evidence,
                metrics={
                    "silence_s": silence,
                    "kill_ts": kill_ts,
                    "last_beat_ts": rec["aligned_ts"],
                },
                replica=victim,
            )
        )
        return findings

    # Silence vs the reference: newest gang beat offline ("someone kept
    # beating, someone stopped"), supervisor now live (a single hung
    # replica has nobody else to compare against before the kill).
    newest = tl.silence_reference()
    for replica, rec in sorted(last_beats.items()):
        silence = newest - rec["aligned_ts"]
        if silence > threshold:
            findings.append(
                Finding(
                    rule="heartbeat_silence",
                    severity="critical",
                    summary=(
                        f"replica {replica} silent for {silence:.1f}s "
                        f"(threshold {threshold:.1f}s = "
                        f"{th.silence_factor:g}x the {gap:.2f}s beat "
                        "interval)"
                    ),
                    evidence=[ev_status(rec, "progress")],
                    metrics={
                        "silence_s": silence,
                        "threshold_s": threshold,
                    },
                    replica=replica,
                )
            )
    return findings


def detect_straggler(
    tl: TimelineView, th: Thresholds = DEFAULT_THRESHOLDS
) -> List[Finding]:
    per_replica: Dict[str, List[float]] = {}
    for replica, rs in tl.progress.items():
        vals = [
            float(r["step_time_ms"])
            for r in rs
            if r.get("step_time_ms") is not None
            and tl.in_window(r["aligned_ts"])
        ]
        if len(vals) >= th.straggler_min_samples:
            per_replica[replica] = vals
    if len(per_replica) < 2:
        return []
    p50s = {r: _median(v) for r, v in per_replica.items()}
    gang_p50 = _median(list(p50s.values()))
    worst, worst_p50 = max(p50s.items(), key=lambda kv: kv[1])
    if gang_p50 <= 0 or worst_p50 <= th.straggler_factor * gang_p50:
        return []
    p99 = _quantile(per_replica[worst], 0.99)
    worst_rec = max(
        (r for r in tl.progress[worst] if r.get("step_time_ms") is not None),
        key=lambda r: float(r["step_time_ms"]),
    )
    evidence = [ev_status(worst_rec, "progress")]
    if worst_rec.get("step") is not None:
        span = tl.find_step_span(worst, int(worst_rec["step"]))
        if span is not None:
            evidence.append(ev_span(span))
    return [
        Finding(
            rule="straggler",
            severity="warning",
            summary=(
                f"replica {worst} straggles the gang: p50 step time "
                f"{worst_p50:.1f}ms vs gang {gang_p50:.1f}ms "
                f"({worst_p50 / gang_p50:.1f}x; its p99 {p99:.1f}ms)"
            ),
            evidence=evidence,
            metrics={
                "replica_p50_ms": worst_p50,
                "gang_p50_ms": gang_p50,
                "replica_p99_ms": p99,
                "spread": worst_p50 / gang_p50,
                "replicas": len(per_replica),
            },
            replica=worst,
        )
    ]


# The serve-plane rules read the "serve" status stream, which carries
# two shapes under one kind: the ROUTER's beat (has queue_depth /
# inflight, written to router.jsonl so its replica name is "router")
# and each ENGINE replica's occupancy beat (has slots / slots_free).
# Field presence — not replica name — selects the shape, so a renamed
# router stays detectable.

#: Replica-death / membership-change event reasons a serve-plane
#: finding cites as the likely cause (the chaos kill, a crashed
#: replica's restart, a preemption, an elastic scale-down).
_DEATH_REASONS = (
    "FaultInjected",
    "TPUJobRestarting",
    "TPUJobPreempted",
    "ElasticScaledDown",
)

#: World-membership transitions the elastic reconciler emits — one
#: event per committed resize generation (or restart-based grow).
_RESIZE_REASONS = (
    "ElasticScaledDown",
    "ElasticScaledUp",
    "ElasticSparePromoted",
)


def _iter_events(tl: TimelineView, *reasons: str) -> List[dict]:
    """Every matching event as a normalized dict, oldest first. Both
    views carry ``.events`` (Timeline: dicts; LiveWindow: Event objects
    or dicts) but the protocol only promises find_event — this is its
    find-ALL sibling, shared by rules that need the full history."""
    out: List[dict] = []
    for e in getattr(tl, "events", ()) or ():
        if isinstance(e, dict):
            if e.get("reason") in reasons:
                out.append(e)
        elif getattr(e, "reason", None) in reasons:
            out.append(
                {
                    "reason": e.reason,
                    "type": e.type,
                    "timestamp": e.timestamp,
                    "message": e.message,
                }
            )
    out.sort(key=lambda e: float(e.get("timestamp", 0.0)))
    return out


def detect_queue_growth(
    tl: TimelineView, th: Thresholds = DEFAULT_THRESHOLDS
) -> List[Finding]:
    """The router's front queue only growing: every sample in the tail
    at least as deep as the one before AND a net rise past the floor.
    A queue that breathes (fills, drains) is healthy batching; one that
    ratchets up is an offered load the replica set cannot clear —
    deadline sheds follow."""
    recs = [
        r
        for r in tl.records.get("serve", [])
        if r.get("queue_depth") is not None
        and tl.in_window(float(r.get("aligned_ts", r.get("ts", 0.0))))
    ]
    if len(recs) < th.queue_growth_samples:
        return []
    recs.sort(key=lambda r: float(r.get("aligned_ts", r.get("ts", 0.0))))
    tail = recs[-th.queue_growth_samples:]
    depths = [float(r["queue_depth"]) for r in tail]
    rise = depths[-1] - depths[0]
    if (
        any(b < a for a, b in zip(depths, depths[1:]))
        or rise < th.queue_growth_min
    ):
        return []
    evidence = [ev_status(tail[0], "serve"), ev_status(tail[-1], "serve")]
    death = tl.find_event(*_DEATH_REASONS)
    cause = ""
    if death is not None:
        evidence.append(ev_event(death))
        cause = (
            f"; coincides with {death.get('reason')} — lost decode "
            "capacity, not extra load"
        )
    return [
        Finding(
            rule="queue_growth",
            severity="warning",
            summary=(
                f"serve front queue only grows: depth "
                f"{depths[0]:.0f} -> {depths[-1]:.0f} over the last "
                f"{len(tail)} beats — arrivals outpace decode "
                f"service{cause}"
            ),
            evidence=evidence,
            metrics={
                "depth_first": depths[0],
                "depth_last": depths[-1],
                "rise": rise,
                "n": len(tail),
            },
        )
    ]


def detect_batch_size_collapse(
    tl: TimelineView, th: Thresholds = DEFAULT_THRESHOLDS
) -> List[Finding]:
    """Live decode batch (busy slots summed across engine replicas,
    per beat) collapsing against the job's own baseline. The classic
    cause is a replica death: the survivors' occupancy cannot cover the
    lost slots, TTFT spikes, and ``why`` should say so — the coinciding
    death event rides along as evidence. Recent/baseline split mirrors
    detect_step_time_regression."""
    samples = [
        r
        for r in tl.records.get("serve", [])
        if r.get("slots") is not None and r.get("slots_free") is not None
    ]
    if not samples:
        return []
    samples.sort(key=lambda r: float(r.get("aligned_ts", r.get("ts", 0.0))))
    # One occupancy point per beat: sum busy slots across replicas
    # reporting in the same beat bucket (the report cadence).
    beats: Dict[int, float] = {}
    beat_recs: Dict[int, dict] = {}
    for r in samples:
        ts = float(r.get("aligned_ts", r.get("ts", 0.0)))
        bucket = int(ts)
        beats[bucket] = beats.get(bucket, 0.0) + (
            float(r["slots"]) - float(r["slots_free"])
        )
        beat_recs[bucket] = r
    points = [
        (float(b), occ, beat_recs[b]) for b, occ in sorted(beats.items())
    ]
    if tl.window_s is not None:
        recent = [p for p in points if tl.in_window(p[0])]
        baseline = [p for p in points if not tl.in_window(p[0])]
    else:
        cut = max(len(points) - max(len(points) // 4, 2), 0)
        baseline, recent = points[:cut], points[cut:]
    if len(baseline) < th.collapse_min_samples or len(recent) < 2:
        return []
    base_med = _median([p[1] for p in baseline])
    rec_med = _median([p[1] for p in recent])
    if (
        base_med < th.collapse_min_baseline
        or rec_med > base_med / th.collapse_factor
    ):
        return []
    evidence = [
        ev_status(baseline[-1][2], "serve"),
        ev_status(recent[-1][2], "serve"),
    ]
    death = tl.find_event(*_DEATH_REASONS)
    cause = ""
    if death is not None:
        evidence.append(ev_event(death))
        cause = (
            f" — coincides with {death.get('reason')}: a replica death "
            "explains the lost slots (and the TTFT spike on what "
            "remains)"
        )
    return [
        Finding(
            rule="batch_size_collapse",
            severity="warning",
            summary=(
                f"live decode batch collapsed: busy slots "
                f"{base_med:.1f} -> {rec_med:.1f} "
                f"({base_med / max(rec_med, 1e-9):.1f}x under the "
                f"job's own baseline){cause}"
            ),
            evidence=evidence,
            metrics={
                "baseline_busy": base_med,
                "recent_busy": rec_med,
                "factor": base_med / max(rec_med, 1e-9),
                "baseline_n": len(baseline),
                "recent_n": len(recent),
            },
        )
    ]


def detect_slo_burn(
    tl: TimelineView, th: Thresholds = DEFAULT_THRESHOLDS
) -> List[Finding]:
    """Error-budget burn sustained at/above the threshold: the last
    ``slo_burn_samples`` in-window serve beats all carry a fast-window
    ``burn`` >= ``slo_burn_rate``. Burn 1.0 means the job is spending
    its (1 - target) budget exactly as fast as it accrues; anything
    above it, sustained, exhausts the budget. Tail semantics (not
    episode-anywhere) so the live engine resolves the alert the moment
    the burn decays below threshold — the offline report still surfaces
    past episodes through the alert log."""
    recs = [
        r
        for r in tl.records.get("serve", [])
        if r.get("burn") is not None
        and tl.in_window(float(r.get("aligned_ts", r.get("ts", 0.0))))
    ]
    if len(recs) < th.slo_burn_samples:
        return []
    recs.sort(key=lambda r: float(r.get("aligned_ts", r.get("ts", 0.0))))
    tail = recs[-th.slo_burn_samples:]
    burns = [float(r["burn"]) for r in tail]
    if any(b < th.slo_burn_rate for b in burns):
        return []
    peak = max(burns)
    shed = sum(float(r.get("shed", 0) or 0) for r in tail)
    evidence = [ev_status(tail[0], "serve"), ev_status(tail[-1], "serve")]
    death = tl.find_event(*_DEATH_REASONS)
    cause = ""
    if death is not None:
        evidence.append(ev_event(death))
        cause = (
            f"; coincides with {death.get('reason')} — lost decode "
            "capacity is spending the budget, not extra load"
        )
    return [
        Finding(
            rule="slo_burn",
            severity="critical" if peak >= 2 * th.slo_burn_rate else "warning",
            summary=(
                f"SLO error budget burning at {burns[-1]:.2f}x the "
                f"sustainable rate (peak {peak:.2f}x over the last "
                f"{len(tail)} beats, threshold {th.slo_burn_rate:g}) — "
                f"sheds and deadline misses are eating the "
                f"availability budget{cause}"
            ),
            evidence=evidence,
            metrics={
                "burn_last": burns[-1],
                "burn_peak": peak,
                "shed_in_tail": shed,
                "n": len(tail),
                "threshold": th.slo_burn_rate,
            },
        )
    ]


def detect_world_resize_thrash(
    tl: TimelineView, th: Thresholds = DEFAULT_THRESHOLDS
) -> List[Finding]:
    """The elastic gang oscillating between world sizes: at least
    ``resize_thrash_count`` resize transitions (scale-down, scale-up,
    spare promotion) inside one ``resize_thrash_window_s`` window. Each
    transition pays a checkpoint restore and state repartition, so a
    thrashing gang burns its time re-joining instead of training. The
    finding cites the triggering death events (kills, preemptions,
    restarts) inside the same span — capacity churn, not the job, is
    usually the cause."""
    resizes = _iter_events(tl, *_RESIZE_REASONS)
    if len(resizes) < th.resize_thrash_count:
        return []
    ts = [float(e.get("timestamp", 0.0)) for e in resizes]
    # Densest qualifying cluster: the earliest sliding window of
    # resize_thrash_count transitions that fits inside the time window.
    best: Optional[tuple] = None  # (i, j) inclusive
    k = th.resize_thrash_count
    for i in range(len(ts) - k + 1):
        j = i + k - 1
        if ts[j] - ts[i] > th.resize_thrash_window_s:
            continue
        # Extend right while still inside the window.
        while j + 1 < len(ts) and ts[j + 1] - ts[i] <= th.resize_thrash_window_s:
            j += 1
        best = (i, j)
        break
    if best is None:
        return []
    i, j = best
    cluster = resizes[i : j + 1]
    span = ts[j] - ts[i]
    deaths = [
        e
        for e in _iter_events(tl, *_DEATH_REASONS)
        if e.get("reason") not in _RESIZE_REASONS
        and ts[i] - th.resize_thrash_window_s
        <= float(e.get("timestamp", 0.0))
        <= ts[j]
    ]
    evidence = [ev_event(e) for e in cluster[:4]]
    evidence.extend(ev_event(e) for e in deaths[:3])
    kinds = ", ".join(
        sorted({str(e.get("reason", "?")) for e in cluster})
    )
    cause = (
        f"; triggered by {len(deaths)} death event(s) in the same span"
        if deaths
        else ""
    )
    return [
        Finding(
            rule="world_resize_thrash",
            severity="warning",
            summary=(
                f"world resized {len(cluster)} times within {span:.1f}s "
                f"(threshold {th.resize_thrash_count} in "
                f"{th.resize_thrash_window_s:.0f}s; {kinds}) — the gang "
                f"is thrashing between sizes instead of training{cause}"
            ),
            evidence=evidence,
            metrics={
                "resizes": len(cluster),
                "span_s": span,
                "deaths": len(deaths),
                "threshold_count": th.resize_thrash_count,
                "threshold_window_s": th.resize_thrash_window_s,
            },
        )
    ]


DETECTORS: Tuple[Callable[..., List[Finding]], ...] = (
    detect_heartbeat_silence,
    detect_step_time_regression,
    detect_feed_stall,
    detect_checkpoint_lag,
    detect_straggler,
    detect_queue_growth,
    detect_batch_size_collapse,
    detect_slo_burn,
    detect_world_resize_thrash,
)

#: Every rule either engine can produce (the alert/report inventory).
RULES = (
    "heartbeat_silence",
    "step_time_regression",
    "feed_stall_dominance",
    "checkpoint_lag",
    "straggler",
    "queue_growth",
    "batch_size_collapse",
    "slo_burn",
    "world_resize_thrash",
    "noisy_neighbor",
)

SEVERITY_ORDER = {"critical": 0, "warning": 1, "info": 2}


def run_detectors(
    tl: TimelineView, th: Thresholds = DEFAULT_THRESHOLDS
) -> List[Finding]:
    """Evaluate every per-job rule over one view, most severe first —
    THE shared entry point: ``tpujob why`` and the live watch both call
    exactly this."""
    findings: List[Finding] = []
    for det in DETECTORS:
        findings.extend(det(tl, th))
    findings.sort(key=lambda f: SEVERITY_ORDER.get(f.severity, 9))
    return findings


# ---- the cross-job rule (watch-level: needs the whole fleet) ----


def correlate_noisy_neighbor(
    regressing: Dict[str, Finding],
    host: str,
    th: Thresholds = DEFAULT_THRESHOLDS,
) -> Dict[str, Finding]:
    """Attribute SIMULTANEOUS step-time regressions across jobs sharing
    one host to a noisy neighbor (MLPerf TPU-pod study: host-level
    interference dominates tails). ``regressing`` maps job key -> its
    live step_time_regression finding this pass; when at least
    ``noisy_neighbor_min_jobs`` regress together, each gets a
    ``noisy_neighbor`` finding citing the others — the per-job
    regression alone would blame the job for the host's problem."""
    if len(regressing) < th.noisy_neighbor_min_jobs:
        return {}
    out: Dict[str, Finding] = {}
    for key, finding in regressing.items():
        others = sorted(k for k in regressing if k != key)
        out[key] = Finding(
            rule="noisy_neighbor",
            severity="warning",
            summary=(
                f"step-time regression correlates across "
                f"{len(regressing)} jobs on host {host} "
                f"(also regressing: {', '.join(others)}) — likely a "
                "noisy neighbor, not this job"
            ),
            evidence=[
                {
                    "source": "alert",
                    "job": k,
                    "rule": "step_time_regression",
                    "summary": regressing[k].summary,
                }
                for k in others
            ],
            metrics={
                "jobs_regressing": len(regressing),
                "factor": finding.metrics.get("factor", 0.0),
            },
        )
    return out
