"""``tpujob why`` — the cross-host postmortem engine.

The flight recorder (obs/trace, obs/metrics) answers "where did the
time go" to a human staring at Perfetto; production pre-training stacks
treat AUTOMATED diagnosis of stragglers, stalls, and checkpoint lag as
a first-class feature (TorchTitan, arXiv:2410.06511 — and the TPU-pod
concurrency study shows host-level skew and input-feed stalls dominate
real regressions). This module turns the recorded artifacts into a
diagnosis:

1. **Align** — per-replica clock offsets from the heartbeat observation
   log (obs/clock.py), so records from skewed hosts land on one causal
   axis (the supervisor's clock, which also stamps events and kills).
2. **Join** — one :class:`Timeline` from the per-replica status records
   (every kind, full history — this is offline, not the per-pass tail
   fold), the job's event sink, and (when recorded) the merged span
   files.
3. **Detect** — the SHARED rule pass (obs/rules.py — the same code the
   live watch evaluates every supervisor pass) over the timeline; each
   :class:`~pytorch_operator_tpu.obs.rules.Finding` cites the exact
   records/spans that evidence it. Per-job threshold overrides come
   from the stored ``spec.observability.alerts`` block, so offline and
   live judge by the same bar.
4. **Render** — a terminal report (:func:`render_report`) and a
   machine-readable dict (:func:`analyze`) for ``--out report.json``,
   including the live engine's alert history (what was already firing
   before death — obs/watch.py's append-only per-job alert log).

Everything runs strictly OFFLINE from recorded artifacts: analysis adds
zero span/metric calls to the step path (the bench_smoke lane pins it).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional

from .clock import OffsetEstimate, estimate_job_offsets, offsets_for_trace_files
from .metrics import parse_exemplars
from .rules import (  # noqa: F401  (re-exported: the pre-refactor public surface)
    DEFAULT_THRESHOLDS,
    DETECTORS,
    SEVERITY_ORDER as _SEVERITY_ORDER,
    Finding,
    Thresholds,
    detect_checkpoint_lag,
    detect_feed_stall,
    detect_heartbeat_silence,
    detect_step_time_regression,
    detect_straggler,
    run_detectors,
    thresholds_from_overrides,
)
from .trace import load_span_file, span_files

# Back-compat aliases: the detector thresholds were module constants
# before the rules moved to obs/rules.py (tests and external callers
# pinned them); the Thresholds dataclass is the source of truth now.
REGRESSION_FACTOR = DEFAULT_THRESHOLDS.regression_factor
REGRESSION_MIN_MS = DEFAULT_THRESHOLDS.regression_min_ms
REGRESSION_MIN_BASELINE = DEFAULT_THRESHOLDS.regression_min_baseline
REGRESSION_MIN_RECENT = DEFAULT_THRESHOLDS.regression_min_recent
FEED_STALL_SHARE = DEFAULT_THRESHOLDS.feed_stall_share
FEED_STALL_MIN_MS = DEFAULT_THRESHOLDS.feed_stall_min_ms
FEED_MIN_SAMPLES = DEFAULT_THRESHOLDS.feed_min_samples
CKPT_LAG_CADENCES = DEFAULT_THRESHOLDS.ckpt_lag_cadences
CKPT_QUEUE_GROWTH_COMMITS = DEFAULT_THRESHOLDS.ckpt_queue_growth_commits
SILENCE_FACTOR = DEFAULT_THRESHOLDS.silence_factor
SILENCE_MIN_S = DEFAULT_THRESHOLDS.silence_min_s
STRAGGLER_FACTOR = DEFAULT_THRESHOLDS.straggler_factor
STRAGGLER_MIN_SAMPLES = DEFAULT_THRESHOLDS.straggler_min_samples


class Timeline:
    """The per-job causal join: status records per replica, events, and
    spans, all on the supervisor's clock. The offline
    :class:`~pytorch_operator_tpu.obs.rules.TimelineView` — detectors
    read this; nothing here touches the live system."""

    def __init__(
        self,
        key: str,
        clock: Dict[str, OffsetEstimate],
        progress: Dict[str, List[dict]],
        records: Dict[str, List[dict]],
        events: List[dict],
        spans: List[dict],
        window_s: Optional[float] = None,
    ):
        self.key = key
        self.clock = clock
        # {replica: [progress records]}, each record sanitized floats
        # with an ``aligned_ts`` added; sorted by aligned_ts.
        self.progress = progress
        # {kind: [records across replicas]} for the non-progress kinds.
        self.records = records
        self.events = events
        self.spans = spans
        ts_all = [
            r["aligned_ts"] for rs in progress.values() for r in rs
        ] + [float(e.get("timestamp", 0.0)) for e in events]
        self.t_end = max(ts_all) if ts_all else 0.0
        self.t_start = min(ts_all) if ts_all else 0.0
        self.window_s = window_s

    def in_window(self, ts: float) -> bool:
        if self.window_s is None:
            return True
        return ts >= self.t_end - self.window_s

    def all_progress(self) -> List[dict]:
        out = [r for rs in self.progress.values() for r in rs]
        out.sort(key=lambda r: r["aligned_ts"])
        return out

    def beat_interval(self) -> float:
        """Median inter-beat gap pooled across replicas (the cadence
        silence is judged against)."""
        gaps: List[float] = []
        for rs in self.progress.values():
            for a, b in zip(rs, rs[1:]):
                gaps.append(b["aligned_ts"] - a["aligned_ts"])
        return _median(gaps) if gaps else 0.0

    def silence_reference(self) -> float:
        """Offline silence is judged against the gang's NEWEST beat
        ("someone kept beating, someone stopped") — never against the
        recording's end, which would flag every replica of a healthy
        finished job."""
        last = [rs[-1]["aligned_ts"] for rs in self.progress.values() if rs]
        return max(last) if last else 0.0

    def find_event(self, *reasons: str) -> Optional[dict]:
        for e in self.events:
            if e.get("reason") in reasons:
                return e
        return None

    def find_step_span(self, replica: str, step: int) -> Optional[dict]:
        for s in self.spans:
            if (
                s.get("name") == "step"
                and s.get("args", {}).get("step") == step
                and s.get("_replica", replica) == replica
            ):
                return s
        return None


def _median(vals: List[float]) -> float:
    from .rules import _median as m

    return m(vals)


# ---- timeline construction ----


def _read_status_records(status_dir) -> Dict[str, List[dict]]:
    """Full parse of every replica status file: {replica: [records]},
    file order preserved (append order == causal order per replica).
    Torn/foreign lines skipped, as everywhere on the read side."""
    d = Path(status_dir)
    out: Dict[str, List[dict]] = {}
    if not d.is_dir():
        return out
    for p in sorted(d.glob("*.jsonl")):
        recs: List[dict] = []
        try:
            data = p.read_bytes()
        except OSError:
            continue
        for line in data.splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict) or "event" not in rec:
                continue
            recs.append(rec)
        if recs:
            out[p.stem] = recs
    return out


def build_timeline(
    state_dir, key: str, window_s: Optional[float] = None
) -> Timeline:
    """Join the recorded artifacts for one job onto the aligned clock.

    Offline by construction: reads the status dir, event sink, clock
    log, and span files; writes nothing, emits no spans or metrics."""
    from ..controller.events import load_merged_events
    from ..controller.store import key_to_fs

    state = Path(state_dir)
    fs = key_to_fs(key)

    clock = estimate_job_offsets(state, key)

    def aligned(replica: str, ts: float) -> float:
        est = clock.get(replica)
        return ts + est.offset_at(ts) if est is not None else ts

    from ..controller.progress import TAILED_KINDS, _sanitize

    raw = _read_status_records(state / "status" / fs)
    progress: Dict[str, List[dict]] = {}
    records: Dict[str, List[dict]] = {}
    for replica, recs in raw.items():
        for rec in recs:
            kind = rec.get("event")
            try:
                ts = float(rec.get("ts", 0.0))
            except (TypeError, ValueError):
                continue
            if kind in TAILED_KINDS:
                # The supervisor-fold kinds get the same numeric
                # coercion the live fold applies — one foreign line
                # must not crash a postmortem either.
                clean = _sanitize(rec, kind)
                if clean is None:
                    continue
            else:
                clean = {k: v for k, v in rec.items() if k != "event"}
            clean["replica"] = replica
            clean["ts"] = ts
            clean["aligned_ts"] = aligned(replica, ts)
            if kind == "progress":
                progress.setdefault(replica, []).append(clean)
            else:
                records.setdefault(kind, []).append(clean)
    for rs in progress.values():
        rs.sort(key=lambda r: r["aligned_ts"])
    for rs in records.values():
        rs.sort(key=lambda r: r["aligned_ts"])

    events = load_merged_events(
        state / "events" / (fs + ".events.jsonl")
    )
    # Sharded control plane: the shard event log is GLOBAL (one bounded
    # sink, not one per job) — fold in the hand-offs of THIS job's
    # shard so the postmortem can cite an ownership change ("the
    # supervisor reconciling this job died at t; shard re-claimed at
    # t+ttl").
    events = events + shard_events_for_job(state, key)
    events.sort(key=lambda e: float(e.get("timestamp", 0.0)))

    # Spans (optional): replica files aligned by the estimator, the
    # supervisor's own files are already in the reference frame.
    spans: List[dict] = []
    for root in (state / "trace" / fs, state / "trace"):
        paths = span_files(root)
        offsets = offsets_for_trace_files(paths, clock)
        for p in paths:
            off_us = 1e6 * offsets.get(p, 0.0)
            replica = _replica_of_trace_file(p)
            for rec in load_span_file(p):
                if rec.get("ph") != "X":
                    continue
                if off_us:
                    rec = dict(rec)
                    rec["ts"] = rec.get("ts", 0) + off_us
                if replica:
                    rec["_replica"] = replica
                spans.append(rec)
    spans.sort(key=lambda r: r.get("ts", 0))

    return Timeline(
        key=key,
        clock=clock,
        progress=progress,
        records=records,
        events=events,
        spans=spans,
        window_s=window_s,
    )


def _replica_of_trace_file(path) -> Optional[str]:
    from .clock import _trace_file_replica

    return _trace_file_replica(path)


def shard_events_for_job(state_dir, key: str) -> List[dict]:
    """Shard hand-off events affecting ``key``'s shard, from the global
    shard event sink (controller/leases.py SHARD_EVENT_KEY). Empty when
    the control plane never ran sharded. The job's shard is resolved
    exactly like the supervisor does: spec pin if set, else key hash."""
    from ..controller.events import load_merged_events
    from ..controller.leases import SHARD_EVENT_KEY, shard_of_key
    from ..controller.store import JobStore, key_to_fs

    state = Path(state_dir)
    from ..controller.leases import read_shard_config

    num_shards = read_shard_config(state)
    if not num_shards:
        return []
    pin = None
    job = JobStore(persist_dir=state / "jobs").get(key)
    if job is not None:
        pin = job.spec.run_policy.scheduling_policy.shard
    shard = shard_of_key(key, num_shards, pin)
    needle = f"shard {shard} "
    out = []
    for ev in load_merged_events(
        state / "events" / (key_to_fs(SHARD_EVENT_KEY) + ".events.jsonl")
    ):
        msg = str(ev.get("message", ""))
        if needle in msg or f"shard(s) [{shard}]" in msg:
            ev = dict(ev)
            ev["shard"] = shard
            out.append(ev)
    return out


# ---- the engine ----

#: The five stages a traced request crosses between enqueue and its
#: decode — each maps the serve-path span names that account for it.
_TTFT_HOPS = (
    ("queue_wait", ("claim",)),
    ("lane_handoff", ("dispatch",)),
    ("transit", ("ring_transit", "spool_transit")),
    ("slot_wait", ("slot_wait",)),
    ("decode", ("decode",)),
)


def ttft_attribution(spans: List[dict]) -> Optional[dict]:
    """Where time-to-first-token went, pooled over every traced request
    in the window: the serve-path hop spans (cat ``serve``) bucketed
    into the stages a request crosses between client enqueue and its
    decode blocks. ``dominant`` names the hop with the largest mean —
    the one sentence the report leads with. None when the job recorded
    no serve spans (tracing off, or a training job)."""
    from .rules import _quantile

    by_name: Dict[str, List[float]] = {}
    rids = set()
    for s in spans:
        if s.get("cat") != "serve":
            continue
        by_name.setdefault(str(s.get("name", "?")), []).append(
            s.get("dur", 0) / 1e3
        )
        rid = (s.get("args") or {}).get("rid")
        if rid:
            rids.add(rid)
    hops: Dict[str, dict] = {}
    for hop, names in _TTFT_HOPS:
        vals = [v for n in names for v in by_name.get(n, [])]
        if not vals:
            continue
        hops[hop] = {
            "n": len(vals),
            "total_ms": round(sum(vals), 3),
            "mean_ms": round(sum(vals) / len(vals), 3),
            "p99_ms": round(_quantile(vals, 0.99), 3),
        }
    if not hops:
        return None
    dominant = max(hops, key=lambda h: hops[h]["mean_ms"])
    return {"requests": len(rids), "hops": hops, "dominant": dominant}


def job_thresholds(job) -> Thresholds:
    """The detector thresholds for one job: defaults overridden by its
    ``spec.observability.alerts.thresholds`` block. Shared bar: the
    live watch resolves the SAME way (obs/watch.py)."""
    if job is not None:
        ob = job.spec.observability
        if ob is not None and ob.alerts is not None:
            return thresholds_from_overrides(ob.alerts.thresholds)
    return DEFAULT_THRESHOLDS


def analyze(
    state_dir,
    key: str,
    window_s: Optional[float] = None,
    now: Optional[float] = None,
) -> dict:
    """Run the full postmortem for one job; returns the report dict
    (``tpujob why --out`` writes it verbatim as JSON)."""
    import time as _time

    from ..controller.store import JobStore

    tl = build_timeline(state_dir, key, window_s=window_s)
    job = JobStore(persist_dir=Path(state_dir) / "jobs").get(key)
    phase = None
    restarts = 0
    if job is not None:
        restarts = job.status.restart_count
        for c in reversed(job.status.conditions):
            if c.status:
                phase = c.type.value
                break

    findings = run_detectors(tl, job_thresholds(job))

    # Exemplar cross-links (when a daemon wrote metrics.prom): the p99
    # cell's latest span id per histogram, so the report can say WHICH
    # span landed the tail.
    exemplars: Dict[str, List[dict]] = {}
    # metrics.prom (unsharded) or one metrics-<identity>.prom per
    # sharded supervisor — the job's series live in its owner's file.
    for prom in sorted(Path(state_dir).glob("metrics*.prom")):
        try:
            for name, rows in parse_exemplars(prom.read_text()).items():
                hits = [
                    {"le": labels.get("le", ""), "span_id": span_id,
                     "value": value}
                    for labels, span_id, value in rows
                    if labels.get("job") == key
                ]
                if hits:
                    exemplars.setdefault(name, []).extend(hits)
        except OSError:
            pass

    # The live engine's verdicts (obs/watch.py alert log): what was
    # already pending/firing before the death `why` is explaining —
    # cross-cited so "the watch saw it live" and "the postmortem found
    # it" are one story.
    from .watch import load_alert_log

    alerts = load_alert_log(state_dir, key)

    # The remediation engine's audit trail (controller/remediation.py):
    # every alert→decision→action→outcome the closed loop took (or would
    # have taken, in dry-run) for this job, each citing the triggering
    # alert instance and the fencing token it committed under.
    from ..controller.remediation import load_remediation_log

    remediations = load_remediation_log(state_dir, key)

    # Control-plane ownership history for this job's shard: who was
    # reconciling it, and when that changed (lease expiry after a
    # supervisor death, rebalance, injected drop) — the citation for
    # "nothing reconciled this job between t and t+ttl".
    shard_handoffs = [
        {
            "ts": float(e.get("timestamp", 0.0)),
            "reason": e.get("reason"),
            "message": e.get("message"),
            "shard": e.get("shard"),
        }
        for e in shard_events_for_job(state_dir, key)
        if tl.in_window(float(e.get("timestamp", 0.0)))
    ]

    # Elastic resize history: every world-membership transition the
    # reconciler committed (shrink-in-place, spare promotion, grow-back)
    # plus the worker-side joins/evictions it fenced — the `why` face of
    # the resize-generation protocol.
    _RESIZE_HISTORY_REASONS = {
        "ElasticScaledDown",
        "ElasticScaledUp",
        "ElasticSparePromoted",
        "ElasticResizeJoined",
        "ElasticResizeEvicted",
        "ElasticResizeHealed",
    }
    resize_history = sorted(
        (
            {
                "ts": float(e.get("timestamp", 0.0)),
                "reason": e.get("reason"),
                "message": e.get("message"),
            }
            for e in tl.events
            if e.get("reason") in _RESIZE_HISTORY_REASONS
            and tl.in_window(float(e.get("timestamp", 0.0)))
        ),
        key=lambda r: r["ts"],
    )

    replicas = {
        replica: {
            "beats": len(rs),
            "first_ts": round(rs[0]["aligned_ts"], 6),
            "last_ts": round(rs[-1]["aligned_ts"], 6),
            "last_step": rs[-1].get("step"),
        }
        for replica, rs in sorted(tl.progress.items())
    }

    return {
        "job": key,
        "generated_at": _time.time() if now is None else now,
        "window_s": window_s,
        "phase": phase,
        "restarts": restarts,
        "clock": {r: est.to_dict() for r, est in sorted(tl.clock.items())},
        "replicas": replicas,
        "events": len(tl.events),
        "spans": len(tl.spans),
        "exemplars": exemplars,
        "ttft_attribution": ttft_attribution(tl.spans),
        "alerts": alerts,
        "remediations": remediations,
        "shard_handoffs": shard_handoffs,
        "resize_history": resize_history,
        "findings": [f.to_dict() for f in findings],
    }


def _fmt_ev(ev: dict) -> str:
    src = ev.get("source")
    if src == "event":
        return (
            f"event  {ev.get('reason')} @ {ev.get('ts'):.3f}  "
            f"{ev.get('message', '')}"
        )
    if src == "span":
        args = ev.get("args") or {}
        blob = " ".join(f"{k}={v}" for k, v in sorted(args.items()))
        return (
            f"span   {ev.get('name')} @ {ev.get('ts'):.3f} "
            f"dur={ev.get('dur_ms'):.1f}ms {blob}".rstrip()
        )
    if src == "alert":
        return (
            f"alert  {ev.get('rule')} on {ev.get('job')}: "
            f"{ev.get('summary', '')}"
        )
    fields = " ".join(
        f"{k}={ev[k]}"
        for k in ("step", "step_time_ms", "feed_stall_ms", "queue_depth")
        if ev.get(k) is not None
    )
    return (
        f"status {ev.get('kind')} {ev.get('replica')} @ "
        f"{ev.get('ts'):.3f} {fields}".rstrip()
    )


def render_report(report: dict) -> str:
    """The terminal face of the report: findings first (most severe on
    top), each with its evidence; alert history and clock table after;
    '-' free prose kept short — the JSON carries the full detail."""
    lines: List[str] = []
    head = f"tpujob why {report['job']}"
    if report.get("phase"):
        head += f" — {report['phase']} (restarts={report['restarts']})"
    lines.append(head)
    reps = report.get("replicas", {})
    lines.append(
        f"analyzed: {sum(r['beats'] for r in reps.values())} heartbeats "
        f"from {len(reps)} replica(s), {report.get('events', 0)} events, "
        f"{report.get('spans', 0)} spans"
        + (
            f", window {report['window_s']:g}s"
            if report.get("window_s")
            else ""
        )
    )
    clock = report.get("clock", {})
    if clock:
        parts = [
            f"{r} {e['offset_s']:+.3f}s ±{e['residual_s']:.3f} (n={e['n']})"
            for r, e in clock.items()
        ]
        lines.append("clock:    " + "; ".join(parts))
    alerts = report.get("alerts", [])
    findings = report.get("findings", [])
    ttft = report.get("ttft_attribution")
    if (
        not findings
        and not alerts
        and not ttft
        and not report.get("remediations")
        and not report.get("shard_handoffs")
        and not report.get("resize_history")
    ):
        lines.append("")
        lines.append("no findings — the recorded window looks healthy.")
        return "\n".join(lines)
    if findings:
        lines.append("")
        lines.append(f"FINDINGS ({len(findings)}):")
        for i, f in enumerate(findings, 1):
            lines.append(
                f"{i:3d}. [{f['severity']}] {f['rule']}: {f['summary']}"
            )
            for ev in f.get("evidence", []):
                lines.append(f"       - {_fmt_ev(ev)}")
    else:
        lines.append("")
        lines.append("no findings — the recorded window looks healthy.")
    if ttft:
        # Serve-path hop breakdown (only when request tracing recorded
        # serve spans): which hop is eating time-to-first-token.
        lines.append("")
        lines.append(
            f"TTFT ATTRIBUTION ({ttft.get('requests', 0)} traced "
            f"request(s)) — dominant hop: {ttft.get('dominant', '?')}"
        )
        for hop, _names in _TTFT_HOPS:
            st = ttft.get("hops", {}).get(hop)
            if st is None:
                continue
            lines.append(
                f"  {hop:<12} mean {st['mean_ms']:8.2f}ms  "
                f"p99 {st['p99_ms']:8.2f}ms  "
                f"total {st['total_ms']:9.1f}ms  (n={st['n']})"
            )
    if alerts:
        # What the live engine already said, while the job was running:
        # every firing/resolved transition, oldest first.
        lines.append("")
        lines.append(f"LIVE ALERTS ({len(alerts)} transition(s)):")
        for rec in alerts:
            who = rec.get("replica") or "*"
            lines.append(
                f"  {rec.get('state', '?'):<8} [{rec.get('severity', '?')}] "
                f"{rec.get('rule', '?')} {who} @ "
                f"{float(rec.get('ts', 0.0)):.3f}  "
                f"{rec.get('summary', '')}"
            )
    remediations = report.get("remediations", [])
    if remediations:
        # What the closed loop DID about those alerts: each action cites
        # the causal alert instance so the remediation and the alert read
        # as one story (and dry-run decisions are visibly inert).
        lines.append("")
        lines.append(f"REMEDIATIONS ({len(remediations)} action(s)):")
        for rec in remediations:
            lines.append(
                f"  {rec.get('outcome', '?'):<8} "
                f"{rec.get('action', '?'):<18} gen={rec.get('generation', 0)} "
                f"rule={rec.get('rule', '?')} @ "
                f"{float(rec.get('ts', 0.0)):.3f}  {rec.get('detail', '')}"
            )
            al = rec.get("alert")
            if al:
                lines.append(
                    f"           └ alert [{al.get('severity', '?')}] "
                    f"{al.get('rule', '?')} {al.get('replica') or '*'} "
                    f"fired @ {float(al.get('fired_at') or 0.0):.3f}  "
                    f"{al.get('summary', '')}"
                )
    handoffs = report.get("shard_handoffs", [])
    if handoffs:
        lines.append("")
        lines.append(
            f"SHARD OWNERSHIP ({len(handoffs)} hand-off event(s) for "
            f"shard {handoffs[0].get('shard')}):"
        )
        for rec in handoffs:
            lines.append(
                f"  {rec.get('reason', '?'):<16} @ "
                f"{float(rec.get('ts', 0.0)):.3f}  {rec.get('message', '')}"
            )
    resizes = report.get("resize_history", [])
    if resizes:
        lines.append("")
        lines.append(f"RESIZE HISTORY ({len(resizes)} transition(s)):")
        for rec in resizes:
            lines.append(
                f"  {rec.get('reason', '?'):<20} @ "
                f"{float(rec.get('ts', 0.0)):.3f}  {rec.get('message', '')}"
            )
    return "\n".join(lines)
