"""``tpujob why`` — the cross-host postmortem engine.

The flight recorder (obs/trace, obs/metrics) answers "where did the
time go" to a human staring at Perfetto; production pre-training stacks
treat AUTOMATED diagnosis of stragglers, stalls, and checkpoint lag as
a first-class feature (TorchTitan, arXiv:2410.06511 — and the TPU-pod
concurrency study shows host-level skew and input-feed stalls dominate
real regressions). This module turns the recorded artifacts into a
diagnosis:

1. **Align** — per-replica clock offsets from the heartbeat observation
   log (obs/clock.py), so records from skewed hosts land on one causal
   axis (the supervisor's clock, which also stamps events and kills).
2. **Join** — one :class:`Timeline` from the per-replica status records
   (every kind, full history — this is offline, not the per-pass tail
   fold), the job's event sink, and (when recorded) the merged span
   files.
3. **Detect** — a rule pass over the timeline; each
   :class:`Finding` cites the exact records/spans that evidence it:

   - ``step_time_regression`` — recent step time vs the job's OWN
     baseline window (no fleet-wide "normal" needed);
   - ``feed_stall_dominance`` — the device feed eats a dominant share
     of the step (the input-bound signature);
   - ``checkpoint_lag`` — committed step falls behind the training
     step, or the async writer queue grows without draining;
   - ``heartbeat_silence`` — a replica stopped beating before a
     hang/deadline kill (names the hung replica, evidence timestamped
     BEFORE the kill event);
   - ``straggler`` — one replica's step-time distribution sits far
     above the gang's (p99/p50 spread across members).

4. **Render** — a terminal report (:func:`render_report`) and a
   machine-readable dict (:func:`analyze`) for ``--out report.json``.

Everything runs strictly OFFLINE from recorded artifacts: analysis adds
zero span/metric calls to the step path (the bench_smoke lane pins it).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional

from .clock import OffsetEstimate, estimate_job_offsets, offsets_for_trace_files
from .metrics import parse_exemplars
from .trace import load_span_file, span_files

# ---- detector thresholds (module constants so tests pin behavior) ----

# step_time_regression: recent median must exceed the baseline median
# by this factor AND by an absolute floor (a 0.1ms -> 0.2ms "doubling"
# is measurement noise, not a regression).
REGRESSION_FACTOR = 1.5
REGRESSION_MIN_MS = 2.0
REGRESSION_MIN_BASELINE = 6
REGRESSION_MIN_RECENT = 3

# feed_stall_dominance: median stall share of the step above this.
FEED_STALL_SHARE = 0.5
FEED_STALL_MIN_MS = 1.0
FEED_MIN_SAMPLES = 4

# checkpoint_lag: final (step - committed) beyond this many commit
# cadences, or a writer queue that only grows over the last commits.
CKPT_LAG_CADENCES = 3.0
CKPT_QUEUE_GROWTH_COMMITS = 3

# heartbeat_silence: a replica is silent when its last beat trails the
# reference by this many median beat intervals (floored, so a 10ms test
# cadence doesn't flag scheduler jitter).
SILENCE_FACTOR = 3.0
SILENCE_MIN_S = 1.0

# straggler: worst replica p50 step time vs the gang median p50, plus a
# per-replica in-distribution tail check (p99/p50).
STRAGGLER_FACTOR = 1.5
STRAGGLER_MIN_SAMPLES = 4


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return 0.0
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _quantile(vals: List[float], q: float) -> float:
    s = sorted(vals)
    if not s:
        return 0.0
    idx = q * (len(s) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(s) - 1)
    return s[lo] * (1 - (idx - lo)) + s[hi] * (idx - lo)


@dataclass
class Finding:
    """One detector hit. ``evidence`` entries are small dicts each
    naming their source (``status`` / ``event`` / ``span``), the
    ALIGNED timestamp, and enough coordinates to find the artifact
    (replica + record kind, event reason, or span name+args)."""

    rule: str
    severity: str  # "critical" | "warning" | "info"
    summary: str
    evidence: List[dict] = field(default_factory=list)
    metrics: Dict[str, float] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "summary": self.summary,
            "evidence": self.evidence,
            "metrics": {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in self.metrics.items()
            },
        }


def _ev_status(rec: dict, kind: str) -> dict:
    out = {
        "source": "status",
        "kind": kind,
        "replica": rec.get("replica", "?"),
        "ts": round(float(rec.get("aligned_ts", rec.get("ts", 0.0))), 6),
    }
    for f in ("step", "step_time_ms", "feed_stall_ms", "queue_depth",
              "commit_ms"):
        if rec.get(f) is not None:
            out[f] = rec[f]
    return out


def _ev_event(rec: dict) -> dict:
    return {
        "source": "event",
        "reason": rec.get("reason", "?"),
        "type": rec.get("type", "?"),
        "ts": round(float(rec.get("timestamp", 0.0)), 6),
        "message": rec.get("message", ""),
    }


def _ev_span(span: dict) -> dict:
    return {
        "source": "span",
        "name": span.get("name", "?"),
        "cat": span.get("cat", ""),
        "ts": round(span.get("ts", 0) / 1e6, 6),
        "dur_ms": round(span.get("dur", 0) / 1e3, 3),
        "args": span.get("args", {}),
    }


class Timeline:
    """The per-job causal join: status records per replica, events, and
    spans, all on the supervisor's clock. Detectors read this; nothing
    here touches the live system."""

    def __init__(
        self,
        key: str,
        clock: Dict[str, OffsetEstimate],
        progress: Dict[str, List[dict]],
        records: Dict[str, List[dict]],
        events: List[dict],
        spans: List[dict],
        window_s: Optional[float] = None,
    ):
        self.key = key
        self.clock = clock
        # {replica: [progress records]}, each record sanitized floats
        # with an ``aligned_ts`` added; sorted by aligned_ts.
        self.progress = progress
        # {kind: [records across replicas]} for the non-progress kinds.
        self.records = records
        self.events = events
        self.spans = spans
        ts_all = [
            r["aligned_ts"] for rs in progress.values() for r in rs
        ] + [float(e.get("timestamp", 0.0)) for e in events]
        self.t_end = max(ts_all) if ts_all else 0.0
        self.t_start = min(ts_all) if ts_all else 0.0
        self.window_s = window_s

    def in_window(self, ts: float) -> bool:
        if self.window_s is None:
            return True
        return ts >= self.t_end - self.window_s

    def all_progress(self) -> List[dict]:
        out = [r for rs in self.progress.values() for r in rs]
        out.sort(key=lambda r: r["aligned_ts"])
        return out

    def beat_interval(self) -> float:
        """Median inter-beat gap pooled across replicas (the cadence
        silence is judged against)."""
        gaps: List[float] = []
        for rs in self.progress.values():
            for a, b in zip(rs, rs[1:]):
                gaps.append(b["aligned_ts"] - a["aligned_ts"])
        return _median(gaps) if gaps else 0.0

    def find_event(self, *reasons: str) -> Optional[dict]:
        for e in self.events:
            if e.get("reason") in reasons:
                return e
        return None

    def find_step_span(self, replica: str, step: int) -> Optional[dict]:
        for s in self.spans:
            if (
                s.get("name") == "step"
                and s.get("args", {}).get("step") == step
                and s.get("_replica", replica) == replica
            ):
                return s
        return None


# ---- timeline construction ----


def _read_status_records(status_dir) -> Dict[str, List[dict]]:
    """Full parse of every replica status file: {replica: [records]},
    file order preserved (append order == causal order per replica).
    Torn/foreign lines skipped, as everywhere on the read side."""
    d = Path(status_dir)
    out: Dict[str, List[dict]] = {}
    if not d.is_dir():
        return out
    for p in sorted(d.glob("*.jsonl")):
        recs: List[dict] = []
        try:
            data = p.read_bytes()
        except OSError:
            continue
        for line in data.splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict) or "event" not in rec:
                continue
            recs.append(rec)
        if recs:
            out[p.stem] = recs
    return out


def build_timeline(
    state_dir, key: str, window_s: Optional[float] = None
) -> Timeline:
    """Join the recorded artifacts for one job onto the aligned clock.

    Offline by construction: reads the status dir, event sink, clock
    log, and span files; writes nothing, emits no spans or metrics."""
    from ..controller.events import load_merged_events
    from ..controller.store import key_to_fs

    state = Path(state_dir)
    fs = key_to_fs(key)

    clock = estimate_job_offsets(state, key)

    def aligned(replica: str, ts: float) -> float:
        est = clock.get(replica)
        return ts + est.offset_at(ts) if est is not None else ts

    from ..controller.progress import TAILED_KINDS, _sanitize

    raw = _read_status_records(state / "status" / fs)
    progress: Dict[str, List[dict]] = {}
    records: Dict[str, List[dict]] = {}
    for replica, recs in raw.items():
        for rec in recs:
            kind = rec.get("event")
            try:
                ts = float(rec.get("ts", 0.0))
            except (TypeError, ValueError):
                continue
            if kind in TAILED_KINDS:
                # The supervisor-fold kinds get the same numeric
                # coercion the live fold applies — one foreign line
                # must not crash a postmortem either.
                clean = _sanitize(rec, kind)
                if clean is None:
                    continue
            else:
                clean = {k: v for k, v in rec.items() if k != "event"}
            clean["replica"] = replica
            clean["ts"] = ts
            clean["aligned_ts"] = aligned(replica, ts)
            if kind == "progress":
                progress.setdefault(replica, []).append(clean)
            else:
                records.setdefault(kind, []).append(clean)
    for rs in progress.values():
        rs.sort(key=lambda r: r["aligned_ts"])
    for rs in records.values():
        rs.sort(key=lambda r: r["aligned_ts"])

    events = load_merged_events(
        state / "events" / (fs + ".events.jsonl")
    )

    # Spans (optional): replica files aligned by the estimator, the
    # supervisor's own files are already in the reference frame.
    spans: List[dict] = []
    for root in (state / "trace" / fs, state / "trace"):
        paths = span_files(root)
        offsets = offsets_for_trace_files(paths, clock)
        for p in paths:
            off_us = 1e6 * offsets.get(p, 0.0)
            replica = _replica_of_trace_file(p)
            for rec in load_span_file(p):
                if rec.get("ph") != "X":
                    continue
                if off_us:
                    rec = dict(rec)
                    rec["ts"] = rec.get("ts", 0) + off_us
                if replica:
                    rec["_replica"] = replica
                spans.append(rec)
    spans.sort(key=lambda r: r.get("ts", 0))

    return Timeline(
        key=key,
        clock=clock,
        progress=progress,
        records=records,
        events=events,
        spans=spans,
        window_s=window_s,
    )


def _replica_of_trace_file(path) -> Optional[str]:
    from .clock import _trace_file_replica

    return _trace_file_replica(path)


# ---- detectors ----


def detect_step_time_regression(tl: Timeline) -> List[Finding]:
    """Recent step time vs the job's own earlier baseline. With a
    --window, "recent" is the window and the baseline is everything
    before it; without one, the newest quarter vs the rest."""
    samples = [
        r for r in tl.all_progress() if r.get("step_time_ms") is not None
    ]
    if tl.window_s is not None:
        recent = [r for r in samples if tl.in_window(r["aligned_ts"])]
        baseline = [r for r in samples if not tl.in_window(r["aligned_ts"])]
    else:
        cut = max(len(samples) - max(len(samples) // 4, REGRESSION_MIN_RECENT), 0)
        baseline, recent = samples[:cut], samples[cut:]
    if (
        len(baseline) < REGRESSION_MIN_BASELINE
        or len(recent) < REGRESSION_MIN_RECENT
    ):
        return []
    base_med = _median([float(r["step_time_ms"]) for r in baseline])
    rec_med = _median([float(r["step_time_ms"]) for r in recent])
    if (
        rec_med <= base_med * REGRESSION_FACTOR
        or rec_med - base_med <= REGRESSION_MIN_MS
    ):
        return []
    worst = max(recent, key=lambda r: float(r["step_time_ms"]))
    evidence = [_ev_status(worst, "progress")]
    if worst.get("step") is not None:
        span = tl.find_step_span(worst["replica"], int(worst["step"]))
        if span is not None:
            evidence.append(_ev_span(span))
    evidence.append(_ev_status(baseline[-1], "progress"))
    return [
        Finding(
            rule="step_time_regression",
            severity="warning",
            summary=(
                f"step time regressed: recent median "
                f"{rec_med:.1f}ms vs baseline {base_med:.1f}ms "
                f"({rec_med / max(base_med, 1e-9):.1f}x)"
            ),
            evidence=evidence,
            metrics={
                "baseline_ms": base_med,
                "recent_ms": rec_med,
                "factor": rec_med / max(base_med, 1e-9),
                "baseline_n": len(baseline),
                "recent_n": len(recent),
            },
        )
    ]


def detect_feed_stall(tl: Timeline) -> List[Finding]:
    samples = [
        r
        for r in tl.all_progress()
        if r.get("feed_stall_ms") is not None
        and r.get("step_time_ms") is not None
        and tl.in_window(r["aligned_ts"])
    ]
    if len(samples) < FEED_MIN_SAMPLES:
        return []
    stall_med = _median([float(r["feed_stall_ms"]) for r in samples])
    step_med = _median([float(r["step_time_ms"]) for r in samples])
    if step_med <= 0 or stall_med < FEED_STALL_MIN_MS:
        return []
    share = stall_med / step_med
    if share <= FEED_STALL_SHARE:
        return []
    worst = max(samples, key=lambda r: float(r["feed_stall_ms"]))
    return [
        Finding(
            rule="feed_stall_dominance",
            severity="warning",
            summary=(
                f"input feed dominates the step: median stall "
                f"{stall_med:.1f}ms is {100 * share:.0f}% of the "
                f"{step_med:.1f}ms step — the job is input-bound"
            ),
            evidence=[_ev_status(worst, "progress")],
            metrics={
                "stall_ms": stall_med,
                "step_ms": step_med,
                "share": share,
                "n": len(samples),
            },
        )
    ]


def detect_checkpoint_lag(tl: Timeline) -> List[Finding]:
    commits = [
        r
        for r in tl.records.get("checkpoint_committed", [])
        if r.get("step") is not None
    ]
    if not commits:
        return []
    findings: List[Finding] = []
    steps = sorted(float(c["step"]) for c in commits)
    cadence = _median([b - a for a, b in zip(steps, steps[1:])]) or 1.0
    prog = [r for r in tl.all_progress() if r.get("step") is not None]
    last_step = float(prog[-1]["step"]) if prog else None
    last_commit = commits[-1]
    if last_step is not None:
        lag = last_step - float(last_commit["step"])
        if lag > max(CKPT_LAG_CADENCES * cadence, CKPT_LAG_CADENCES):
            findings.append(
                Finding(
                    rule="checkpoint_lag",
                    severity="warning",
                    summary=(
                        f"checkpoints trail training by {lag:.0f} steps "
                        f"(last commit step {last_commit['step']:.0f} vs "
                        f"trained step {last_step:.0f}; commit cadence "
                        f"~{cadence:.0f} steps) — a kill now loses that "
                        "progress"
                    ),
                    evidence=[
                        _ev_status(last_commit, "checkpoint_committed"),
                        _ev_status(prog[-1], "progress"),
                    ],
                    metrics={
                        "lag_steps": lag,
                        "cadence_steps": cadence,
                        "last_commit_step": float(last_commit["step"]),
                        "last_trained_step": last_step,
                    },
                )
            )
    depths = [
        float(c["queue_depth"])
        for c in commits
        if c.get("queue_depth") is not None
    ]
    tail = depths[-CKPT_QUEUE_GROWTH_COMMITS:]
    if (
        len(tail) >= CKPT_QUEUE_GROWTH_COMMITS
        and all(b > a for a, b in zip(tail, tail[1:]))
        and tail[-1] >= 2
    ):
        findings.append(
            Finding(
                rule="checkpoint_lag",
                severity="warning",
                summary=(
                    f"async checkpoint queue growing without draining "
                    f"(depth {tail[0]:.0f} -> {tail[-1]:.0f} over the "
                    f"last {len(tail)} commits) — commits are slower "
                    "than the save cadence"
                ),
                evidence=[_ev_status(last_commit, "checkpoint_committed")],
                metrics={"queue_depth": tail[-1]},
            )
        )
    return findings


def detect_heartbeat_silence(tl: Timeline) -> List[Finding]:
    """The hung-replica detector. Two triggers: a recorded hang/deadline
    kill (name the replica whose beats stopped first, with evidence
    timestamped BEFORE the kill), or a replica silent while the rest of
    the gang kept beating."""
    last_beats = {
        replica: rs[-1] for replica, rs in tl.progress.items() if rs
    }
    if not last_beats:
        return []
    gap = tl.beat_interval()
    threshold = max(SILENCE_FACTOR * gap, SILENCE_MIN_S)
    findings: List[Finding] = []

    kill = tl.find_event("TPUJobHung", "DeadlineExceeded")
    if kill is not None:
        kill_ts = float(kill.get("timestamp", 0.0))
        # The hung replica: oldest last-beat in the gang (with
        # drop_heartbeat or a wedged collective, the victim stops first;
        # a fully-wedged world makes every replica a victim — name the
        # earliest-silent one).
        victim, rec = min(
            last_beats.items(), key=lambda kv: kv[1]["aligned_ts"]
        )
        silence = kill_ts - rec["aligned_ts"]
        evidence = [_ev_status(rec, "progress"), _ev_event(kill)]
        if rec.get("step") is not None:
            span = tl.find_step_span(victim, int(rec["step"]))
            if span is not None:
                evidence.insert(1, _ev_span(span))
        findings.append(
            Finding(
                rule="heartbeat_silence",
                severity="critical",
                summary=(
                    f"replica {victim} went silent {silence:.1f}s before "
                    f"the {kill.get('reason')} kill (last beat at step "
                    f"{rec.get('step', '?')})"
                ),
                evidence=evidence,
                metrics={
                    "silence_s": silence,
                    "kill_ts": kill_ts,
                    "last_beat_ts": rec["aligned_ts"],
                },
            )
        )
        return findings

    # Partial silence: someone kept beating, someone stopped.
    newest = max(r["aligned_ts"] for r in last_beats.values())
    for replica, rec in sorted(last_beats.items()):
        silence = newest - rec["aligned_ts"]
        if silence > threshold:
            findings.append(
                Finding(
                    rule="heartbeat_silence",
                    severity="critical",
                    summary=(
                        f"replica {replica} silent for {silence:.1f}s "
                        f"while the gang kept beating (threshold "
                        f"{threshold:.1f}s = {SILENCE_FACTOR:g}x the "
                        f"{gap:.2f}s beat interval)"
                    ),
                    evidence=[_ev_status(rec, "progress")],
                    metrics={
                        "silence_s": silence,
                        "threshold_s": threshold,
                    },
                )
            )
    return findings


def detect_straggler(tl: Timeline) -> List[Finding]:
    per_replica: Dict[str, List[float]] = {}
    for replica, rs in tl.progress.items():
        vals = [
            float(r["step_time_ms"])
            for r in rs
            if r.get("step_time_ms") is not None
            and tl.in_window(r["aligned_ts"])
        ]
        if len(vals) >= STRAGGLER_MIN_SAMPLES:
            per_replica[replica] = vals
    if len(per_replica) < 2:
        return []
    p50s = {r: _median(v) for r, v in per_replica.items()}
    gang_p50 = _median(list(p50s.values()))
    worst, worst_p50 = max(p50s.items(), key=lambda kv: kv[1])
    if gang_p50 <= 0 or worst_p50 <= STRAGGLER_FACTOR * gang_p50:
        return []
    p99 = _quantile(per_replica[worst], 0.99)
    worst_rec = max(
        (r for r in tl.progress[worst] if r.get("step_time_ms") is not None),
        key=lambda r: float(r["step_time_ms"]),
    )
    evidence = [_ev_status(worst_rec, "progress")]
    if worst_rec.get("step") is not None:
        span = tl.find_step_span(worst, int(worst_rec["step"]))
        if span is not None:
            evidence.append(_ev_span(span))
    return [
        Finding(
            rule="straggler",
            severity="warning",
            summary=(
                f"replica {worst} straggles the gang: p50 step time "
                f"{worst_p50:.1f}ms vs gang {gang_p50:.1f}ms "
                f"({worst_p50 / gang_p50:.1f}x; its p99 {p99:.1f}ms)"
            ),
            evidence=evidence,
            metrics={
                "replica_p50_ms": worst_p50,
                "gang_p50_ms": gang_p50,
                "replica_p99_ms": p99,
                "spread": worst_p50 / gang_p50,
                "replicas": len(per_replica),
            },
        )
    ]


DETECTORS = (
    detect_heartbeat_silence,
    detect_step_time_regression,
    detect_feed_stall,
    detect_checkpoint_lag,
    detect_straggler,
)

_SEVERITY_ORDER = {"critical": 0, "warning": 1, "info": 2}


# ---- the engine ----


def analyze(
    state_dir,
    key: str,
    window_s: Optional[float] = None,
    now: Optional[float] = None,
) -> dict:
    """Run the full postmortem for one job; returns the report dict
    (``tpujob why --out`` writes it verbatim as JSON)."""
    import time as _time

    from ..controller.store import JobStore

    tl = build_timeline(state_dir, key, window_s=window_s)
    job = JobStore(persist_dir=Path(state_dir) / "jobs").get(key)
    phase = None
    restarts = 0
    if job is not None:
        restarts = job.status.restart_count
        for c in reversed(job.status.conditions):
            if c.status:
                phase = c.type.value
                break

    findings: List[Finding] = []
    for det in DETECTORS:
        findings.extend(det(tl))
    findings.sort(key=lambda f: _SEVERITY_ORDER.get(f.severity, 9))

    # Exemplar cross-links (when a daemon wrote metrics.prom): the p99
    # cell's latest span id per histogram, so the report can say WHICH
    # span landed the tail.
    exemplars: Dict[str, List[dict]] = {}
    prom = Path(state_dir) / "metrics.prom"
    if prom.exists():
        try:
            for name, rows in parse_exemplars(prom.read_text()).items():
                hits = [
                    {"le": labels.get("le", ""), "span_id": span_id,
                     "value": value}
                    for labels, span_id, value in rows
                    if labels.get("job") == key
                ]
                if hits:
                    exemplars[name] = hits
        except OSError:
            pass

    replicas = {
        replica: {
            "beats": len(rs),
            "first_ts": round(rs[0]["aligned_ts"], 6),
            "last_ts": round(rs[-1]["aligned_ts"], 6),
            "last_step": rs[-1].get("step"),
        }
        for replica, rs in sorted(tl.progress.items())
    }

    return {
        "job": key,
        "generated_at": _time.time() if now is None else now,
        "window_s": window_s,
        "phase": phase,
        "restarts": restarts,
        "clock": {r: est.to_dict() for r, est in sorted(tl.clock.items())},
        "replicas": replicas,
        "events": len(tl.events),
        "spans": len(tl.spans),
        "exemplars": exemplars,
        "findings": [f.to_dict() for f in findings],
    }


def _fmt_ev(ev: dict) -> str:
    src = ev.get("source")
    if src == "event":
        return (
            f"event  {ev.get('reason')} @ {ev.get('ts'):.3f}  "
            f"{ev.get('message', '')}"
        )
    if src == "span":
        args = ev.get("args") or {}
        blob = " ".join(f"{k}={v}" for k, v in sorted(args.items()))
        return (
            f"span   {ev.get('name')} @ {ev.get('ts'):.3f} "
            f"dur={ev.get('dur_ms'):.1f}ms {blob}".rstrip()
        )
    fields = " ".join(
        f"{k}={ev[k]}"
        for k in ("step", "step_time_ms", "feed_stall_ms", "queue_depth")
        if ev.get(k) is not None
    )
    return (
        f"status {ev.get('kind')} {ev.get('replica')} @ "
        f"{ev.get('ts'):.3f} {fields}".rstrip()
    )


def render_report(report: dict) -> str:
    """The terminal face of the report: findings first (most severe on
    top), each with its evidence; clock table after; '-' free prose
    kept short — the JSON carries the full detail."""
    lines: List[str] = []
    head = f"tpujob why {report['job']}"
    if report.get("phase"):
        head += f" — {report['phase']} (restarts={report['restarts']})"
    lines.append(head)
    reps = report.get("replicas", {})
    lines.append(
        f"analyzed: {sum(r['beats'] for r in reps.values())} heartbeats "
        f"from {len(reps)} replica(s), {report.get('events', 0)} events, "
        f"{report.get('spans', 0)} spans"
        + (
            f", window {report['window_s']:g}s"
            if report.get("window_s")
            else ""
        )
    )
    clock = report.get("clock", {})
    if clock:
        parts = [
            f"{r} {e['offset_s']:+.3f}s ±{e['residual_s']:.3f} (n={e['n']})"
            for r, e in clock.items()
        ]
        lines.append("clock:    " + "; ".join(parts))
    findings = report.get("findings", [])
    if not findings:
        lines.append("")
        lines.append("no findings — the recorded window looks healthy.")
        return "\n".join(lines)
    lines.append("")
    lines.append(f"FINDINGS ({len(findings)}):")
    for i, f in enumerate(findings, 1):
        lines.append(f"{i:3d}. [{f['severity']}] {f['rule']}: {f['summary']}")
        for ev in f.get("evidence", []):
            lines.append(f"       - {_fmt_ev(ev)}")
    return "\n".join(lines)
