"""Cross-layer flight recorder: span tracing + latency histograms.

The control and data planes got fast (PRs 2-3) but only offline bench
JSON proves it; this package makes the LIVE system debuggable. Two
primitives, deliberately tiny and import-light (the step loop and the
supervisor's per-job reconcile both touch them every iteration):

- :class:`~pytorch_operator_tpu.obs.metrics.Histogram` — fixed
  log-spaced buckets, Prometheus text exposition alongside the existing
  Counter/Gauge (controller/metrics.py registers them; ``/metrics``
  serves step-time, reconcile-pass, and checkpoint-commit
  distributions, not just point gauges).
- :class:`~pytorch_operator_tpu.obs.trace.SpanRecorder` — appends
  ``{name, cat, ts, dur, pid, tid, args}`` span records to a
  per-process JSONL ring file under ``$TPUJOB_TRACE_DIR``. The module
  helpers (:func:`span`, :func:`tracer`) are ZERO-overhead when the env
  knob is unset: one cached None check, a shared nullcontext, no I/O.

``tpujob trace <job>`` merges the supervisor's and every replica's span
files into one Chrome-trace/Perfetto JSON (:func:`merge_trace_files`),
clock-aligning cross-host files via the heartbeat-matched offset
estimator (obs/clock.py); ``tpujob top`` renders the live fleet table
from ``/metrics`` + progress heartbeats (obs/top.py); ``tpujob why``
runs the offline postmortem — causal timeline + anomaly detectors —
over the recorded artifacts (obs/analyze.py).
"""

from .metrics import (
    DEFAULT_BUCKETS,
    Histogram,
    histogram_quantile,
    parse_exemplars,
    parse_prometheus_text,
)
from .trace import (
    SERVE_CAT,
    SpanRecorder,
    instant,
    load_span_file,
    merge_trace_files,
    records_emitted,
    reset_tracer,
    serve_span,
    span,
    trace_enabled,
    tracer,
)

__all__ = [
    "DEFAULT_BUCKETS",
    "Histogram",
    "SERVE_CAT",
    "SpanRecorder",
    "histogram_quantile",
    "instant",
    "load_span_file",
    "merge_trace_files",
    "parse_exemplars",
    "parse_prometheus_text",
    "records_emitted",
    "reset_tracer",
    "serve_span",
    "span",
    "trace_enabled",
    "tracer",
]
