"""Span recording to per-process JSONL ring files + Chrome-trace merge.

Write side: :class:`SpanRecorder` appends one JSON object per span —
``{"name", "cat", "ph": "X", "ts", "dur", "pid", "tid", "args"}`` with
``ts``/``dur`` in microseconds (the Chrome trace event format, so the
merged output loads in Perfetto / ``chrome://tracing`` unmodified) — to
``$TPUJOB_TRACE_DIR/<proc>-<pid>.trace.jsonl``. The file is a ring:
past ``max_bytes`` it rotates once (``.1`` generation kept, older
dropped), so a week-long daemon cannot fill the disk with spans.

Enablement is the ``TPUJOB_TRACE_DIR`` env knob, injected per replica
by runtime/env.py and read once per process: with it unset,
:func:`tracer` caches None and :func:`span` returns a shared
nullcontext — no I/O, no allocation, one attribute check. The
``bench_smoke`` lane pins that a tracing-disabled step loop emits ZERO
span records.

Timestamps are ``time.time()`` (wall clock — all replicas of a local
world share it, and it is the same clock the progress heartbeats carry,
so a future multi-host merger can align skewed hosts by matching each
replica's heartbeat ``ts`` against the supervisor's fold time). Each
file opens with a ``clock_sync`` metadata record carrying both the wall
clock and ``perf_counter`` so sub-ms skew is reconstructable.

Read side: :func:`load_span_file` skips torn/foreign lines (a
SIGKILLed writer tears its last line — the ring-file tests pin that the
merger survives it); :func:`merge_trace_files` folds many span files
into one ``{"traceEvents": [...]}`` document.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import threading
import time
from pathlib import Path
from typing import Dict, Iterable, List, Optional

ENV_VAR = "TPUJOB_TRACE_DIR"

# Ring size per generation; two generations (current + .1) are kept.
# Overridable per process via TPUJOB_TRACE_RING_BYTES — threaded from
# spec.observability.trace_ring_bytes by runtime/env.py (a long soak
# run wants deeper rings; a tiny CI world wants smaller ones).
DEFAULT_MAX_BYTES = 8 << 20
RING_BYTES_ENV = "TPUJOB_TRACE_RING_BYTES"

# Flush cadence: buffered records are cheap to lose only if a crash
# tears them anyway; every FLUSH_EVERY records the buffer hits disk so
# a live `tpujob trace` sees near-current spans. Overridable via
# TPUJOB_TRACE_FLUSH_EVERY (spec.observability.trace_flush_every).
FLUSH_EVERY = 32
FLUSH_EVERY_ENV = "TPUJOB_TRACE_FLUSH_EVERY"


def _env_int(name: str, default: int) -> int:
    """A positive int env override, or the default (malformed or
    non-positive values must never break span recording)."""
    raw = os.environ.get(name, "")
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v > 0 else default

_NULL = contextlib.nullcontext()

# Process-global recorder, resolved lazily from the env once.
_TRACER: Optional["SpanRecorder"] = None
_RESOLVED = False
_LOCK = threading.Lock()

# Total span records emitted by this process (across recorders) — the
# bench_smoke "zero step-path spans when disabled" pin reads this.
_RECORDS = 0


def _default_process_name() -> str:
    rtype = os.environ.get("TPUJOB_REPLICA_TYPE")
    if rtype:
        idx = os.environ.get("TPUJOB_REPLICA_INDEX", "0")
        return f"{rtype.lower()}-{idx}"
    return "supervisor"


def tracer() -> Optional["SpanRecorder"]:
    """The process recorder, or None when ``TPUJOB_TRACE_DIR`` is unset
    or empty. Resolved once; :func:`reset_tracer` re-reads (tests)."""
    global _TRACER, _RESOLVED
    if _RESOLVED:
        return _TRACER
    with _LOCK:
        if not _RESOLVED:
            d = os.environ.get(ENV_VAR, "")
            _TRACER = (
                SpanRecorder(
                    d,
                    _default_process_name(),
                    max_bytes=_env_int(RING_BYTES_ENV, DEFAULT_MAX_BYTES),
                    flush_every=_env_int(FLUSH_EVERY_ENV, FLUSH_EVERY),
                )
                if d
                else None
            )
            _RESOLVED = True
    return _TRACER


def trace_enabled() -> bool:
    return tracer() is not None


def reset_tracer() -> None:
    """Close and forget the process recorder so the next :func:`tracer`
    call re-reads the env — tests and the CLI's ``--trace`` flag (which
    sets the env after import time) use this."""
    global _TRACER, _RESOLVED
    with _LOCK:
        if _TRACER is not None:
            _TRACER.close()
        _TRACER, _RESOLVED = None, False


def span(name: str, cat: str = "span", **args):
    """Context manager recording one complete span — THE call sites
    sprinkle through the stack. Disabled: returns a shared nullcontext
    (no allocation)."""
    rec = tracer()
    if rec is None:
        return _NULL
    return rec.span(name, cat, **args)


def instant(name: str, cat: str = "span", **args) -> None:
    """Zero-duration marker event (restarts, kills, fault injections)."""
    rec = tracer()
    if rec is not None:
        rec.emit(name, cat, time.time(), 0.0, **args)


def records_emitted() -> int:
    """Span records emitted by this process so far (0 when disabled —
    the zero-overhead invariant the bench_smoke lane asserts)."""
    return _RECORDS


# Category for every serve-path request hop (enqueue → claim →
# dispatch → ring/spool transit → slot wait → decode → respond →
# publish). One cat so `tpujob trace --request` and the why TTFT
# attribution can select the request waterfall without a name list.
SERVE_CAT = "serve"


def serve_span(name: str, ts: float, dur_s: float, **args) -> None:
    """One serve-path hop span with EXPLICIT endpoints.

    The request path measures hops with its own clocks (a queue wait
    starts at the client's submit wall time, a ring transit at the
    sender's stamp), so the context-manager form can't express them.
    Disabled: one cached-None check, nothing else — the serve-path
    zero-overhead pin counts on call sites computing their args only
    after checking :func:`tracer` themselves, or tolerating the cost
    of a few float subtractions.
    """
    rec = tracer()
    if rec is not None:
        rec.emit(name, SERVE_CAT, ts, dur_s, **args)


class SpanRecorder:
    """Appends span records to one per-process JSONL ring file.

    Lock-cheap by construction: the JSON line is formatted OUTSIDE the
    lock; inside it there is an append + a size check, with a real
    ``flush()`` only every :data:`FLUSH_EVERY` records (plus close).
    A crash can therefore tear the buffered tail — the merge side
    (:func:`load_span_file`) skips torn lines by contract.
    """

    def __init__(
        self,
        trace_dir,
        process_name: Optional[str] = None,
        max_bytes: int = DEFAULT_MAX_BYTES,
        flush_every: int = FLUSH_EVERY,
    ):
        self.trace_dir = Path(trace_dir)
        self.trace_dir.mkdir(parents=True, exist_ok=True)
        self.process_name = process_name or _default_process_name()
        self.pid = os.getpid()
        self.path = self.trace_dir / f"{self.process_name}-{self.pid}.trace.jsonl"
        self.max_bytes = max_bytes
        self.flush_every = max(1, flush_every)
        self.records = 0
        self._lock = threading.Lock()
        self._f = open(self.path, "ab")
        self._since_flush = 0
        self._write_header()
        # Normal process exit flushes the buffered tail; a SIGKILL tears
        # it, which the merge side tolerates by contract.
        atexit.register(self.close)

    def _write_header(self) -> None:
        # Metadata the merger turns into Perfetto process names, plus
        # the clock-sync pair for (future) cross-host alignment.
        meta = [
            {
                "ph": "M",
                "name": "process_name",
                "pid": self.pid,
                "tid": 0,
                "args": {"name": self.process_name},
            },
            {
                "ph": "M",
                "name": "clock_sync",
                "pid": self.pid,
                "tid": 0,
                "args": {
                    "unix_ts": time.time(),
                    "perf_counter": time.perf_counter(),
                    "job": os.environ.get("TPUJOB_KEY", ""),
                },
            },
        ]
        with self._lock:
            for m in meta:
                self._f.write(json.dumps(m).encode() + b"\n")
            self._f.flush()

    def emit(
        self, name: str, cat: str, ts: float, dur_s: float, **args
    ) -> None:
        """Record one complete span; ``ts`` is wall-clock seconds of the
        span START, ``dur_s`` its duration."""
        global _RECORDS
        rec = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": round(ts * 1e6, 1),
            "dur": round(dur_s * 1e6, 1),
            "pid": self.pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
        }
        if args:
            rec["args"] = args
        line = json.dumps(rec).encode() + b"\n"
        with self._lock:
            if self._f.closed:
                return
            self._maybe_rotate(len(line))
            self._f.write(line)
            self.records += 1
            _RECORDS += 1
            self._since_flush += 1
            if self._since_flush >= self.flush_every:
                self._f.flush()
                self._since_flush = 0

    def _maybe_rotate(self, incoming: int) -> None:
        """Ring rotation under the held lock: current generation moves
        to ``.1`` (replacing the previous one), a fresh file starts."""
        try:
            if self._f.tell() + incoming <= self.max_bytes:
                return
            self._f.flush()
            self._f.close()
            self.path.replace(self.path.with_suffix(".jsonl.1"))
            self._f = open(self.path, "ab")
        except OSError:
            # A full disk must never take the traced process down.
            if self._f.closed:
                self._f = open(os.devnull, "ab")
        # Re-emit the header so the new generation is self-describing.
        for m in (
            {
                "ph": "M",
                "name": "process_name",
                "pid": self.pid,
                "tid": 0,
                "args": {"name": self.process_name},
            },
        ):
            self._f.write(json.dumps(m).encode() + b"\n")

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "span", **args):
        t_wall = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.emit(name, cat, t_wall, time.perf_counter() - t0, **args)

    def flush(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._since_flush = 0

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()


# ---- merge / export ----


def load_span_file(path) -> List[dict]:
    """Parse one span JSONL file into event dicts. Torn last lines
    (crashed writer), foreign lines, and records missing the required
    Chrome-trace fields are skipped — the trace dir is written by live
    processes and read after kills."""
    out: List[dict] = []
    try:
        data = Path(path).read_bytes()
    except OSError:
        return out
    for line in data.splitlines():
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn or foreign
        if not isinstance(rec, dict) or "ph" not in rec or "name" not in rec:
            continue
        if rec["ph"] == "X" and ("ts" not in rec or "dur" not in rec):
            continue
        out.append(rec)
    return out


def span_files(trace_dir, include_rotated: bool = True) -> List[Path]:
    """The span files (current + rotated generations) directly under
    ``trace_dir``, stable order."""
    d = Path(trace_dir)
    if not d.is_dir():
        return []
    pats = ["*.trace.jsonl"] + (["*.trace.jsonl.1"] if include_rotated else [])
    return sorted(p for pat in pats for p in d.glob(pat))


def merge_trace_files(paths: Iterable, clock_offsets: Optional[Dict] = None) -> dict:
    """Fold span files into one Chrome-trace JSON document.

    ``clock_offsets`` maps path -> seconds to ADD to that file's
    timestamps — the cross-host alignment hook, now fed by the
    heartbeat-matching estimator (obs/clock.py:estimate_job_offsets via
    ``tpujob trace``/``tpujob why``; local worlds share a clock so the
    default is 0 everywhere). Each corrected file gets a
    ``clock_sync_correction`` metadata record naming the applied offset
    so a merged trace is self-describing about its own alignment.
    Events are sorted by ts; metadata records keep their file order.
    The result loads directly in Perfetto (https://ui.perfetto.dev) or
    chrome://tracing."""
    meta: List[dict] = []
    events: List[dict] = []
    for p in paths:
        off_s = (clock_offsets or {}).get(p, 0.0)
        off_us = 1e6 * off_s
        file_pid = None
        for rec in load_span_file(p):
            if file_pid is None:
                file_pid = rec.get("pid", 0)
            if rec.get("ph") == "M":
                if rec not in meta:
                    meta.append(rec)
            else:
                if off_us:
                    rec = dict(rec)
                    rec["ts"] = rec.get("ts", 0) + off_us
                events.append(rec)
        if off_us:
            meta.append(
                {
                    "ph": "M",
                    "name": "clock_sync_correction",
                    "pid": file_pid or 0,
                    "tid": 0,
                    "args": {
                        "file": os.path.basename(str(p)),
                        "offset_s": round(off_s, 6),
                    },
                }
            )
    events.sort(key=lambda r: r.get("ts", 0))
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}
