"""Histogram metric type + Prometheus text helpers.

The existing Counter/Gauge (controller/metrics.py) answer "how many"
and "how much right now"; at scale the interesting failures live in
tail latencies neither can see (the TPU-pod concurrency study,
PAPERS.md). :class:`Histogram` adds distributions with FIXED log-spaced
buckets — fixed so that two scrapes, two supervisors, or two runs are
always mergeable (dynamic buckets are not), log-spaced because latency
is multiplicative (a 63ms and a 70ms pass are the same story; 63ms vs
630ms is the story).

Exposition follows the Prometheus text format contract the conformance
tests pin: cumulative ``_bucket`` series with ``le`` labels, the
``+Inf`` bucket equal to ``_count``, and ``_sum``; label escaping is
shared with the Counter/Gauge ``_fmt_labels`` so a queue name with a
quote in it cannot invalidate one metric family but not another.

:func:`parse_prometheus_text` / :func:`histogram_quantile` are the read
side — ``tpujob top`` turns a scraped ``/metrics`` (or the daemon's
``metrics.prom`` file) back into p50/p99 columns.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Tuple

from ..controller.metrics import _fmt_labels

# Default bucket boundaries (seconds): ~log-spaced 1-2.5-5 per decade,
# 100 microseconds to 100 s — wide enough for a store persist (sub-ms)
# and a cold rendezvous join (tens of seconds) on one fixed grid.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005,
    0.001, 0.0025, 0.005,
    0.01, 0.025, 0.05,
    0.1, 0.25, 0.5,
    1.0, 2.5, 5.0,
    10.0, 25.0, 50.0, 100.0,
)


def _fmt_le(bound: float) -> str:
    """Prometheus renders +Inf literally; finite bounds as shortest repr."""
    if bound == float("inf"):
        return "+Inf"
    return f"{bound:g}"


class Histogram:
    """A labeled histogram with fixed log-spaced buckets.

    ``observe(value, **labels)`` is the hot-path call: one lock, one
    bisect, three adds — cheap enough for per-reconcile and per-persist
    observation with no sampling. Series (label sets) are created on
    first observation, like Counter/Gauge.
    """

    def __init__(
        self,
        name: str,
        help_text: str = "",
        buckets: Optional[Tuple[float, ...]] = None,
    ):
        self.name = name
        self.help = help_text
        bs = tuple(sorted(buckets if buckets is not None else DEFAULT_BUCKETS))
        if any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])):
            raise ValueError(f"histogram buckets must strictly increase: {bs}")
        self.buckets = bs
        # key -> [per-bucket counts (+1 overflow slot for +Inf), sum, count]
        self._series: Dict[tuple, list] = {}
        self._lock = threading.Lock()

    def observe(
        self, value: float, exemplar: Optional[str] = None, **labels: str
    ) -> None:
        key = tuple(sorted(labels.items()))
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                # [per-bucket counts (+Inf slot), sum, count, exemplars]
                s = [[0] * (len(self.buckets) + 1), 0.0, 0, None]
                self._series[key] = s
            s[0][idx] += 1
            s[1] += value
            s[2] += 1
            if exemplar is not None:
                # Latest span id per bucket (OpenMetrics exemplars): the
                # jump-off point from a histogram cell to the exact
                # trace span that landed in it.
                if s[3] is None:
                    s[3] = [None] * (len(self.buckets) + 1)
                s[3][idx] = (str(exemplar), value)

    def drop_series(self, label: str, value: str) -> int:
        """Retire every series carrying ``label == value`` (metric
        lifecycle: a deleted job's per-job series must not live in the
        registry forever). Returns the count dropped."""
        pair = (label, str(value))
        with self._lock:
            doomed = [k for k in self._series if pair in k]
            for k in doomed:
                del self._series[k]
        return len(doomed)

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def exemplars(self, **labels: str) -> Dict[str, Tuple[str, float]]:
        """``{le: (span_id, observed_value)}`` for one series — the
        latest exemplar recorded per bucket (buckets without one are
        absent)."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            s = self._series.get(key)
            ex = None if s is None else s[3]
            ex = list(ex) if ex else []
        out: Dict[str, Tuple[str, float]] = {}
        bounds = self.buckets + (float("inf"),)
        for bound, e in zip(bounds, ex):
            if e is not None:
                out[_fmt_le(bound)] = e
        return out

    def count(self, **labels: str) -> int:
        key = tuple(sorted(labels.items()))
        with self._lock:
            s = self._series.get(key)
            return 0 if s is None else s[2]

    def sum(self, **labels: str) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            s = self._series.get(key)
            return 0.0 if s is None else s[1]

    def quantile(self, q: float, **labels: str) -> Optional[float]:
        """Bucket-interpolated quantile (the promQL histogram_quantile
        estimate) for live rendering; None with no observations."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            s = self._series.get(key)
            if s is None or s[2] == 0:
                return None
            counts = list(s[0])
        cum: List[Tuple[float, int]] = []
        total = 0
        for bound, c in zip(self.buckets + (float("inf"),), counts):
            total += c
            cum.append((bound, total))
        return histogram_quantile(cum, q)

    def render(self) -> str:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} histogram")
        with self._lock:
            series = {
                k: ([*v[0]], v[1], v[2], list(v[3]) if v[3] else None)
                for k, v in self._series.items()
            }
        for key, (counts, total_sum, total_count, exemplars) in sorted(
            series.items()
        ):
            base = _fmt_labels(key)
            cum = 0
            for i, (bound, c) in enumerate(
                zip(self.buckets + (float("inf"),), counts)
            ):
                cum += c
                le = _fmt_labels((("le", _fmt_le(bound)),))
                labels = f"{base},{le}" if base else le
                line = f"{self.name}_bucket{{{labels}}} {cum}"
                ex = exemplars[i] if exemplars else None
                if ex is not None:
                    # OpenMetrics exemplar suffix: the latest span that
                    # landed in THIS bucket (not cumulative), so a p99
                    # cell links to a concrete trace span.
                    eid, val = ex
                    line += f' # {{{_fmt_labels((("span_id", eid),))}}} {val:g}'
                lines.append(line)
            brace = f"{{{base}}}" if base else ""
            lines.append(f"{self.name}_sum{brace} {total_sum:g}")
            lines.append(f"{self.name}_count{brace} {total_count}")
        if not series:
            # Family present (HELP/TYPE) but no series yet — same idle
            # shape as Counter/Gauge, minus a fake zero sample (an empty
            # histogram has no meaningful le grid to fabricate).
            pass
        return "\n".join(lines)


def histogram_quantile(
    cumulative: List[Tuple[float, int]], q: float
) -> Optional[float]:
    """PromQL-style quantile from cumulative ``(le_bound, cum_count)``
    pairs (the last bound may be +Inf). Linear interpolation within the
    winning bucket; values in the +Inf bucket clamp to the last finite
    bound (Prometheus's behavior)."""
    if not cumulative:
        return None
    total = cumulative[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_bound, prev_cum = 0.0, 0
    for bound, cum in cumulative:
        if cum >= rank:
            if bound == float("inf"):
                return prev_bound
            if cum == prev_cum:
                return bound
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_bound + frac * (bound - prev_bound)
        prev_bound, prev_cum = (0.0 if bound == float("inf") else bound), cum
    return prev_bound


def parse_prometheus_text(text: str) -> Dict[str, List[Tuple[dict, float]]]:
    """Parse Prometheus text exposition into
    ``{metric_name: [(labels_dict, value), ...]}`` — the read side of
    ``render_text`` that ``tpujob top`` uses on ``metrics.prom`` or a
    scraped ``/metrics`` body. Tolerant: unparseable lines are skipped
    (the file may be mid-rewrite when read)."""
    out: Dict[str, List[Tuple[dict, float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # OpenMetrics exemplar suffix (` # {span_id="..."} value`) is
        # metadata, not part of the sample — strip it here so exemplared
        # bucket lines parse identically to plain ones
        # (:func:`parse_exemplars` is the suffix's read side).
        line = line.split(" # ", 1)[0].rstrip()
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                label_blob, value_part = rest.rsplit("}", 1)
                labels = _parse_labels(label_blob)
                value = float(value_part.strip())
            else:
                name, value_part = line.rsplit(None, 1)
                labels = {}
                value = float(value_part)
        except ValueError:
            continue
        out.setdefault(name.strip(), []).append((labels, value))
    return out


def parse_exemplars(
    text: str,
) -> Dict[str, List[Tuple[dict, str, float]]]:
    """The exemplar read side of :func:`Histogram.render`:
    ``{metric_name: [(labels, span_id, observed_value), ...]}`` for
    every exposition line carrying an OpenMetrics exemplar suffix.
    Tolerant like :func:`parse_prometheus_text` — a malformed suffix
    just yields no exemplar for that line."""
    out: Dict[str, List[Tuple[dict, str, float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or " # " not in line:
            continue
        sample, suffix = line.split(" # ", 1)
        try:
            name = sample.split("{", 1)[0].strip()
            labels = (
                _parse_labels(sample.split("{", 1)[1].rsplit("}", 1)[0])
                if "{" in sample
                else {}
            )
            ex_blob, ex_value = suffix.rsplit("}", 1)
            ex_labels = _parse_labels(ex_blob.lstrip().lstrip("{"))
            span_id = ex_labels.get("span_id", "")
            value = float(ex_value.strip())
        except (ValueError, IndexError):
            continue
        if span_id:
            out.setdefault(name, []).append((labels, span_id, value))
    return out


def _parse_labels(blob: str) -> dict:
    """Inverse of ``_fmt_labels`` (quoted, escaped label values)."""
    labels: dict = {}
    i, n = 0, len(blob)
    while i < n:
        eq = blob.index("=", i)
        key = blob[i:eq].strip().lstrip(",").strip()
        if blob[eq + 1] != '"':
            raise ValueError(f"unquoted label value in {blob!r}")
        j = eq + 2
        val = []
        while j < n:
            ch = blob[j]
            if ch == "\\" and j + 1 < n:
                nxt = blob[j + 1]
                val.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                j += 2
                continue
            if ch == '"':
                break
            val.append(ch)
            j += 1
        labels[key] = "".join(val)
        i = j + 1
    return labels
