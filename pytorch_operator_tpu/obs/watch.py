"""The live health engine — streaming detector rules + alert lifecycle.

The postmortem engine (obs/analyze.py) can explain a dead job; this
module notices the dying one. Production pretraining treats degradation
as routine and detects it ONLINE (TorchTitan), and per-host stragglers
dominate tail behavior long before they become failures (MLPerf on
TPU-v3 pods) — so the same detector rules ``tpujob why`` runs offline
(obs/rules.py) are evaluated incrementally inside the supervisor's
steady phase, over telemetry the per-pass gauge fold ALREADY tailed:

- :meth:`WatchEngine.observe` ingests the newest per-replica records
  straight from :meth:`ProgressTailer.replica_latest` poll state —
  zero extra file I/O, ever (the bench_smoke lane pins zero alert-log
  appends and zero store reads/writes on an idle healthy pass);
- :meth:`WatchEngine.evaluate` runs the shared rule pass over a
  bounded rolling window per job (:class:`LiveWindow` — the live
  :class:`~pytorch_operator_tpu.obs.rules.TimelineView`);
- findings feed an alert LIFECYCLE with hysteresis: ``pending`` while
  younger than ``for_s`` (a one-pass blip never pages), ``firing``
  after, ``resolved`` once the finding has been absent ``clear_s``
  seconds — deduplicated by (job, rule, replica);
- every firing/resolved TRANSITION is appended to a per-job alert log
  (``<state>/alerts/<ns>_<job>/alerts.jsonl`` — an artifact root, so
  ``delete --purge`` reclaims it and ``tpujob why`` cites it after a
  death); steady states write nothing;
- the fleet view exports as ``tpujob_alerts{job,rule,severity}``
  gauges, the ``/alerts`` monitoring route, the ``tpujob alerts``
  verb, and the ALERTS column in ``tpujob top``.

Cross-job correlation (:meth:`WatchEngine.correlate`, end of each
pass): simultaneous step-time regressions across jobs sharing this
host raise ``noisy_neighbor`` alerts attributing the regression to the
host rather than the jobs.

Per-job tuning comes from ``spec.observability.alerts`` (api/types:
``enabled`` / ``for_s`` / ``clear_s`` / ``thresholds``), resolved the
same way ``tpujob why`` resolves it offline — one bar, two engines.
"""

from __future__ import annotations

import json
import socket
import time
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Deque, Dict, Iterable, List, Optional, Tuple

from .rules import (
    DEFAULT_THRESHOLDS,
    Finding,
    SEVERITY_ORDER,
    Thresholds,
    correlate_noisy_neighbor,
    run_detectors,
    thresholds_from_overrides,
)

# Subdirectory of the supervisor state dir holding per-job alert logs
# (a sibling of jobs/, status/, events/, clock/ — and an ARTIFACT_ROOT,
# so `delete --purge` sweeps it).
ALERTS_DIR = "alerts"

# Rolling-window bounds: enough history for every rule's minimum sample
# counts with headroom, small enough that a pass over N jobs stays
# O(N * constant). The live regression baseline is therefore the last
# ~WINDOW_BEATS observed beats, not all time — a week-long drift shows
# up offline in `tpujob why`, which reads the full recording.
WINDOW_BEATS = 240
WINDOW_RECORDS = 64

# Lifecycle defaults (spec.observability.alerts overrides per job).
# for_s=0 fires on first detection — the rules already embed their own
# persistence (minimum sample counts, silence thresholds), so by the
# time a rule matches, the condition has lasted; jobs that want calmer
# paging raise it. clear_s keeps a flapping signal from resolving and
# re-firing every other pass.
DEFAULT_FOR_S = 0.0
DEFAULT_CLEAR_S = 5.0

# Alert-log size cap, rotated once like the clock log: lifecycle
# transitions are rare, but a pathological flapper must not fill a disk.
LOG_MAX_BYTES = 1 << 20


def job_alert_log(state_dir, key: str) -> Path:
    """THE per-job alert-log path (write and read side agree)."""
    from ..controller.store import key_to_fs

    return Path(state_dir) / ALERTS_DIR / key_to_fs(key) / "alerts.jsonl"


def load_alert_log(state_dir, key: str) -> List[dict]:
    """Parse one job's alert log (rotated generation included), oldest
    first. Torn/foreign lines skipped — appended by a live daemon, read
    after kills, like every recorded artifact."""
    p = job_alert_log(state_dir, key)
    out: List[dict] = []
    for gen in (p.with_suffix(".jsonl.1"), p):
        try:
            data = gen.read_bytes()
        except OSError:
            continue
        for line in data.splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                float(rec.get("ts", 0.0))
            except (ValueError, TypeError, AttributeError):
                continue
            if not isinstance(rec, dict) or "rule" not in rec:
                continue
            out.append(rec)
    return out


def fold_alert_log(records: Iterable[dict]) -> List[dict]:
    """Collapse a transition log to the LATEST state per (rule,
    replica) — the "what is the situation now" view a daemon-less CLI
    reconstructs from disk. Sorted most-severe-first, firing before
    resolved."""
    cur: Dict[Tuple[str, str], dict] = {}
    for rec in records:
        cur[(str(rec.get("rule")), str(rec.get("replica") or "*"))] = rec
    return sorted(
        cur.values(),
        key=lambda r: (
            r.get("state") != "firing",
            SEVERITY_ORDER.get(r.get("severity", ""), 9),
            r.get("rule", ""),
        ),
    )


def list_alert_jobs(state_dir) -> List[str]:
    """Job keys with an alert log on disk (the `tpujob alerts` fleet
    scan)."""
    from ..controller.store import fs_to_key

    root = Path(state_dir) / ALERTS_DIR
    if not root.is_dir():
        return []
    return sorted(
        fs_to_key(d.name)
        for d in root.iterdir()
        if d.is_dir()
        and (
            (d / "alerts.jsonl").exists()
            or (d / "alerts.jsonl.1").exists()
        )
    )


# ---- the live TimelineView ----


class LiveWindow:
    """The rules' read surface over one job's rolling window. Same
    duck-typed protocol as obs/analyze.Timeline; timestamps are raw
    replica send times on the supervisor's frame-of-reference pass
    (``aligned_ts == ts`` — the live engine trades clock alignment for
    zero latency; the offline engine re-judges with alignment)."""

    window_s: Optional[float] = None

    def __init__(
        self,
        progress: Dict[str, List[dict]],
        records: Dict[str, List[dict]],
        events: Iterable,
        now: float,
    ):
        self.progress = progress
        self.records = records
        self.events = events
        self.now = now

    def all_progress(self) -> List[dict]:
        out = [r for rs in self.progress.values() for r in rs]
        out.sort(key=lambda r: r["aligned_ts"])
        return out

    def in_window(self, ts: float) -> bool:
        return True

    def beat_interval(self) -> float:
        gaps: List[float] = []
        for rs in self.progress.values():
            for a, b in zip(rs, rs[1:]):
                gaps.append(b["aligned_ts"] - a["aligned_ts"])
        gaps.sort()
        n = len(gaps)
        if n == 0:
            return 0.0
        return gaps[n // 2] if n % 2 else 0.5 * (gaps[n // 2 - 1] + gaps[n // 2])

    def silence_reference(self) -> float:
        """Live silence is judged against the supervisor's wall clock —
        a hung single-replica job has nobody else to compare against,
        and the whole point is alerting BEFORE the deadline kill."""
        return self.now

    def find_event(self, *reasons: str) -> Optional[dict]:
        for e in self.events:
            r = e.get("reason") if isinstance(e, dict) else getattr(e, "reason", None)
            if r in reasons:
                if isinstance(e, dict):
                    return e
                return {
                    "reason": e.reason,
                    "type": e.type,
                    "timestamp": e.timestamp,
                    "message": e.message,
                }
        return None

    def find_step_span(self, replica: str, step: int) -> Optional[dict]:
        return None  # spans are an offline artifact


# ---- alerts ----


@dataclass
class Alert:
    """One lifecycle instance: created pending at first detection,
    firing after ``for_s``, resolved after ``clear_s`` of absence (or
    at job finish). Dedup key is (job, rule, replica) — a re-detection
    after resolve starts a NEW instance."""

    job: str
    rule: str
    replica: str  # "*" when the rule is not replica-specific
    severity: str
    state: str  # pending | firing | resolved
    since: float  # first detection
    last_seen: float
    summary: str
    evidence: List[dict] = field(default_factory=list)
    metrics: Dict[str, float] = field(default_factory=dict)
    fired_at: Optional[float] = None
    resolved_at: Optional[float] = None

    def to_dict(self) -> dict:
        d = {
            "job": self.job,
            "rule": self.rule,
            "replica": self.replica,
            "severity": self.severity,
            "state": self.state,
            "since": round(self.since, 6),
            "last_seen": round(self.last_seen, 6),
            "summary": self.summary,
            "metrics": {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in self.metrics.items()
            },
        }
        if self.fired_at is not None:
            d["fired_at"] = round(self.fired_at, 6)
        if self.resolved_at is not None:
            d["resolved_at"] = round(self.resolved_at, 6)
        return d


class WatchIOCounters:
    """Watch-side I/O accounting, snapshot like StoreIOCounters — the
    bench_smoke lane pins ``log_appends`` at zero across idle healthy
    passes (the engine must stay write-free when nothing transitions)."""

    __slots__ = ("log_appends", "evaluations")

    def __init__(self) -> None:
        self.log_appends = 0
        self.evaluations = 0

    def snapshot(self) -> dict:
        return {
            "log_appends": self.log_appends,
            "evaluations": self.evaluations,
        }


class _JobWatch:
    """Per-job rolling state: bounded sample windows, dedup watermarks,
    and the live alert instances."""

    __slots__ = ("progress", "records", "seen", "alerts", "cfg")

    def __init__(self) -> None:
        self.progress: Dict[str, Deque[dict]] = {}
        self.records: Dict[str, Deque[dict]] = {}
        self.seen: Dict[Tuple[str, str], float] = {}
        self.alerts: Dict[Tuple[str, str], Alert] = {}
        # (enabled, for_s, clear_s, thresholds) as of the last evaluate
        # — correlate() runs after the per-job pass and reuses it.
        self.cfg: Tuple[bool, float, float, Thresholds] = (
            True, DEFAULT_FOR_S, DEFAULT_CLEAR_S, DEFAULT_THRESHOLDS,
        )


# The record kinds the live window accumulates (a subset of
# progress.TAILED_KINDS — clock_probe is the estimator's, not a rule's).
_WATCHED_KINDS = ("progress", "checkpoint_committed", "serve")


class WatchEngine:
    """The supervisor-resident streaming evaluator. One instance per
    supervisor; all methods are called from the sync pass (single
    logical writer — the steady phase parallelizes RECONCILES, the
    gauge fold that feeds this stays on the pass thread)."""

    def __init__(self, state_dir, host: Optional[str] = None):
        self.state_dir = Path(state_dir)
        self.host = host or socket.gethostname()
        self._jobs: Dict[str, _JobWatch] = {}
        # job -> its step_time_regression finding this pass (the
        # noisy-neighbor correlation input).
        self._regressing: Dict[str, Finding] = {}
        self.io = WatchIOCounters()

    # ---- ingest ----

    def observe(self, key: str, by_replica: Dict[str, dict]) -> None:
        """Fold the newest per-replica records (the dict
        :meth:`ProgressTailer.replica_latest` returns — already-polled
        state, zero I/O) into the job's rolling window. A record is
        ingested once, by its ``ts`` watermark; a job with no telemetry
        never allocates state (idle fleets stay O(0) here)."""
        if not by_replica:
            return
        jw = None
        for replica, kinds in by_replica.items():
            for kind in _WATCHED_KINDS:
                rec = kinds.get(kind)
                if rec is None:
                    continue
                if jw is None:
                    jw = self._jobs.get(key)
                    if jw is None:
                        jw = self._jobs[key] = _JobWatch()
                wm = jw.seen.get((replica, kind))
                if wm is not None and rec["ts"] <= wm:
                    continue
                jw.seen[(replica, kind)] = rec["ts"]
                self._ingest(jw, replica, kind, rec)

    def ingest_record(self, key: str, replica: str, kind: str, rec: dict) -> None:
        """Feed one raw status record (replay/tests — and the
        offline-vs-live parity contract: replaying a recorded timeline
        through here must reproduce ``tpujob why``'s findings)."""
        if kind not in _WATCHED_KINDS:
            return
        jw = self._jobs.get(key)
        if jw is None:
            jw = self._jobs[key] = _JobWatch()
        self._ingest(jw, replica, kind, rec)

    @staticmethod
    def _ingest(jw: _JobWatch, replica: str, kind: str, rec: dict) -> None:
        r = dict(rec)
        r["replica"] = replica
        r.setdefault("aligned_ts", float(r.get("ts", 0.0)))
        if kind == "progress":
            win = jw.progress.get(replica)
            if win is None:
                win = jw.progress[replica] = deque(maxlen=WINDOW_BEATS)
            win.append(r)
        else:
            win = jw.records.get(kind)
            if win is None:
                win = jw.records[kind] = deque(maxlen=WINDOW_RECORDS)
            win.append(r)

    def tracked(self, key: str) -> bool:
        """Cheap pre-check so the supervisor skips evaluation (and the
        per-job event-list copy) for jobs that never reported."""
        return key in self._jobs

    # ---- evaluate ----

    @staticmethod
    def _resolve_cfg(job) -> Tuple[bool, float, float, Thresholds]:
        if job is not None:
            ob = job.spec.observability
            if ob is not None and ob.alerts is not None:
                al = ob.alerts
                return (
                    al.enabled,
                    float(al.for_s),
                    float(al.clear_s),
                    thresholds_from_overrides(al.thresholds),
                )
        return (True, DEFAULT_FOR_S, DEFAULT_CLEAR_S, DEFAULT_THRESHOLDS)

    def evaluate(
        self,
        key: str,
        job=None,
        events: Iterable = (),
        now: Optional[float] = None,
    ) -> List[Alert]:
        """Run the shared rule pass over the job's window and step the
        alert lifecycle. Returns the job's live (pending|firing)
        alerts. Pure compute plus at most one log append per
        transition; an unchanged healthy job costs rule evaluation over
        its bounded window and zero I/O."""
        jw = self._jobs.get(key)
        if jw is None:
            return []
        now = time.time() if now is None else now
        enabled, for_s, clear_s, th = self._resolve_cfg(job)
        jw.cfg = (enabled, for_s, clear_s, th)
        if not enabled:
            # Alerting turned off mid-flight: resolve what's firing so
            # the surfaces don't show frozen alerts forever.
            self._step(jw, key, {}, now, for_s, 0.0, _per_job_rule)
            self._regressing.pop(key, None)
            return []
        view = LiveWindow(
            progress={r: list(d) for r, d in jw.progress.items()},
            records={k: list(d) for k, d in jw.records.items()},
            events=events,
            now=now,
        )
        findings = run_detectors(view, th)
        self.io.evaluations += 1
        reg = next(
            (f for f in findings if f.rule == "step_time_regression"), None
        )
        if reg is not None:
            self._regressing[key] = reg
        else:
            self._regressing.pop(key, None)
        keyed: Dict[Tuple[str, str], Finding] = {}
        for f in findings:
            keyed.setdefault((f.rule, f.replica or "*"), f)
        return self._step(jw, key, keyed, now, for_s, clear_s, _per_job_rule)

    def correlate(self, now: Optional[float] = None) -> None:
        """End-of-pass cross-job rule: simultaneous regressions on this
        host become ``noisy_neighbor`` alerts (per affected job, with
        that job's lifecycle config)."""
        now = time.time() if now is None else now
        findings = correlate_noisy_neighbor(self._regressing, self.host)
        for key, jw in self._jobs.items():
            f = findings.get(key)
            enabled, for_s, clear_s, _ = jw.cfg
            keyed = (
                {(f.rule, "*"): f} if f is not None and enabled else {}
            )
            self._step(jw, key, keyed, now, for_s, clear_s, _cross_job_rule)

    def _step(
        self,
        jw: _JobWatch,
        key: str,
        findings: Dict[Tuple[str, str], Finding],
        now: float,
        for_s: float,
        clear_s: float,
        in_scope,
    ) -> List[Alert]:
        """One lifecycle step over the alerts whose rule ``in_scope``
        covers: pending→firing after ``for_s`` of persistence,
        firing→resolved after ``clear_s`` of absence, pending dropped
        on the first miss (the condition must hold continuously to
        fire). Transitions append to the job's log; steady states
        don't."""
        for k, f in findings.items():
            a = jw.alerts.get(k)
            if a is None:
                a = Alert(
                    job=key,
                    rule=f.rule,
                    replica=k[1],
                    severity=f.severity,
                    state="pending",
                    since=now,
                    last_seen=now,
                    summary=f.summary,
                    evidence=f.evidence,
                    metrics=f.metrics,
                )
                jw.alerts[k] = a
            else:
                a.last_seen = now
                a.summary = f.summary
                a.evidence = f.evidence
                a.metrics = f.metrics
                a.severity = f.severity
            if a.state == "pending" and now - a.since >= for_s:
                a.state = "firing"
                a.fired_at = now
                self._append(key, a, now)
        for k, a in list(jw.alerts.items()):
            if k in findings or not in_scope(a.rule):
                continue
            if a.state == "pending":
                del jw.alerts[k]
            elif a.state == "firing" and now - a.last_seen >= clear_s:
                a.state = "resolved"
                a.resolved_at = now
                self._append(key, a, now)
                del jw.alerts[k]
        return [a for a in jw.alerts.values()]

    # ---- lifecycle edges ----

    def finalize(self, key: str, now: Optional[float] = None) -> None:
        """The job finished: resolve anything still firing (logged — a
        postmortem must see the alert CLOSED by the death, not left
        dangling) and drop the rolling state. Idempotent."""
        jw = self._jobs.pop(key, None)
        self._regressing.pop(key, None)
        if jw is None:
            return
        now = time.time() if now is None else now
        for a in jw.alerts.values():
            if a.state == "firing":
                a.state = "resolved"
                a.resolved_at = now
                a.summary += " (job finished)"
                self._append(key, a, now)

    def retire_job(self, key: str) -> None:
        """The job was DELETED: drop state without logging — the alert
        log on disk stays as the postmortem surface unless the delete
        purged artifacts."""
        self._jobs.pop(key, None)
        self._regressing.pop(key, None)

    def _append(self, key: str, a: Alert, now: float) -> None:
        rec = {
            "ts": round(now, 6),
            "state": a.state,
            "job": key,
            "rule": a.rule,
            "replica": a.replica,
            "severity": a.severity,
            "summary": a.summary,
            "since": round(a.since, 6),
        }
        if a.state == "firing":
            rec["evidence"] = a.evidence
            rec["metrics"] = {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in a.metrics.items()
            }
        line = (json.dumps(rec) + "\n").encode()
        path = job_alert_log(self.state_dir, key)
        try:
            try:
                if path.stat().st_size + len(line) > LOG_MAX_BYTES:
                    path.replace(path.with_suffix(".jsonl.1"))
            except OSError:
                pass
            path.parent.mkdir(parents=True, exist_ok=True)
            with path.open("ab") as f:
                f.write(line)
            self.io.log_appends += 1
        except OSError:
            pass  # best-effort, like the event sink

    # ---- read surfaces ----

    def active_alerts(self, key: Optional[str] = None) -> List[Alert]:
        """Live pending/firing alerts, firing first then most severe."""
        out: List[Alert] = []
        if key is not None:
            jw = self._jobs.get(key)
            if jw is not None:
                out = list(jw.alerts.values())
        else:
            for jw in self._jobs.values():
                out.extend(jw.alerts.values())
        out.sort(
            key=lambda a: (
                a.state != "firing",
                SEVERITY_ORDER.get(a.severity, 9),
                a.job,
                a.rule,
                a.replica,
            )
        )
        return out

    def export_gauge(self, gauge) -> None:
        """Rebuild ``tpujob_alerts{job,rule,severity}`` from the live
        state (cleared per pass like the other per-job gauges — a
        resolved alert's series must not linger)."""
        gauge.clear()
        counts: Dict[Tuple[str, str, str], int] = {}
        for a in self.active_alerts():
            if a.state != "firing":
                continue
            k = (a.job, a.rule, a.severity)
            counts[k] = counts.get(k, 0) + 1
        for (job, rule, severity), n in counts.items():
            gauge.set(n, job=job, rule=rule, severity=severity)

    def render_text(self, now: Optional[float] = None) -> str:
        """The ``/alerts`` monitoring route body."""
        now = time.time() if now is None else now
        alerts = self.active_alerts()
        firing = sum(1 for a in alerts if a.state == "firing")
        lines = [
            f"alerts: {firing} firing, {len(alerts) - firing} pending "
            f"(host {self.host})"
        ]
        rows = [("STATE", "AGE", "JOB", "RULE", "REPLICA", "SEV", "SUMMARY")]
        for a in alerts:
            rows.append(
                (
                    a.state,
                    f"{max(now - a.since, 0.0):.0f}s",
                    a.job,
                    a.rule,
                    a.replica,
                    a.severity,
                    a.summary,
                )
            )
        widths = [max(len(r[i]) for r in rows) for i in range(6)]
        for r in rows:
            lead = "  ".join(c.ljust(w) for c, w in zip(r[:6], widths))
            lines.append(f"{lead}  {r[6]}".rstrip())
        if not alerts:
            lines.append("(no active alerts)")
        return "\n".join(lines)


def _per_job_rule(rule: str) -> bool:
    return rule != "noisy_neighbor"


def _cross_job_rule(rule: str) -> bool:
    return rule == "noisy_neighbor"


# ---- CLI-side (daemon-less) rendering from the on-disk logs ----


def format_alert_record(rec: dict, now: Optional[float] = None) -> str:
    """One transition record as a human line (`tpujob alerts [-f]`)."""
    who = rec.get("replica") or "*"
    return (
        f"[{rec.get('state', '?')}] {rec.get('severity', '?')} "
        f"{rec.get('rule', '?')} {rec.get('job', '?')}/{who}: "
        f"{rec.get('summary', '')}"
    )


def gather_alert_rows(
    state_dir, key: Optional[str] = None, now: Optional[float] = None
) -> List[dict]:
    """Current alert state per (job, rule, replica) folded from the
    on-disk logs — works with or without a daemon, like `tpujob top`."""
    keys = [key] if key is not None else list_alert_jobs(state_dir)
    rows: List[dict] = []
    for k in keys:
        rows.extend(fold_alert_log(load_alert_log(state_dir, k)))
    rows.sort(
        key=lambda r: (
            r.get("state") != "firing",
            SEVERITY_ORDER.get(r.get("severity", ""), 9),
            r.get("job", ""),
            r.get("rule", ""),
        )
    )
    return rows


def render_alert_table(rows: List[dict], now: Optional[float] = None) -> str:
    """The `tpujob alerts` table (current state per job/rule/replica)."""
    now = time.time() if now is None else now
    table = [("AGE", "STATE", "JOB", "RULE", "REPLICA", "SEV", "SUMMARY")]
    for r in rows:
        age = max(now - float(r.get("ts", now)), 0.0)
        table.append(
            (
                f"{age:.0f}s",
                str(r.get("state", "?")),
                str(r.get("job", "?")),
                str(r.get("rule", "?")),
                str(r.get("replica") or "*"),
                str(r.get("severity", "?")),
                str(r.get("summary", "")),
            )
        )
    if len(table) == 1:
        return "no alerts"
    widths = [max(len(r[i]) for r in table) for i in range(6)]
    return "\n".join(
        "  ".join(c.ljust(w) for c, w in zip(r[:6], widths)) + f"  {r[6]}"
        for r in table
    ).rstrip()
