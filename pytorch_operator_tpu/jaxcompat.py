"""jax version-compat seam.

The parallel/ops stack is written against the current jax surface
(``jax.shard_map`` with ``axis_names``/``check_vma``, ``jax.typeof``
with vma-annotated avals). Deployments pinning an older jax (this
image ships 0.4.x, where shard_map lives in ``jax.experimental`` and
speaks ``auto``/``check_rep``) must still run the same code — one
wrapper owns the translation so call sites stay written against the
NEW API and this file is the only thing to delete when the floor
moves.
"""

from __future__ import annotations


def shard_map(
    f,
    *,
    mesh,
    in_specs,
    out_specs,
    axis_names=None,
    check_vma=None,
):
    """``jax.shard_map`` when available, else the experimental one with
    the kwargs translated:

    - ``axis_names`` (the set of MANUAL mesh axes) becomes the old
      ``auto`` complement (every other mesh axis stays automatic);
    - ``check_vma`` becomes ``check_rep`` (same replication check,
      renamed when the vma machinery landed).
    """
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return sm(f, **kwargs)
    from jax.experimental.shard_map import shard_map as legacy

    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - set(axis_names)
        if auto:
            # TRUE partial-manual (some axes left automatic) trips an
            # XLA SPMD-partitioner CHECK on this jaxlib (manual-subgroup
            # mismatch) — a process ABORT at compile, not an exception.
            # Refuse cleanly at trace instead; full-manual regions
            # (axis_names covering the whole mesh) are fine.
            raise NotImplementedError(
                f"partial-manual shard_map (auto axes {sorted(auto)}) "
                "miscompiles on this jax version; use a mesh whose axes "
                "are all manual here, or a newer jax"
            )
    # The legacy replication checker predates vma casts: code written to
    # satisfy the vma type system (pcast-ing scan carries to varying) is
    # identity under this jax, so the old checker rejects exactly the
    # carries the casts exist to bless. Default it OFF here — numerics
    # are pinned by tests, not by the advisory checker — unless the
    # caller asked explicitly.
    kwargs["check_rep"] = False if check_vma is None else check_vma
    return legacy(f, **kwargs)


def pcast_varying(x, axis):
    """``jax.lax.pcast(x, (axis,), to="varying")`` on jax versions with
    vma typing; identity on older jax, where manual-region types carry
    no varying-axis annotation and carry-type stability needs no cast."""
    import jax

    if not hasattr(jax.lax, "pcast"):
        return x
    return jax.lax.pcast(x, (axis,), to="varying")


def axis_size(axis):
    """``jax.lax.axis_size`` when available; on older jax,
    ``psum(1, axis)`` — special-cased there to return the static axis
    size as a Python int, so perm-list builders stay static either way.
    Call inside a manual region (shard_map) only."""
    import jax

    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis)
    return jax.lax.psum(1, axis)
