"""Small CNN for digit classification.

Reference analog: the Net in ``examples/mnist/mnist.py`` (conv-conv-fc-fc;
SURVEY.md §2 "Example: mnist") — re-designed as a flax module that is
shape-agnostic (works on 8×8 sklearn digits and 28×28 MNIST alike) and
bfloat16-friendly for the MXU.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class DigitCNN(nn.Module):
    """conv32-conv64-pool-dense128-dense10, NHWC."""

    num_classes: int = 10
    dtype: Any = jnp.float32  # compute dtype; params stay f32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x
