"""Import PyTorch (HuggingFace-layout) Llama weights into the flax model.

The migration story's missing half: `api/convert.py` carries a user's
PyTorchJob MANIFESTS over; this carries their trained WEIGHTS. A
state_dict using the HF ``LlamaForCausalLM`` naming scheme
(``model.layers.N.self_attn.q_proj.weight`` …) maps 1:1 onto this
package's flax tree — torch ``Linear`` stores ``[out, in]`` so kernels
transpose, attention projections reshape into the (heads, head_dim)
DenseGeneral layout, and per-layer tensors stack into the
``nn.scan``-stacked ``[n_layers, ...]`` arrays.

RoPE convention note: this package's ``apply_rope`` uses the rotate-half
convention — the same one HF's modeling_llama applies — so projections
import WITHOUT the permutation needed when converting from Meta's
original interleaved checkpoints. The equivalence test
(tests/test_llama_import.py) runs a real torch reference forward and
asserts logits match.

Accepts either live ``torch.Tensor`` values or numpy arrays, so packed
state_dicts can be imported without torch installed.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch.Tensor
        # Real HF checkpoints ship bf16, which numpy can't represent —
        # widen on the torch side first.
        t = t.detach().float().cpu().numpy()
    return np.asarray(t)


def import_hf_llama_state_dict(sd: Dict[str, Any], cfg) -> dict:
    """HF-layout state_dict → this package's flax ``params`` tree
    (unboxed numpy arrays, ready for ``jax.device_put`` /
    ``model.apply({"params": ...})``)."""
    if cfg.n_experts > 0:
        raise NotImplementedError(
            "HF import for MoE configs is not implemented (dense Llama only)"
        )
    L = cfg.n_layers
    H, K, D, hd = cfg.n_heads, cfg.n_kv_heads, cfg.d_model, cfg.head_dim

    def take(name, shape):
        if name not in sd:
            raise KeyError(f"state_dict missing {name!r}")
        a = _np(sd[name]).astype(np.float32)
        if tuple(a.shape) != tuple(shape):
            raise ValueError(
                f"{name}: expected shape {tuple(shape)}, got {tuple(a.shape)}"
            )
        return a

    def stack(fmt, shape):
        return np.stack([take(fmt.format(i), shape) for i in range(L)])

    # torch Linear [out, in] → flax kernel [in, out].
    def lin(fmt, out_dim, in_dim):
        return stack(fmt, (out_dim, in_dim)).transpose(0, 2, 1)

    params = {
        "embed": {
            "embedding": take("model.embed_tokens.weight", (cfg.vocab_size, D))
        },
        "layers": {
            "attn_norm": {
                "scale": stack("model.layers.{}.input_layernorm.weight", (D,))
            },
            "attn": {
                "q_proj": {
                    "kernel": lin(
                        "model.layers.{}.self_attn.q_proj.weight", H * hd, D
                    ).reshape(L, D, H, hd)
                },
                "k_proj": {
                    "kernel": lin(
                        "model.layers.{}.self_attn.k_proj.weight", K * hd, D
                    ).reshape(L, D, K, hd)
                },
                "v_proj": {
                    "kernel": lin(
                        "model.layers.{}.self_attn.v_proj.weight", K * hd, D
                    ).reshape(L, D, K, hd)
                },
                "o_proj": {
                    "kernel": lin(
                        "model.layers.{}.self_attn.o_proj.weight", D, H * hd
                    )
                },
            },
            "mlp_norm": {
                "scale": stack(
                    "model.layers.{}.post_attention_layernorm.weight", (D,)
                )
            },
            "mlp": {
                "gate_proj": {
                    "kernel": lin("model.layers.{}.mlp.gate_proj.weight", cfg.d_ff, D)
                },
                "up_proj": {
                    "kernel": lin("model.layers.{}.mlp.up_proj.weight", cfg.d_ff, D)
                },
                "down_proj": {
                    "kernel": lin("model.layers.{}.mlp.down_proj.weight", D, cfg.d_ff)
                },
            },
        },
        "final_norm": {"scale": take("model.norm.weight", (D,))},
        "lm_head": {
            # tie_word_embeddings checkpoints (e.g. Llama-3.2-1B/3B)
            # omit lm_head.weight — the head is the embedding table.
            "kernel": take(
                "lm_head.weight"
                if "lm_head.weight" in sd
                else "model.embed_tokens.weight",
                (cfg.vocab_size, D),
            ).T.copy()
        },
    }
    return params


def export_hf_llama_state_dict(params, cfg) -> Dict[str, np.ndarray]:
    """The inverse of :func:`import_hf_llama_state_dict`: this package's
    flax ``params`` tree (boxed or not) → an HF-layout state_dict of
    numpy f32 arrays, so a model trained here can be handed back to a
    PyTorch/HF stack. Round-trip is exact (tests/test_llama_import.py).
    """
    if cfg.n_experts > 0:
        raise NotImplementedError(
            "HF export for MoE configs is not implemented (dense Llama only)"
        )

    def unbox(tree):
        leaves = {}
        for k, v in tree.items():
            if isinstance(v, dict):
                leaves[k] = unbox(v)
            else:
                leaves[k] = _np(v.unbox() if hasattr(v, "unbox") else v)
        return leaves

    p = unbox(params)
    L = cfg.n_layers
    H, K, D, hd = cfg.n_heads, cfg.n_kv_heads, cfg.d_model, cfg.head_dim

    sd: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": p["embed"]["embedding"],
        "model.norm.weight": p["final_norm"]["scale"],
        "lm_head.weight": p["lm_head"]["kernel"].T,
    }
    lay = p["layers"]
    for i in range(L):
        pre = f"model.layers.{i}."
        sd[pre + "input_layernorm.weight"] = lay["attn_norm"]["scale"][i]
        sd[pre + "post_attention_layernorm.weight"] = lay["mlp_norm"]["scale"][i]
        # flax kernel [D, h, hd] → torch Linear [h*hd, D].
        sd[pre + "self_attn.q_proj.weight"] = (
            lay["attn"]["q_proj"]["kernel"][i].reshape(D, H * hd).T
        )
        sd[pre + "self_attn.k_proj.weight"] = (
            lay["attn"]["k_proj"]["kernel"][i].reshape(D, K * hd).T
        )
        sd[pre + "self_attn.v_proj.weight"] = (
            lay["attn"]["v_proj"]["kernel"][i].reshape(D, K * hd).T
        )
        sd[pre + "self_attn.o_proj.weight"] = lay["attn"]["o_proj"]["kernel"][i].T
        sd[pre + "mlp.gate_proj.weight"] = lay["mlp"]["gate_proj"]["kernel"][i].T
        sd[pre + "mlp.up_proj.weight"] = lay["mlp"]["up_proj"]["kernel"][i].T
        sd[pre + "mlp.down_proj.weight"] = lay["mlp"]["down_proj"]["kernel"][i].T
    # np.array (not asarray): exactly ONE cast+copy per tensor, producing
    # WRITABLE contiguous buffers — views over JAX-backed arrays are
    # read-only (torch.from_numpy warns, in-place fine-tune writes would
    # be UB) and would alias the source flax tree.
    return {k: np.array(v, np.float32) for k, v in sd.items()}
