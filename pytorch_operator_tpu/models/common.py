"""Helpers shared across model families."""

from __future__ import annotations


def remat_policy(cfg):
    """Resolve ``cfg.remat_policy`` to a jax.checkpoint policy (None =
    save nothing beyond block boundaries, i.e. full remat). Duck-typed:
    any config with a ``remat_policy`` field (LlamaConfig, ViTConfig).

    ``"dots"`` saves outputs of batch-dim-free dot_generals — the
    projection and MLP GEMMs — so backward recomputes only the cheap
    elementwise/norm work (and attention, whose score einsums carry
    batch dims; the flash kernel recomputes internally regardless).
    Measured +8.5% on the 0.3b LM and +12% on ViT-B vs full remat
    (BASELINE.md round-3 sweep).
    """
    import jax

    if cfg.remat_policy == "full":
        return None
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(
        f"remat_policy={cfg.remat_policy!r} not in ('full', 'dots')"
    )
