"""Vision Transformer (ViT) for image classification.

Reference analog: none in-tree (the reference's model zoo lives in user
containers — SURVEY.md §2); this extends the rebuild's model families
(ResNet, BERT, Llama) with the standard ViT architecture (patchify →
transformer encoder → classification head), which maps onto the TPU far
better than convnets: the whole network is large matmuls for the MXU,
with none of ResNet's batch-norm HBM reduce traffic.

TPU-first choices:
- patch embedding as one strided conv (= a single matmul per patch grid
  on the MXU), NHWC layout;
- bf16 compute / f32 params, LayerNorm statistics in f32;
- encoder blocks under ``lax.scan`` (one compiled block × depth) with
  the same logical-axis annotations the LM stack uses ("embed", "heads",
  "mlp"), so dp/fsdp/tp meshes shard it with the existing rule table;
- optional pallas flash attention (``attn_impl="flash"``) for large
  token counts; the 196-token ImageNet grid stays dense (S << the
  flash crossover).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from .common import remat_policy as _remat_policy

Dtype = Any


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    d_model: int = 768
    depth: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    attn_impl: str = "dense"  # "dense" | "flash"
    # Rematerialize each encoder block in backward (jax.checkpoint under
    # the layer scan, like LlamaConfig.remat): trades ~1/3 more FLOPs for
    # O(depth) activation memory -> larger batches fit (the round-2 ViT-B
    # bench was batch-capped at 64 by activation HBM; VERDICT r2 Weak #2).
    remat: bool = False
    # Remat policy when remat=True — same semantics as
    # LlamaConfig.remat_policy: "full" saves only block boundaries;
    # "dots" saves batch-dim-free GEMM outputs so backward skips
    # recomputing the MXU-bound work (+8% on the 0.3b LM, BASELINE.md).
    remat_policy: str = "full"

    @property
    def grid(self) -> int:
        if self.image_size % self.patch_size:
            raise ValueError(
                f"image {self.image_size} not divisible by patch {self.patch_size}"
            )
        return self.image_size // self.patch_size

    @property
    def seq_len(self) -> int:
        return self.grid * self.grid + 1  # + [CLS]


def vit_s16(**over) -> ViTConfig:
    return ViTConfig(**{"d_model": 384, "depth": 12, "n_heads": 6, "d_ff": 1536, **over})


def vit_b16(**over) -> ViTConfig:
    return ViTConfig(**over)


def vit_l16(**over) -> ViTConfig:
    return ViTConfig(
        **{"d_model": 1024, "depth": 24, "n_heads": 16, "d_ff": 4096, **over}
    )


BY_NAME = {"s16": vit_s16, "b16": vit_b16, "l16": vit_l16}


class EncoderBlock(nn.Module):
    """Pre-norm transformer encoder block (bidirectional attention)."""

    cfg: ViTConfig

    @nn.compact
    def __call__(self, x, _=None):
        cfg = self.cfg
        B, S, D = x.shape
        H = cfg.n_heads
        hd = D // H

        y = nn.LayerNorm(dtype=cfg.dtype, name="attn_norm")(x)
        qkv_init = nn.with_logical_partitioning(
            nn.initializers.xavier_uniform(), ("embed", "heads", "head_dim")
        )
        q = nn.DenseGeneral((H, hd), dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                            kernel_init=qkv_init, name="q_proj")(y)
        k = nn.DenseGeneral((H, hd), dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                            kernel_init=qkv_init, name="k_proj")(y)
        v = nn.DenseGeneral((H, hd), dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                            kernel_init=qkv_init, name="v_proj")(y)
        if cfg.attn_impl == "flash":
            from ..ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, causal=False)
        else:
            s = jnp.einsum(
                "bshd,bthd->bhst", q, k, preferred_element_type=jnp.float32
            ) / jnp.sqrt(hd).astype(jnp.float32)
            p = jax.nn.softmax(s, axis=-1).astype(cfg.dtype)
            out = jnp.einsum("bhst,bthd->bshd", p, v)
        out = nn.DenseGeneral(
            D, axis=(-2, -1), dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.xavier_uniform(), ("heads", "head_dim", "embed")
            ),
            name="o_proj",
        )(out)
        x = x + out

        y = nn.LayerNorm(dtype=cfg.dtype, name="mlp_norm")(x)
        y = nn.Dense(
            cfg.d_ff, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.xavier_uniform(), ("embed", "mlp")
            ),
            name="up_proj",
        )(y)
        y = nn.gelu(y)
        y = nn.Dense(
            D, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.xavier_uniform(), ("mlp", "embed")
            ),
            name="down_proj",
        )(y)
        return x + y, None


class ViT(nn.Module):
    """images [B, H, W, 3] → logits [B, num_classes].

    Deliberately regularizer-free (no dropout knob): the benchmark/test
    configs never use one, and a config field no code reads would be a
    silent no-op trap.
    """

    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B = x.shape[0]
        x = x.astype(cfg.dtype)
        # Patchify: one strided conv = a matmul over the patch grid.
        x = nn.Conv(
            cfg.d_model,
            (cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            padding="VALID",
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.xavier_uniform(), (None, None, None, "embed")
            ),
            name="patch_embed",
        )(x)
        x = x.reshape(B, -1, cfg.d_model)  # [B, grid², D]

        cls = self.param(
            "cls",
            nn.with_logical_partitioning(
                nn.initializers.zeros_init(), (None, None, "embed")
            ),
            (1, 1, cfg.d_model),
            cfg.param_dtype,
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(cfg.dtype), (B, 1, cfg.d_model)), x],
            axis=1,
        )
        pos = self.param(
            "pos_embed",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), (None, "seq", "embed")
            ),
            (1, cfg.seq_len, cfg.d_model),
            cfg.param_dtype,
        )
        x = x + pos.astype(cfg.dtype)

        block = EncoderBlock
        if cfg.remat:
            block = nn.remat(
                EncoderBlock, prevent_cse=False, policy=_remat_policy(cfg)
            )
        ScanBlocks = nn.scan(
            block,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            length=cfg.depth,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        x, _ = ScanBlocks(cfg, name="layers")(x, None)

        x = nn.LayerNorm(dtype=cfg.dtype, name="final_norm")(x)
        x = x[:, 0]  # [CLS]
        return nn.Dense(
            cfg.num_classes,
            dtype=jnp.float32,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("embed", None)
            ),
            name="head",
        )(x)
