"""BERT-style bidirectional encoder, TPU-first.

Reference analog: the BERT-base FSDP fine-tune PyTorchJob config
(BASELINE.json:9) — as with every model here, the reference keeps the model
in user containers; this is a from-scratch flax implementation of the
original BERT architecture (learned positions, post-LayerNorm, GELU MLP,
pooler over [CLS]) with a classification head for fine-tuning and an MLM
head for pretraining-style objectives.

TPU-first choices mirror models/llama.py: logical-axis-annotated params
(fsdp/tp portable across meshes), scan over layers, bf16 compute / f32
params, static shapes. The padding mask is an input, not dynamic shapes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30_522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_len: int = 512
    type_vocab: int = 2
    ln_eps: float = 1e-12
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def bert_base(**over) -> BertConfig:
    return BertConfig(**over)


def bert_tiny(**over) -> BertConfig:
    base = dict(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        max_len=64, dtype=jnp.float32,
    )
    base.update(over)
    return BertConfig(**base)


def _dense(cfg, features, axes, name):
    return nn.DenseGeneral(
        features,
        axis=-1,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.normal(stddev=0.02), axes
        ),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), axes[1:]
        ),
        name=name,
    )


class SelfAttention(nn.Module):
    """Bidirectional multi-head attention with a padding mask."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, x, pad_mask):
        cfg = self.cfg
        B, S, _ = x.shape
        H, D = cfg.n_heads, cfg.head_dim
        qkv_axes = ("embed", "heads", "head_dim")
        q = _dense(cfg, (H, D), qkv_axes, "q_proj")(x)
        k = _dense(cfg, (H, D), qkv_axes, "k_proj")(x)
        v = _dense(cfg, (H, D), qkv_axes, "v_proj")(x)

        scores = jnp.einsum(
            "bshd,bthd->bhst", q, k, preferred_element_type=jnp.float32
        ) / jnp.sqrt(D).astype(jnp.float32)
        if pad_mask is not None:
            # pad_mask [B,S]: True = real token. Mask out attending TO pads.
            scores = jnp.where(
                pad_mask[:, None, None, :], scores, jnp.finfo(jnp.float32).min
            )
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhst,bthd->bshd", probs, v).reshape(B, S, H * D)
        out = nn.with_logical_constraint(out, ("batch", "seq", None))
        return nn.DenseGeneral(
            cfg.d_model, axis=-1, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("heads", "embed")
            ),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("embed",)
            ),
            name="o_proj",
        )(out)


class EncoderLayer(nn.Module):
    """Post-LN transformer encoder layer (original BERT residual order)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, carry, _):
        x, pad_mask = carry
        cfg = self.cfg
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=cfg.ln_eps, dtype=jnp.float32, param_dtype=cfg.param_dtype,
            scale_init=nn.with_logical_partitioning(
                nn.initializers.ones_init(), ("norm",)
            ),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("norm",)
            ),
            name=name,
        )
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        x = ln("attn_ln")(x + SelfAttention(cfg, name="attn")(x, pad_mask))
        x = x.astype(cfg.dtype)
        h = _dense(cfg, cfg.d_ff, ("embed", "mlp"), "mlp_up")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.with_logical_constraint(h, ("batch", "seq", "mlp"))
        h = _dense(cfg, cfg.d_model, ("mlp", "embed"), "mlp_down")(h)
        x = ln("mlp_ln")(x + h).astype(cfg.dtype)
        return (x, pad_mask), None


class Bert(nn.Module):
    """Encoder backbone: tokens [B,S] (+ optional type ids, padding mask)
    → (sequence_output [B,S,d], pooled [B,d])."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, tokens, type_ids=None, pad_mask=None):
        cfg = self.cfg
        B, S = tokens.shape
        emb = lambda n, v, axes, name: nn.Embed(  # noqa: E731
            n, cfg.d_model, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), axes
            ),
            name=name,
        )(v)
        x = emb(cfg.vocab_size, tokens, ("vocab", "embed"), "word_embed")
        x = x + emb(
            cfg.max_len,
            jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S)),
            (None, "embed"),
            "pos_embed",
        )
        if type_ids is not None:
            x = x + emb(cfg.type_vocab, type_ids, (None, "embed"), "type_embed")
        x = nn.LayerNorm(
            epsilon=cfg.ln_eps, dtype=jnp.float32, param_dtype=cfg.param_dtype,
            name="embed_ln",
        )(x).astype(cfg.dtype)

        layer = EncoderLayer
        if cfg.remat:
            layer = nn.remat(EncoderLayer, prevent_cse=False)
        ScanLayers = nn.scan(
            layer,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            length=cfg.n_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        (x, _), _ = ScanLayers(cfg, name="layers")((x, pad_mask), None)

        # Square kernels annotate only the input dim — a repeated "embed"
        # would map both dims onto the same mesh axis (invalid PartitionSpec).
        pooled = nn.tanh(
            _dense(cfg, cfg.d_model, ("embed", None), "pooler")(x[:, 0])
        )
        return x, pooled


class BertClassifier(nn.Module):
    """Backbone + classification head — the fine-tune surface
    (BASELINE.json:9 workload)."""

    cfg: BertConfig
    num_classes: int

    @nn.compact
    def __call__(self, tokens, type_ids=None, pad_mask=None):
        _, pooled = Bert(self.cfg, name="bert")(tokens, type_ids, pad_mask)
        return nn.DenseGeneral(
            self.num_classes, dtype=jnp.float32, param_dtype=self.cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("embed", None)
            ),
            name="classifier",
        )(pooled)


class BertMLM(nn.Module):
    """Backbone + masked-LM head (tied-free, like the untied Llama head)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, tokens, type_ids=None, pad_mask=None):
        seq, _ = Bert(self.cfg, name="bert")(tokens, type_ids, pad_mask)
        h = _dense(self.cfg, self.cfg.d_model, ("embed", None), "mlm_transform")(seq)
        h = nn.gelu(h, approximate=True)
        h = nn.LayerNorm(
            epsilon=self.cfg.ln_eps, dtype=jnp.float32,
            param_dtype=self.cfg.param_dtype, name="mlm_ln",
        )(h)
        return nn.DenseGeneral(
            self.cfg.vocab_size, dtype=jnp.float32, param_dtype=self.cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("embed", "vocab")
            ),
            name="mlm_head",
        )(h)
