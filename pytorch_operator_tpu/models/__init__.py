"""Model zoo: JAX/flax workload models (MNIST CNN, ResNet, BERT, Llama).

Mirror of the model code inside the reference's example containers
(SURVEY.md §1 layer 7) plus the BASELINE.json:7-11 target workloads.
Import is lazy per-model — the control plane never pulls in jax/flax.
"""
