"""Llama-3-family decoder-only transformer, TPU-first.

Reference analog: the Llama-3-8B multi-host PyTorchJob config
(BASELINE.json:10) — the model itself lives in the reference's user
containers; this is a from-scratch flax implementation of the Llama-3
architecture (RMSNorm, rotary embeddings with the rotate-half convention,
SwiGLU MLP, grouped-query attention, untied LM head).

TPU-first choices:
- every parameter carries *logical* axis names (flax spmd metadata); the
  rule table in ``parallel/sharding.py`` maps them onto a dp×fsdp×tp(×sp)
  mesh and XLA inserts the collectives — no hand-written NCCL-style code.
- ``lax.scan`` over layers (one compiled block × n_layers) keeps compile
  time O(1) in depth; optional rematerialization trades FLOPs for HBM.
- bfloat16 activations / float32 params and softmax; static shapes; the
  causal mask is a compile-time constant.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from .common import remat_policy  # shared with ViT (models/common.py)

Dtype = Any

# Logical axis vocabulary (see parallel/sharding.py DEFAULT_RULES):
#   "vocab"   → tp      "embed" → fsdp     "heads"/"kv_heads"/"mlp" → tp
#   "batch"   → dp+fsdp "seq"   → sp       "layers" (scan axis) → unsharded
#   "head_dim"/"norm"   → replicated


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: int = 128
    d_ff: int = 14_336
    rope_theta: float = 500_000.0
    rms_eps: float = 1e-5
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    remat: bool = False  # checkpoint each block (jax.checkpoint under scan)
    # Remat policy when remat=True: "full" (save only block boundaries —
    # minimum HBM, recompute everything in backward) or "dots" (save the
    # outputs of non-batch matmuls via XLA's offloadable-names policy —
    # backward skips recomputing the big GEMMs at the price of holding
    # their outputs; the right trade when HBM has headroom, since the
    # recompute being avoided is exactly the MXU-bound work).
    remat_policy: str = "full"
    # Attention implementation: "dense" (materialized S×S scores), "flash"
    # (pallas blockwise kernel, O(S·D) HBM traffic — ops/flash_attention.py),
    # "ring" (sequence-parallel ring attention over the mesh's ``sp`` axis —
    # parallel/ring.py), "ulysses" (all-to-all seq↔head swap over ``sp`` —
    # parallel/ulysses.py; 2 collectives vs ring's P rotations, full-S
    # scores per local kv head, needs n_kv_heads % sp == 0). ring/ulysses
    # require passing the mesh to the model.
    attn_impl: str = "dense"
    # Loss implementation: "dense" ([B,S,V] logits then optax xent) or
    # "chunked" (fused head+loss over vocab chunks — ops/chunked_xent.py;
    # saves O(B·S·V) HBM, the dominant activation at V=128k).
    xent_impl: str = "dense"
    # Mixture-of-experts: n_experts > 0 replaces the dense SwiGLU MLP with
    # a top-k gated gelu MoE whose experts shard over the mesh's ``ep``
    # axis (parallel/moe.py); 0 = dense.
    n_experts: int = 0
    moe_top_k: int = 2
    # Expert dispatch: "dense" (every device runs its local experts over
    # all tokens — exact, no drops, FLOPs ∝ local experts) or "sparse"
    # (GShard capacity-factor dispatch — FLOPs ∝ top_k·capacity_factor,
    # over-capacity tokens dropped; measured 1.2-1.3x ideal vs dense's
    # 2.1-4.9x at E=8-32, BASELINE.md). Prefer "sparse" from E >= 16.
    moe_dispatch: str = "dense"
    moe_capacity_factor: float = 1.25
    # Switch-style load-balancing auxiliary loss weight (0 = off). With
    # top-k routing — and capacity-factor sparse dispatch especially,
    # which DROPS over-capacity tokens — an unregularized router
    # collapses onto a few experts; the standard weight is ~1e-2. Sown
    # into the "losses" collection per layer; make_lm_train_step adds
    # weight * mean(per-layer aux) to the objective.
    moe_aux_weight: float = 0.0
    # Autoregressive decoding: ``decode=True`` switches attention to a
    # KV-cache path (flax "cache" collection: cached_key/cached_value of
    # static length ``max_decode_len``, updated in place each step) —
    # prefill writes the whole prompt at once, decode steps append one
    # token. Static shapes throughout: the scores run against the full
    # cache with a position mask, so the decode step is ONE fixed XLA
    # program regardless of how much of the cache is filled.
    decode: bool = False
    max_decode_len: int = 2048
    # KV-cache quantization (decode only): "int8" stores cached_key/
    # cached_value as int8 with per-(token, kv-head) f32 scales
    # (amax/127 over head_dim), quantized at write time, dequantized
    # inside the attention einsums (the convert+scale fuses into the
    # dot's operand read — the cache is a scan CARRY, not a scan input,
    # so no materialization issue arises). Halves cache HBM: the lever
    # that fits long-context 8B serving on one chip next to the int8
    # weights (BASELINE.md round-4). Independent of ``quantize``.
    kv_quantize: Optional[str] = None
    # Per-row decode offsets (decode only): False keeps the batch-uniform
    # contract (every row at the same position; cache writes are ONE
    # dynamic_update_slice at positions[0,0] — the fastest write and the
    # right one for the single-stream generate loop). True switches the
    # cache write to per-row offsets (positions[:, 0] may differ per row
    # — a batched vmapped update-slice, i.e. a scatter), which is what a
    # continuous-batching serving engine needs: each row of the batch is
    # a DIFFERENT request at a different depth in its own stream. The
    # attention validity mask is per-row in BOTH modes (it reads the
    # full positions array; the uniform case is just the special case
    # where the rows agree).
    decode_per_row: bool = False
    # Multi-token decode inputs (S > 1): "self" = the whole prompt of a
    # FRESH cache (positions [0, S)) — causal self-attention over the
    # incoming tokens alone IS the full attention, so the flash kernel
    # applies and no [B,K,G,S,L] scores materialize. "cache" = a CHUNK
    # of a partially prefilled stream (positions [start, start+S)): the
    # chunk is written to the cache, then attends against the full cache
    # with the position-validity mask — intra-chunk causality and the
    # prefix both fall out of col <= row. Memory is O(S·L) scores, so
    # chunked prefill picks S (the chunk) to bound it; that bound is the
    # point (one-shot 8B long prompts exceed one program's activation
    # budget).
    prefill_mode: str = "self"
    # Weight-only quantization mode (inference): "int8" makes apply()
    # expect a params tree produced by ``ops.quantize.quantize_tree``
    # (QuantizedTensor leaves — int8 payload + per-channel scales).
    # Dequantization happens INSIDE each consuming module via
    # nn.map_variables — critically, inside the layer-scan body, so the
    # per-layer weights are dequantized AFTER the scan slices them and
    # the convert+scale fuses into each matmul's operand read. A
    # top-level tree dequant instead turns the stacked [L, ...] weights
    # into materialized full-precision scan inputs (measured 2.1x
    # SLOWER than the f32 control at 1b on the chip — the failure mode
    # this field exists to avoid). Plain-array trees still work in this
    # mode (dequant is identity), which is what the same-program
    # quantized-vs-full A/B in workloads/generate.py rides on.
    quantize: Optional[str] = None

    def __post_init__(self):
        if self.quantize not in (None, "int8"):
            # Fail at construction, matching the workload entry point —
            # any truthy value would otherwise silently run the int8
            # dequant hook.
            raise ValueError(
                f"quantize={self.quantize!r} not in (None, 'int8')"
            )
        if self.kv_quantize not in (None, "int8"):
            raise ValueError(
                f"kv_quantize={self.kv_quantize!r} not in (None, 'int8')"
            )
        if self.prefill_mode not in ("self", "cache"):
            raise ValueError(
                f"prefill_mode={self.prefill_mode!r} not in ('self', 'cache')"
            )
        if (self.decode_per_row or self.prefill_mode != "self") and not self.decode:
            raise ValueError(
                "decode_per_row / prefill_mode='cache' require decode=True"
            )
        if self.decode and self.attn_impl in ("ring", "ulysses"):
            # The decode prefill runs plain causal self-attention over
            # the incoming tokens (flash/dense); sequence-parallel
            # schemes don't compose with the KV-cache write layout.
            raise ValueError(
                f"attn_impl={self.attn_impl!r} is not supported with "
                "decode=True (prefill uses flash/dense self-attention)"
            )
        if (
            self.n_experts > 0
            and self.moe_dispatch == "sparse"
            and not self.moe_aux_weight
        ):
            # Capacity-factor dispatch DROPS over-capacity tokens, so an
            # unregularized router collapsing onto a few experts (the
            # moe_aux_weight docstring's failure mode) also silently
            # drops most of the batch — warn at construction, where every
            # entry path (workload flags, library use, import) passes.
            import warnings

            warnings.warn(
                "moe_dispatch='sparse' with moe_aux_weight=0: without the "
                "load-balance loss the router can collapse onto a few "
                "experts and capacity-factor dispatch then drops most "
                "tokens. Set moe_aux_weight~1e-2.",
                stacklevel=2,
            )

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads


def llama3_8b(**over) -> LlamaConfig:
    """The real Llama-3-8B shape (BASELINE.json:10 target workload).

    Defaults to the pallas flash kernel: at this scale the S×S score
    materialization dominates attention HBM traffic (3.5 ms vs 75 ms dense
    fwd at S=8192 — BASELINE.md). flash_attention zero-pads unaligned
    shapes to the kernel tiling and masks the padding (round 4; no dense
    fallback cliff). Also defaults to
    the chunked-vocab loss: [B,S,128256] f32 logits would otherwise be the
    single largest activation in the step.
    """
    return LlamaConfig(**{"attn_impl": "flash", "xent_impl": "chunked", **over})


def llama_0_3b(**over) -> LlamaConfig:
    """~0.32B-parameter Llama shape for single-chip benchmarking: the
    largest config that trains comfortably on one v5e chip at long
    sequence lengths. Same architecture and kernel defaults as
    :func:`llama3_8b` (flash attention — head_dim stays 128, the kernel's
    lane width — and chunked-vocab loss); the BASELINE.md "0.33B llama
    variant" rows use this config.
    """
    return llama3_8b(
        **{
            "vocab_size": 32000,
            "d_model": 1024,
            "n_layers": 16,
            "n_heads": 8,
            "n_kv_heads": 4,
            "head_dim": 128,
            "d_ff": 4096,
            **over,
        }
    )


def llama_1b(**over) -> LlamaConfig:
    """~1.14B-parameter Llama shape: the largest config whose bf16
    params + adafactor state + 'dots'-remat residuals fit one v5e chip
    (batch 2 × seq 4096; batch 4 needs 'full' remat and measures worse).

    Role: the MFU-vs-scale evidence point. The 0.3b config's 63% MFU is
    bounded by per-step elementwise/issue floors that amortize with
    width — this config measures 76% of the sustained matmul rate on
    the same chip (BASELINE.md round-4 "MFU vs scale"), showing the
    framework's ceiling tracks the hardware, not the harness.
    """
    return llama3_8b(
        **{
            "vocab_size": 32000,
            "d_model": 2048,
            "n_layers": 16,
            "n_heads": 16,
            "n_kv_heads": 8,
            "head_dim": 128,
            "d_ff": 8192,
            **over,
        }
    )


def llama_tiny(**over) -> LlamaConfig:
    """Scaled-down config for tests/dryruns: same architecture, tiny dims."""
    base = dict(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        head_dim=16,
        d_ff=128,
        dtype=jnp.float32,
    )
    base.update(over)
    return LlamaConfig(**base)


class RMSNorm(nn.Module):
    eps: float
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(nn.initializers.ones_init(), ("norm",)),
            (x.shape[-1],),
            self.param_dtype,
        )
        x32 = x.astype(jnp.float32)
        y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (y * scale).astype(x.dtype)


def apply_rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding, rotate-half convention. x: [B,S,H,D], positions: [B,S]."""
    half = x.shape[-1] // 2
    freqs = (theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class Attention(nn.Module):
    """Grouped-query attention with RoPE and a causal mask.

    ``mesh`` is only consulted by the ring implementation (attn_impl="ring"),
    which shards the sequence over the mesh's ``sp`` axis and rotates K/V
    around the ring (parallel/ring.py).
    """

    cfg: LlamaConfig
    mesh: Any = None

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        B, S, _ = x.shape
        H, K, D = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

        q = nn.DenseGeneral(
            (H, D), use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "heads", "head_dim")
            ),
            name="q_proj",
        )(x)
        kv_kernel = nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), ("embed", "kv_heads", "head_dim")
        )
        k = nn.DenseGeneral(
            (K, D), use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=kv_kernel, name="k_proj",
        )(x)
        v = nn.DenseGeneral(
            (K, D), use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=kv_kernel, name="v_proj",
        )(x)

        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)

        # GQA: group q heads over their kv head: [B,S,K,G,D] against [B,S,K,D].
        G = cfg.q_per_kv
        q = q.reshape(B, S, K, G, D)
        if cfg.decode:
            return self._decode_attend(q, k, v, positions)
        if cfg.attn_impl == "ring":
            if self.mesh is None:
                raise ValueError(
                    "attn_impl='ring' needs the mesh: Llama(cfg, mesh=mesh)"
                )
            from ..parallel.ring import ring_self_attention

            out = ring_self_attention(q, k, v, positions, self.mesh)
        elif cfg.attn_impl == "ulysses":
            # All-to-all sequence parallelism (parallel/ulysses.py):
            # attention runs with full S and 1/sp of the kv heads per
            # device — two collectives total vs ring's P rotations.
            if self.mesh is None:
                raise ValueError(
                    "attn_impl='ulysses' needs the mesh: Llama(cfg, mesh=mesh)"
                )
            from ..parallel.ulysses import ulysses_self_attention

            out = ulysses_self_attention(q, k, v, positions, self.mesh)
        else:
            out = self._self_attend(q, k, v)
        out = out.reshape(B, S, H * D)
        out = nn.with_logical_constraint(out, ("batch", "seq", None))

        return self._o_proj(out)

    def _self_attend(self, q, k, v):
        """Causal self-attention over the incoming tokens only (flash or
        dense per ``cfg.attn_impl``): the non-sequence-parallel train
        path, and the decode path's PREFILL (a fresh cache's prompt
        occupies positions [0, S), so attention over the prompt alone is
        the full causal attention — no [B,K,G,S,L] score tensor against
        the whole cache budget, which at S=L=8k would be ~17 GB)."""
        cfg = self.cfg
        B, S, K, G, D = q.shape
        if cfg.attn_impl == "flash":
            # Blockwise pallas kernel; assumes the standard causal layout
            # (positions = arange), which Llama.__call__ defaults to.
            from ..ops.flash_attention import flash_attention

            return flash_attention(
                q.reshape(B, S, K * G, D), k, v, causal=True, mesh=self.mesh
            ).reshape(B, S, K, G, D)
        scores = jnp.einsum(
            "bskgd,btkd->bkgst", q, k, preferred_element_type=jnp.float32
        ) / jnp.sqrt(D).astype(jnp.float32)
        causal = jnp.tril(jnp.ones((S, S), dtype=bool))
        scores = jnp.where(causal, scores, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        return jnp.einsum("bkgst,btkd->bskgd", probs, v)

    def _o_proj(self, out):
        cfg = self.cfg
        return nn.DenseGeneral(
            cfg.d_model, axis=-1, use_bias=False,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("heads", "embed")
            ),
            name="o_proj",
        )(out)

    def _decode_attend(self, q, k, v, positions):
        """KV-cache attention (prefill AND single-token decode steps).

        Cache: ``cached_key``/``cached_value`` [B, K, max_decode_len, D]
        (heads-major) in the flax "cache" collection, written in place
        at the current positions; scores run q against the FULL cache with a
        position-validity mask (col_pos <= row_pos), so the program shape
        is static no matter how much of the cache is filled.

        CONTRACT (``cfg.decode_per_row=False``): positions must be
        batch-uniform (every row at the same offsets — the standard
        unpadded generate loop); the cache write offset reads row 0.
        With ``decode_per_row=True`` each row writes at its own
        ``positions[b, 0]`` (continuous-batching serving, where every
        row is a different request mid-stream). The attention validity
        mask is per-row in both modes. Because a contract violation is
        silently wrong (not an error), ``TPUJOB_DEBUG_CHECKS=1``
        installs a host-callback assert at the model top level (see
        ``Llama.__call__`` — once per step, not per layer).
        """
        cfg = self.cfg
        B, S, K, G, D = q.shape
        L = cfg.max_decode_len
        kv8 = cfg.kv_quantize == "int8"
        cache_dtype = jnp.int8 if kv8 else cfg.dtype
        # Heads-major [B, K, L, D] layout: each (b, k) head's [L, D]
        # panel is contiguous for the attention dots. (Measured neutral
        # vs seq-major on its own — XLA picks physical layouts — but it
        # is the natural shape for the per-layer slabs decode_forward
        # threads, and the einsums below read it without relayout.)
        ck = self.variable(
            "cache", "cached_key", jnp.zeros, (B, K, L, D), cache_dtype
        )
        cv = self.variable(
            "cache", "cached_value", jnp.zeros, (B, K, L, D), cache_dtype
        )
        ks = vs = None
        if kv8:
            # Per-(token, kv-head) scales: amax/127 over head_dim — one
            # f32 per D int8 payload bytes (3% overhead at D=128).
            ks = self.variable(
                "cache", "key_scale", jnp.zeros, (B, K, L, 1), jnp.float32
            )
            vs = self.variable(
                "cache", "value_scale", jnp.zeros, (B, K, L, 1), jnp.float32
            )
        if not self.is_initializing():
            # The incoming S tokens sit at contiguous positions starting
            # at positions[:, 0] (prefill: the prompt or a chunk of it;
            # decode: one token at the current index).
            if cfg.decode_per_row:
                # Per-row write offsets: a batched update-slice (XLA
                # lowers the vmapped DUS to a scatter). Only the serving
                # engine's mixed-depth batches pay this; the uniform
                # path below stays a single DUS.
                starts = positions[:, 0]

                def write(slab, vals):
                    return jax.vmap(
                        lambda c, u, s: jax.lax.dynamic_update_slice(
                            c, u, (0, s, 0)
                        )
                    )(slab, vals, starts)

            else:
                start = positions[0, 0]

                def write(slab, vals):
                    return jax.lax.dynamic_update_slice(
                        slab, vals, (0, 0, start, 0)
                    )

            k_in = k.swapaxes(1, 2)  # [B, K, S, D]
            v_in = v.swapaxes(1, 2)
            if kv8:
                from ..ops.quantize import quantize

                kq, vq = quantize(k_in, axis=-1), quantize(v_in, axis=-1)
                ck.value = write(ck.value, kq.q)
                ks.value = write(ks.value, kq.scale)
                cv.value = write(cv.value, vq.q)
                vs.value = write(vs.value, vq.scale)
            else:
                ck.value = write(ck.value, k_in.astype(cfg.dtype))
                cv.value = write(cv.value, v_in.astype(cfg.dtype))
        if S > 1 and cfg.prefill_mode == "self":
            # PREFILL (mode "self"): the prompt lands at positions
            # [0, S) of a fresh cache, so causal attention over the
            # incoming tokens alone IS the full attention — run the
            # standard self-attention path (flash when configured:
            # O(S·D) blockwise HBM) after the cache writes above,
            # instead of materializing [B, K, G, S, L] f32 scores
            # against the whole cache budget (~17 GB at S=L=8k — the
            # long-prompt OOM this branch removes). A nonzero prefill
            # start would make this silently wrong, so the
            # TPUJOB_DEBUG_CHECKS callback in ``Llama.__call__``
            # asserts start == 0 for multi-token inputs in this mode;
            # chunked continuations use prefill_mode="cache" below.
            out = self._self_attend(q, k, v)
        else:
            # Single-token decode steps, and (prefill_mode="cache")
            # chunks of a partially prefilled stream: attend against
            # the full cache — the chunk's own tokens were written
            # above at their true positions, so intra-chunk causality
            # and the prefix both fall out of the col <= row mask.
            out = self._cache_attend(q, positions, ck, cv, ks, vs)
        out = out.reshape(B, S, K * G * D)
        out = nn.with_logical_constraint(out, ("batch", "seq", None))
        return self._o_proj(out)

    def _cache_attend(self, q, positions, ck, cv, ks, vs):
        """q against the FULL cache with a per-(row, token) position-
        validity mask — static shapes however much of the cache is
        filled. Serves single-token decode steps (S=1, possibly at
        per-row depths) and chunked-prefill continuations (S>1,
        prefill_mode="cache")."""
        cfg = self.cfg
        B, S, K, G, D = q.shape
        L = cfg.max_decode_len
        kv8 = cfg.kv_quantize == "int8"
        if kv8:
            # Convert-ONLY on the big slabs (int8 -> 256 levels is exact
            # in a bf16 mantissa); the per-token scales fold into the
            # TINY score/prob tensors after the dots. A fused
            # convert+scale on the slab defeats operand fusion and
            # materializes a full-precision copy per layer per step —
            # measured -9% vs the fp cache at 1b/b8/L=4096, where this
            # formulation measures +43% (BASELINE.md round-4).
            kc, vc = ck.value.astype(cfg.dtype), cv.value.astype(cfg.dtype)
        else:
            kc, vc = ck.value, cv.value
        scores = jnp.einsum(
            "bskgd,bktd->bkgst", q, kc, preferred_element_type=jnp.float32
        ) / jnp.sqrt(D).astype(jnp.float32)
        if kv8:
            # scores[b,k,g,s,t] · key_scale[b,k,t]: the K dequant, moved
            # past the dot (linear in K).
            scores = scores * ks.value.squeeze(-1)[:, :, None, None, :]
        col = jnp.arange(L)[None, None, :]      # cache position [1,1,L]
        row = positions[:, :, None]             # query position [B,S,1]
        # Per-(row, token) validity: col <= row — the uniform generate
        # loop is just the special case where the B rows agree.
        scores = jnp.where(
            (col <= row)[:, None, None, :, :],  # [B,1,1,S,L]
            scores,
            jnp.finfo(jnp.float32).min,
        )
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        if kv8:
            # The V dequant, folded into probs (linear in V).
            probs = (
                probs * vs.value.squeeze(-1)[:, :, None, None, :]
            ).astype(cfg.dtype)
        return jnp.einsum("bkgst,bktd->bskgd", probs, vc)


class MLP(nn.Module):
    """SwiGLU: down(silu(gate(x)) * up(x))."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        proj = lambda name: nn.DenseGeneral(  # noqa: E731
            cfg.d_ff, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "mlp")
            ),
            name=name,
        )
        h = nn.silu(proj("gate_proj")(x)) * proj("up_proj")(x)
        h = nn.with_logical_constraint(h, ("batch", "seq", "mlp"))
        return nn.DenseGeneral(
            cfg.d_model, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("mlp", "embed")
            ),
            name="down_proj",
        )(h)


class MoEMLP(nn.Module):
    """Expert-parallel top-k MoE feed-forward (parallel/moe.py dispatch).

    Experts shard over the mesh's ``ep`` axis via the ``expert`` logical
    annotation; without a mesh (or with ep extent 1) the dense reference
    runs — same math, no shard_map.
    """

    cfg: LlamaConfig
    mesh: Any = None

    @nn.compact
    def __call__(self, x):
        from ..parallel.moe import moe_mlp, moe_mlp_reference

        cfg = self.cfg
        E, D, F = cfg.n_experts, cfg.d_model, cfg.d_ff
        gate = self.param(
            "gate",
            nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", None)
            ),
            (D, E),
            cfg.param_dtype,
        )
        w_in = self.param(
            "w_in",
            nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("expert", "embed", "mlp")
            ),
            (E, D, F),
            cfg.param_dtype,
        )
        w_out = self.param(
            "w_out",
            nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("expert", "mlp", "embed")
            ),
            (E, F, D),
            cfg.param_dtype,
        )
        params = {
            "gate": gate,
            "w_in": w_in.astype(cfg.dtype),
            "w_out": w_out.astype(cfg.dtype),
        }
        x2d = x.reshape(-1, D)
        if cfg.moe_aux_weight > 0:
            from ..parallel.moe import load_balance_loss

            # The router matmul recurs inside the dispatch below; both
            # run outside any shard_map (dispatch tensors are computed
            # replicated), the op is <1% of the expert FFN FLOPs, and
            # XLA CSEs identical-trace repeats — not worth threading
            # precomputed logits through both call paths.
            self.sow(
                "losses",
                "moe_aux",
                load_balance_loss(params, x2d, cfg.moe_top_k),
            )
        ep_live = self.mesh is not None and self.mesh.shape.get("ep", 1) > 1
        if cfg.moe_dispatch not in ("dense", "sparse"):
            raise ValueError(
                f"moe_dispatch={cfg.moe_dispatch!r} not in ('dense', 'sparse')"
            )
        if cfg.moe_dispatch == "sparse":
            from ..parallel.moe import moe_mlp_sparse

            out = moe_mlp_sparse(
                params,
                x2d,
                top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                mesh=self.mesh if ep_live else None,
            )
        elif ep_live:
            out = moe_mlp(params, x2d, mesh=self.mesh, top_k=cfg.moe_top_k)
        else:
            out = moe_mlp_reference(params, x2d, top_k=cfg.moe_top_k)
        return out.reshape(x.shape).astype(x.dtype)


class Block(nn.Module):
    """Pre-norm decoder block; carries (hidden, positions) through scan."""

    cfg: LlamaConfig
    mesh: Any = None

    @nn.compact
    def __call__(self, carry, _):
        x, positions = carry
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        x = x + Attention(self.cfg, self.mesh, name="attn")(
            RMSNorm(self.cfg.rms_eps, name="attn_norm")(x), positions
        )
        if self.cfg.n_experts > 0:
            mlp = MoEMLP(self.cfg, self.mesh, name="moe_mlp")
        else:
            mlp = MLP(self.cfg, name="mlp")
        x = x + mlp(RMSNorm(self.cfg.rms_eps, name="mlp_norm")(x))
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        return (x, positions), None


class Llama(nn.Module):
    """Decoder-only LM: tokens [B,S] int32 → logits [B,S,vocab].

    ``return_hidden=True`` returns the final-norm hidden states [B,S,D]
    instead of applying the LM head — the input to the chunked-vocab loss
    (ops/chunked_xent.py), which fuses head matmul + cross-entropy without
    materializing [B,S,V] logits. The head params exist either way.
    """

    cfg: LlamaConfig
    mesh: Any = None

    @staticmethod
    def head_kernel(params):
        """The LM-head weight [D, V] out of a params tree (unboxed) — the
        model-owned accessor the chunked-loss trainer path uses, so head
        naming stays out of shared infrastructure. Dequantizes an int8
        leaf (the consumer's matmul fuses the convert — plain dots do;
        see LlamaConfig.quantize)."""
        from ..ops.quantize import QuantizedTensor

        w = params["lm_head"]["kernel"]
        if isinstance(w, QuantizedTensor):
            return w.dequantize()
        return w.unbox() if hasattr(w, "unbox") else w

    @nn.compact
    def __call__(self, tokens, positions=None, return_hidden: bool = False):
        import os

        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[-1], dtype=jnp.int32), tokens.shape
            )
        elif cfg.decode and not self.is_initializing():
            # The decode path's KV-cache write offset and validity mask
            # read positions row 0 (_decode_attend contract) — a ragged
            # batch is silently wrong, not an error — and prefill
            # (S > 1) attends over the incoming tokens only, so a
            # nonzero start silently drops context. Debug mode asserts
            # both ONCE at the model top (not per layer); costs one
            # device->host sync per decode step. decode_forward (the
            # serving path, which bypasses this __call__) installs the
            # same check.
            _debug_check_decode_positions(positions, cfg)

        dequant = None
        if cfg.quantize:
            if self.is_initializing():
                raise ValueError(
                    "a quantize-mode model cannot init: init the "
                    "full-precision model and quantize its params with "
                    "ops.quantize.quantize_tree"
                )
            from ..ops.quantize import dequantize_tree as dequant

        embed_cls = (
            nn.map_variables(nn.Embed, "params", dequant) if dequant else nn.Embed
        )
        embed = embed_cls(
            cfg.vocab_size,
            cfg.d_model,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=1.0), ("vocab", "embed")
            ),
            name="embed",
        )
        x = embed(tokens)

        block = Block
        if cfg.remat:
            block = nn.remat(
                Block, prevent_cse=False, policy=remat_policy(cfg)
            )
        if dequant:
            # INSIDE the scan wrapper: the scan slices the stacked int8
            # leaves first, this dequantizes the slice in the body (see
            # LlamaConfig.quantize).
            block = nn.map_variables(block, "params", dequant)
        ScanBlocks = nn.scan(
            block,
            # Per-layer stacking for params, the decode KV cache, and
            # sown aux losses (each gains a leading layer axis).
            variable_axes={"params": 0, "cache": 0, "losses": 0},
            split_rngs={"params": True},
            length=cfg.n_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
            # Deliberately no unroll knob: lax.scan unroll=2/4 measured
            # -13% on chip (BASELINE.md) — XLA pipelines the rolled scan
            # better than merged bodies.
        )
        (x, _), _ = ScanBlocks(cfg, self.mesh, name="layers")((x, positions), None)

        x = RMSNorm(cfg.rms_eps, name="final_norm")(x)
        head_cls = (
            nn.map_variables(nn.DenseGeneral, "params", dequant)
            if dequant
            else nn.DenseGeneral
        )
        lm_head = head_cls(
            cfg.vocab_size, use_bias=False,
            dtype=jnp.float32, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "vocab")
            ),
            name="lm_head",
        )
        if return_hidden:
            if self.is_initializing():
                # Params must exist regardless of the loss path; a 1-token
                # slice keeps the init trace cheap.
                lm_head(x[:, :1])
            return x
        return lm_head(x)

    @nn.nowrap
    def pp_forward(self, params, tokens, *, mesh, microbatches, return_hidden=False):
        """Model-owned pipeline-parallel forward (the hook
        make_lm_train_step calls when the mesh has a pp axis — keeps
        llama param naming out of shared trainer infrastructure, like
        ``head_kernel``). ``nn.nowrap``: this is plain orchestration, not
        a scoped module method — wrapping would make the in-function
        ``Block``/``RMSNorm`` constructions claim ``self`` as parent."""
        return forward_pp(
            self, params, tokens,
            mesh=mesh, microbatches=microbatches, return_hidden=return_hidden,
        )

    @nn.nowrap
    def pp_value_and_grad(self, params, tokens, *, mesh, microbatches):
        """Model-owned 1F1B train gradients (the make_lm_train_step hook
        for ``--pp-schedule 1f1b``); see :func:`train_value_and_grad_pp`."""
        return train_value_and_grad_pp(
            self, params, tokens, mesh=mesh, microbatches=microbatches
        )


def _debug_check_decode_positions(positions, cfg):
    """Install the TPUJOB_DEBUG_CHECKS host assert on decode positions,
    per the config's contract:

    - always: rows are per-row CONTIGUOUS (pos[b, s] = pos[b, 0] + s)
      and the last write lands inside the cache (pos < max_decode_len —
      dynamic_update_slice would silently CLAMP an overflow and corrupt
      the newest cache rows).
    - ``decode_per_row=False``: batch-uniform (the cache write offset
      reads row 0).
    - ``prefill_mode="self"``: multi-token inputs start at position 0
      (self-attention prefill would silently drop earlier context at a
      nonzero start; chunked continuations need prefill_mode="cache").

    No-op unless the env var is set."""
    import os

    if os.environ.get("TPUJOB_DEBUG_CHECKS", "").lower() in (
        "", "0", "false", "no",
    ):
        return
    per_row, prefill_mode, L = (
        cfg.decode_per_row, cfg.prefill_mode, cfg.max_decode_len,
    )

    def _assert_valid(pos):
        import numpy as np

        S = pos.shape[-1]
        if not (pos == pos[:, :1] + np.arange(S)).all():
            raise ValueError(
                f"decode positions must be contiguous per row; got {pos}"
            )
        if not per_row and not (pos == pos[0:1]).all():
            raise ValueError(
                "decode positions must be batch-uniform (unpadded "
                f"equal-length batch); got rows {pos}. Bucket ragged "
                "prompts to equal length, generate row-by-row, or build "
                "the model with decode_per_row=True (serving engine)."
            )
        if pos.max() >= L:
            raise ValueError(
                f"decode position {pos.max()} >= max_decode_len {L}: "
                "the cache write would clamp and corrupt the rollout"
            )
        if prefill_mode == "self" and S > 1 and (pos[:, 0] != 0).any():
            raise ValueError(
                "multi-token decode input (prefill) must start at "
                f"position 0, got starts {pos[:, 0]}: prefill_mode="
                "'self' attends over the incoming tokens only. Chunked "
                "prefill needs prefill_mode='cache'."
            )

    jax.debug.callback(_assert_valid, positions)


def init_decode_cache(cfg: LlamaConfig, batch: int):
    """Zero KV cache for :func:`decode_forward`: a flat per-layer dict
    (``layer_0`` .. ``layer_{n-1}``), each holding the slab the block's
    attention declares — NOT the flax-scan stacked form. The flat form
    is the point: per-layer slabs flow as plain scan-carry leaves, so a
    decode step's only cache writes are one token-slice
    dynamic_update_slice per layer, updated in place."""
    B, L, K, D = batch, cfg.max_decode_len, cfg.n_kv_heads, cfg.head_dim
    kv8 = cfg.kv_quantize == "int8"

    def slab():
        # Fresh arrays per layer: shared buffers would alias when the
        # caller donates the cache into the jitted generate.
        s = {
            "cached_key": jnp.zeros(
                (B, K, L, D), jnp.int8 if kv8 else cfg.dtype
            ),
            "cached_value": jnp.zeros(
                (B, K, L, D), jnp.int8 if kv8 else cfg.dtype
            ),
        }
        if kv8:
            s["key_scale"] = jnp.zeros((B, K, L, 1), jnp.float32)
            s["value_scale"] = jnp.zeros((B, K, L, 1), jnp.float32)
        return s

    return {f"layer_{i}": {"attn": slab()} for i in range(cfg.n_layers)}


def decode_forward(
    model: "Llama",
    params,
    cache,
    tokens,
    positions=None,
    *,
    return_hidden: bool = True,
):
    """The SERVING forward: numerically identical to
    ``Llama(decode=True).apply`` (pinned by test), but with the layer
    loop UNROLLED and the KV cache as an explicit argument/return
    (:func:`init_decode_cache` layout) instead of a flax-scan-lifted
    collection.

    Why this exists: under ``nn.scan(variable_axes={"cache": 0})`` every
    decode step dynamic-slices each layer's whole slab out of the
    stacked cache, rewrites it wholesale, and copies the stack — an
    xplane profile at 1b/b8/L=4096 showed 16 of 22.3 ms/step going to
    exactly that (copy 30% + DS/DUS fusions 43%; BASELINE.md round-4).
    Here each layer's slab is a plain carry leaf: the step reads it once
    (fused into the attention einsums) and writes ONE token slice in
    place. Quantized (``cfg.quantize``) trees are dequantized per layer
    at the use site — python-unrolled, so there is no scan-input
    materialization hazard and no map_variables hook is needed.

    Returns ``(hidden_or_logits, new_cache)``.
    """
    from ..ops.quantize import QuantizedTensor, dequantize_tree

    cfg = model.cfg
    if positions is None:
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[-1], dtype=jnp.int32), tokens.shape
        )
    else:
        # Same TPUJOB_DEBUG_CHECKS contract assert as Llama.__call__
        # (this path bypasses it); the checked contract follows the
        # config: batch-uniform unless decode_per_row, start-0 prefill
        # unless prefill_mode="cache".
        _debug_check_decode_positions(positions, model.cfg)
    p = nn.meta.unbox(params)

    table = p["embed"]["embedding"]
    if isinstance(table, QuantizedTensor):
        # Gather rows first, dequantize the gathered rows (per-row
        # scales) — never the whole table.
        x = (
            table.q[tokens].astype(jnp.float32) * table.scale[tokens]
        ).astype(cfg.dtype)
    else:
        x = table.astype(cfg.dtype)[tokens]

    block = Block(cfg, model.mesh)
    new_cache = {}
    for i in range(cfg.n_layers):
        # Static per-layer slice; QuantizedTensor is a pytree node, so
        # its q/scale fields are sliced like any other stacked leaf.
        lp = dequantize_tree(jax.tree.map(lambda a: a[i], p["layers"]))
        with nn.logical_axis_rules(()):
            ((x, _pos), _), upd = block.apply(
                {"params": lp, "cache": cache[f"layer_{i}"]},
                (x, positions),
                None,
                mutable=["cache"],
            )
        new_cache[f"layer_{i}"] = upd["cache"]

    x = RMSNorm(cfg.rms_eps).apply(
        {"params": dequantize_tree(p["final_norm"])}, x
    )
    if return_hidden:
        return x, new_cache
    w = Llama.head_kernel(p)
    return x.astype(jnp.float32) @ w.astype(jnp.float32), new_cache


def forward_pp(
    model: "Llama",
    params,
    tokens,
    *,
    mesh,
    microbatches: int,
    return_hidden: bool = False,
):
    """Pipeline-parallel forward: the layer stack runs through
    ``parallel.pipeline.pipeline_apply`` over the mesh's ``pp`` axis,
    numerically identical to ``model.apply`` (same params, same order).

    The scan-stacked layer params (leading axis n_layers) regroup into
    P stages of n_layers/P consecutive layers; embed / final norm / LM
    head run outside the pipeline under the surrounding pjit (their
    FLOPs are a sliver of the stack's, and keeping them SPMD avoids
    first/last-stage special cases). ``cfg.remat`` applies per layer
    inside each stage. Composes with dp/fsdp on the same mesh —
    pipeline_apply takes manual control of pp only.

    Constraints: ``cfg.n_layers % pp == 0``; ring attention (sp) cannot
    nest inside the pp pipeline.
    """
    from ..parallel.pipeline import pipeline_apply

    cfg = model.cfg
    p, stage_params, stage = _pp_parts(model, params, mesh)

    # Embedding lookup, matching nn.Embed(dtype=cfg.dtype) semantics
    # (table cast to the compute dtype, then take).
    x = p["embed"]["embedding"].astype(cfg.dtype)[tokens]

    x = pipeline_apply(
        stage, stage_params, x, mesh=mesh, microbatches=microbatches
    )

    x = RMSNorm(cfg.rms_eps, name="final_norm").apply(
        {"params": p["final_norm"]}, x
    )
    if return_hidden:
        return x
    # DenseGeneral(dtype=float32) semantics: promote input and kernel.
    w = p["lm_head"]["kernel"]
    return x.astype(jnp.float32) @ w.astype(jnp.float32)


def _pp_parts(model: "Llama", params, mesh):
    """The shared pp decomposition behind forward_pp and
    train_value_and_grad_pp: ``(unboxed_params, stage_params, stage_fn)``
    — the scan-stacked layer params (leading axis n_layers) regrouped
    into P stages of n_layers/P consecutive layers, and the per-stage
    computation over them."""
    import jax

    cfg = model.cfg
    n_stages = mesh.shape["pp"]
    if cfg.quantize:
        raise ValueError(
            "quantize-mode params (inference) cannot run the pp pipeline"
        )
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pp={n_stages}"
        )
    if cfg.attn_impl in ("ring", "ulysses"):
        raise ValueError(
            f"attn_impl={cfg.attn_impl!r} cannot run inside the pp pipeline"
        )
    p = nn.meta.unbox(params)
    stage_params = jax.tree.map(
        lambda l: l.reshape((n_stages, cfg.n_layers // n_stages) + l.shape[1:]),
        p["layers"],
    )
    # Blocks inside the pipeline get mesh=None: pp is already manual in
    # pipeline_apply, and the remaining axes (dp/fsdp) are compiler-
    # propagated — the block needs no mesh consultation (ring is the one
    # mesh consumer, rejected above; flash runs unwrapped).
    block = Block(cfg, None)

    def stage(sp, act):
        pos = jnp.broadcast_to(
            jnp.arange(act.shape[1], dtype=jnp.int32), act.shape[:2]
        )

        def layer(carry, lp):
            # Logical-axis rules off inside the pipeline: pp is manual
            # here, so flax's constraint/unbox machinery would try to
            # bind logical names against a Manual-axis mesh and reject;
            # the remaining axes (dp/fsdp) propagate through shard_map's
            # auto mode without annotations.
            with nn.logical_axis_rules(()):
                out, _ = block.apply({"params": lp}, carry, None)
            return out, None

        if cfg.remat:
            layer = jax.checkpoint(
                layer, prevent_cse=False, policy=remat_policy(cfg)
            )
        (act_out, _pos), _ = jax.lax.scan(layer, (act, pos), sp)
        return act_out

    return p, stage_params, stage


def train_value_and_grad_pp(
    model: "Llama",
    params,
    tokens,
    *,
    mesh,
    microbatches: int,
):
    """1F1B fused train gradients for the llama stack: returns
    ``(loss, grads)`` with grads matching the (boxed) params tree —
    numerically equal to ``jax.value_and_grad`` over the GPipe forward,
    but with per-stage activation residency bounded by the schedule
    depth O(P·mb) instead of O(M·mb)
    (parallel/pipeline.pipeline_value_and_grad; backward='stored'
    residual stashing keeps compute at GPipe parity).

    The embed lookup runs outside the pipeline (its input-cotangent
    stream dx comes back from the pipeline's backward); the final norm +
    LM head + next-token loss run INSIDE as a VOCAB-PARALLEL loss tail
    (``sharded_loss=True``) whenever pp > 1 and the vocab divides: the
    head kernel is chunked ``[P, d, V/P]`` over the pp axis, every stage
    computes online-softmax partial stats for its columns
    (ops.chunked_xent.chunked_vocab_stats — ``cfg.xent_impl='chunked'``
    streams [N, 8192] sub-chunks, 'dense' takes the local V/P in one
    pass), and the per-token log-sum-exp + target logit combine with one
    pmax + two psums. This is the round-4 fix for the P-fold loss-tail
    duplication: the tail costs ~1/P per stage instead of 1× per stage.

    Degenerate/fallback cases keep the REPLICATED tail (the pre-round-4
    behavior, correct at any vocab): pp=1 (nothing to shard), or a vocab
    that does not divide the pp extent (warns — the tail then duplicates
    P-fold, so prefer a divisible vocab/pp pairing).

    MoE aux losses are not supported on pp meshes (same restriction as
    the GPipe path — flax sow collections don't thread the pipeline).
    """
    import jax

    from ..ops.chunked_xent import chunked_vocab_stats
    from ..parallel.pipeline import pipeline_value_and_grad

    cfg = model.cfg
    if getattr(cfg, "moe_aux_weight", 0.0):
        raise ValueError(
            "moe_aux_weight is not supported on a pp mesh (the pipeline "
            "path bypasses flax sow collections)"
        )
    p, stage_params, stage = _pp_parts(model, params, mesh)
    n_stages = mesh.shape["pp"]
    V = cfg.vocab_size
    sharded = n_stages > 1 and V % n_stages == 0
    if n_stages > 1 and not sharded:
        import warnings

        warnings.warn(
            f"vocab_size={V} does not divide pp={n_stages}: the pipeline "
            "loss tail cannot be vocab-parallel and will run replicated "
            f"on every stage ({n_stages}x duplicated head FLOPs). Prefer "
            "a vocab/pp pairing that divides.",
            stacklevel=2,
        )

    x, embed_vjp = jax.vjp(
        lambda table: table.astype(cfg.dtype)[tokens], p["embed"]["embedding"]
    )
    w_full = p["lm_head"]["kernel"]  # [d, V]

    def norm_hidden(scale_params, y_mb):
        h = RMSNorm(cfg.rms_eps).apply({"params": scale_params}, y_mb)
        return h[:, :-1].reshape(-1, h.shape[-1])

    if sharded:
        Vp = V // n_stages
        lp = {
            # Stage s owns vocab columns [s*Vp, (s+1)*Vp).
            "w": jnp.moveaxis(
                w_full.reshape(w_full.shape[0], n_stages, Vp), 1, 0
            ),
            # The norm scale is tiny: stack P copies; total grad = sum of
            # the per-stage partials (each stage's chunk loss consumed
            # its copy).
            "final_norm": jax.tree.map(
                lambda l: jnp.broadcast_to(l, (n_stages,) + l.shape),
                p["final_norm"],
            ),
        }

        def loss_fn(lp_, y_mb, tok_mb):
            # Vocab-parallel next-token xent: per-stage online-softmax
            # partials + collective log-sum-exp. Equals optax integer-
            # label xent on the assembled logits. (m carries no tangent,
            # so pmax — which has no differentiation rule — is skipped
            # by AD.)
            hh = norm_hidden(lp_["final_norm"], y_mb)
            labels = tok_mb[:, 1:].reshape(-1)
            off = jax.lax.axis_index("pp") * Vp
            chunk = 8192 if cfg.xent_impl == "chunked" else Vp
            m, s, lab = chunked_vocab_stats(
                hh, lp_["w"], labels, chunk=chunk, col_offset=off
            )
            m_g = jax.lax.pmax(m, "pp")
            se = jax.lax.psum(s * jnp.exp(m - m_g), "pp")
            tgt = jax.lax.psum(lab, "pp")
            return (m_g + jnp.log(se) - tgt).mean()

        def reassemble(d_lp):
            return {
                "final_norm": jax.tree.map(
                    lambda g: g.sum(0), d_lp["final_norm"]
                ),
                "lm_head": {
                    "kernel": jnp.moveaxis(d_lp["w"], 0, 1).reshape(
                        w_full.shape
                    )
                },
            }

    else:
        import optax

        lp = {"final_norm": p["final_norm"], "lm_head": p["lm_head"]}

        def loss_fn(lp_, y_mb, tok_mb):
            hh = norm_hidden(lp_["final_norm"], y_mb)
            w = lp_["lm_head"]["kernel"]
            labels = tok_mb[:, 1:].reshape(-1)
            if cfg.xent_impl == "chunked":
                from ..ops.chunked_xent import chunked_softmax_xent

                return chunked_softmax_xent(hh, w, labels).mean()
            logits = hh.astype(jnp.float32) @ w.astype(jnp.float32)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()

        def reassemble(d_lp):
            return {
                "final_norm": d_lp["final_norm"],
                "lm_head": d_lp["lm_head"],
            }

    loss, (d_stage, d_lp, dx) = pipeline_value_and_grad(
        stage, loss_fn, stage_params, lp, x, tokens,
        mesh=mesh, microbatches=microbatches, schedule="1f1b",
        sharded_loss=sharded,
        # Megatron-style residual stashing: backward reuses the forward's
        # policy-saved residuals (compute parity with GPipe) instead of
        # re-running each stage from its saved input. The transformer
        # stage's residuals are shape-separable (activations carry the
        # microbatch dim; weights/tables don't), which is exactly the
        # contract backward='stored' needs.
        backward="stored",
    )
    (d_embed,) = embed_vjp(dx)
    grads_unboxed = {
        "embed": {"embedding": d_embed},
        "layers": jax.tree.map(
            lambda g, ref: g.reshape(ref.shape), d_stage, p["layers"]
        ),
        **reassemble(d_lp),
    }
    # Re-box to the params tree's flax metadata so the optimizer sees the
    # exact params structure (Partitioned leaves and all).
    return loss, jax.tree.map(
        lambda box, g: (
            box.replace_boxed(g)
            if isinstance(box, nn.meta.Partitioned)
            else g
        ),
        params,
        grads_unboxed,
        is_leaf=lambda v: isinstance(v, nn.meta.Partitioned),
    )
