"""ResNet v1.5 for image classification — the headline benchmark model.

Reference analog: the ResNet-50 ImageNet PyTorchJob config (BASELINE.json:8,
"DDP → xla backend on v5p-8"); the model itself lives in the reference's
user containers (torchvision), so this is a from-scratch flax implementation
of the standard v1.5 architecture (stride-2 in the 3×3 of each bottleneck —
the MLPerf convention).

TPU-first choices:
- NHWC layout (XLA's native conv layout on TPU),
- bfloat16 compute / float32 params and batch-norm statistics (MXU-friendly
  without accuracy loss; ``bn_f32_stats=False`` is an experimental knob that
  drops BN stats AND BN scale/bias to bf16 — BASELINE.md A/B),
- no data-dependent control flow — the whole step is one XLA program.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1×1 → 3×3(stride) → 1×1(×4) with projection shortcut when needed."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale: residual branches start as identity,
        # the standard trick for stable large-batch training.
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """3×3 → 3×3 residual block (ResNet-18/34)."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class SpaceToDepthStem(nn.Module):
    """The 7×7/stride-2 stem conv, computed as a 4×4/stride-1 conv on a
    2×2 space-to-depth transform of the input — mathematically EXACT.

    Why: with 3 input channels the MXU runs the 7×7 conv mostly on padding
    (channel dim is packed far below the systolic array's native width).
    Space-to-depth moves 2×2 spatial blocks into channels (3→12), which
    packs the contraction 4× denser at identical FLOPs — the standard
    MLPerf-era TPU ResNet stem optimization.

    Exactness: zero-pad the 7×7 kernel to 8×8 (one extra top row / left
    column), then for output (i,j):
        y[i,j] = Σ_{u,v∈0..7} K8[u,v] · x[2i+u−4, 2j+v−4]
    splitting u=2r+a, v=2s+b (r,s∈0..3; a,b∈0..1) turns the sum into a
    4×4 stride-1 conv over z[p,q,(a,b,c)] = x[2p+a, 2q+b, c] with spatial
    padding (2,1) — same outputs, same gradients (the kernel reshape is
    linear). The parameter keeps the canonical (7,7,C,F) shape, so
    checkpoints interop with the plain stem.
    """

    features: int
    dtype: Any
    kernel_init: Callable

    @nn.compact
    def __call__(self, x):
        n, h, w, c = x.shape
        if h % 2 or w % 2:
            raise ValueError(f"space-to-depth stem needs even H/W, got {(h, w)}")
        k7 = self.param(
            "kernel", self.kernel_init, (7, 7, c, self.features), jnp.float32
        )
        k8 = jnp.pad(k7, ((1, 0), (1, 0), (0, 0), (0, 0)))
        # K8[2r+a, 2s+b, c, o] → K4[r, s, (a,b,c), o]; (a,b,c) flattens in
        # the same order as the z channel layout below.
        k4 = (
            k8.reshape(4, 2, 4, 2, c, self.features)
            .transpose(0, 2, 1, 3, 4, 5)
            .reshape(4, 4, 4 * c, self.features)
        )
        z = (
            x.reshape(n, h // 2, 2, w // 2, 2, c)
            .transpose(0, 1, 3, 2, 4, 5)
            .reshape(n, h // 2, w // 2, 4 * c)
        )
        return jax.lax.conv_general_dilated(
            z.astype(self.dtype),
            k4.astype(self.dtype),
            window_strides=(1, 1),
            padding=((2, 1), (2, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    block_cls: ModuleDef = BottleneckBlock
    # Batch-norm precision. f32 (default) is the numerically safe choice
    # for convergence runs. False computes the BN reductions in bf16 AND
    # (a flax constraint: stats are stored in param_dtype) downcasts the
    # learnable scale/bias to bf16 — so their SGD updates quantize to an
    # 8-bit mantissa too. Measured throughput-neutral on this hardware
    # (BASELINE.md A/B); kept as an experiment knob only.
    bn_f32_stats: bool = True
    # Compute the stem as a space-to-depth 4×4 conv (exact; see
    # SpaceToDepthStem). Same parameters/checkpoints either way.
    s2d_stem: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            kernel_init=nn.initializers.variance_scaling(2.0, "fan_out", "normal"),
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            # flax computes stats in max(param_dtype, f32) unless
            # force_float32_reductions; bf16 stats need both relaxed.
            param_dtype=jnp.float32 if self.bn_f32_stats else self.dtype,
            force_float32_reductions=self.bn_f32_stats,
        )
        act = nn.relu

        x = x.astype(self.dtype)
        if self.s2d_stem:
            x = SpaceToDepthStem(
                features=self.num_filters,
                dtype=self.dtype,
                kernel_init=nn.initializers.variance_scaling(
                    2.0, "fan_out", "normal"
                ),
                name="conv_init",
            )(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=act,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(
            self.num_classes,
            dtype=jnp.float32,
            kernel_init=nn.initializers.variance_scaling(1.0, "fan_in", "truncated_normal"),
        )(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])

BY_DEPTH = {18: ResNet18, 34: ResNet34, 50: ResNet50, 101: ResNet101, 152: ResNet152}
