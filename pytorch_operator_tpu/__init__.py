"""pytorch_operator_tpu — a TPU-native distributed training job framework.

A ground-up rebuild of the capabilities of the Kubeflow PyTorch operator
(reference: sd3g14/pytorch-operator, a fork of kubeflow/pytorch-operator —
see SURVEY.md for the structural analysis) designed TPU-first:

- ``api``        — the TPUJob spec: typed job objects, defaulting, validation,
                   YAML serialization (mirrors ``pkg/apis/pytorch/v1/``).
- ``controller`` — the supervisor/reconciler: gang process launch, restart
                   policies, the Created→Running→Succeeded/Failed/Restarting
                   condition state machine, cleanup, events, metrics (mirrors
                   ``pkg/controller.v1/pytorch/`` + the vendored
                   ``kubeflow/common`` job framework).
- ``runtime``    — cluster-spec env injection and jax.distributed rendezvous
                   (mirrors ``SetClusterSpec`` in ``pod.go``; replaces the
                   c10d MASTER_ADDR/NCCL wiring with PJRT/XLA-collective
                   equivalents per BASELINE.json:5).
- ``parallel``   — device meshes, sharding rules, collectives: the TPU-native
                   stand-in for the NCCL/Gloo layer the reference delegated to
                   user containers.
- ``models`` / ``ops`` — JAX/flax workload model zoo (MNIST, ResNet-50, BERT,
                   Llama) and TPU kernels (attention, etc.).
- ``workloads``  — runnable training entrypoints launched by the supervisor
                   (mirrors ``examples/`` of the reference).
- ``client``     — the ``tpujob`` CLI (submit/get/describe/logs/delete), the
                   stand-in for kubectl+CRD.

The control plane is pure Python with no jax import at module scope, so the
supervisor stays lightweight; workload processes import jax themselves.
"""

__version__ = "0.1.0"
