"""Hand-written TPU kernels (pallas) for the hot ops.

The reference ships no kernels — its numerical layer is whatever PyTorch
the user containers bring (SURVEY.md §2: "no C++/Rust/CUDA components in
the reference"). The rebuild's compute path is JAX/XLA; these pallas
kernels cover the few spots where fusing beyond XLA pays: attention's
O(S^2) score materialization.
"""

from .flash_attention import flash_attention  # noqa: F401
from .quantize import (  # noqa: F401
    QuantizedTensor,
    dequantize_tree,
    quantize_tree,
)
