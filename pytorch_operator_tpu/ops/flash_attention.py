"""Flash attention as a pallas TPU kernel (forward + custom-VJP backward).

Why a kernel at all: XLA fuses elementwise chains into matmuls well, but a
dense causal attention still materializes the [S, S] score matrix in HBM
(O(S^2) bytes) and round-trips it for softmax + PV. The flash form streams
K/V blocks through VMEM with an online softmax, so HBM traffic is O(S·D)
and the MXU stays fed from on-chip memory — the canonical memory-bound →
compute-bound rewrite for TPU (pallas_guide.md: HBM → VMEM → MXU).

Design notes:

- Grid ``(B·H, S/block_q, S/block_k)``; the K-block dimension is innermost
  and sequential, carrying the online-softmax state (running max ``m``,
  denominator ``l``, accumulator ``acc``) in VMEM scratch across grid
  steps. Fully-masked K blocks (above the causal diagonal) are skipped
  with ``pl.when`` — ~2x fewer FLOPs for causal LM.
- GQA without materialization: K/V block specs index with ``head // G``
  (G = query heads per KV head), so grouped heads read the same KV shard
  straight from HBM — no ``repeat`` before the kernel.
- Backward is the standard two-kernel flash recomputation (no [S, S]
  residual): forward saves only ``lse = m + log l`` per row; ``dq`` re-walks
  K blocks, ``dk/dv`` re-walks Q blocks, each recomputing ``p = exp(s -
  lse)`` on the fly. dK/dV are produced per *query* head and group-summed
  outside the kernel (keeps every grid cell's output block private).
- Matmuls run in the input dtype (bf16 in production) with
  ``preferred_element_type=float32``; softmax math is float32.
- Unaligned shapes (S not divisible by the blocks; D not lane-aligned)
  are zero-padded to the tiling and masked via a static ``kv_len``
  (padded key columns score -inf; padded query rows are sliced off), so
  e.g. ViT's S=197/D=64 runs the O(S·D) kernel instead of falling back
  to a dense O(S^2) path (round 4).
- Multi-device: pass ``mesh`` — the call is wrapped in a partial-manual
  ``shard_map`` over the dp/fsdp (batch) and tp (heads) axes, composing
  with the pjit-sharded training step the same way parallel/ring.py does
  for sp. Sequence parallelism is ring attention's job, not this kernel's.

Reference analog: none (SURVEY.md §2 — attention kernels live outside the
reference, in the user containers' PyTorch).
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional

_NEG = -1e30  # finite mask value: exp(_NEG - m) underflows to exactly 0.0


class _FlashCfg(NamedTuple):
    """Static kernel config (hashable — custom_vjp nondiff arg)."""

    causal: bool
    block_q: int
    block_k: int
    groups: int  # query heads per kv head (GQA)
    interpret: bool
    # Softmax scale — 1/sqrt(d) of the TRUE head dim: when the wrapper
    # zero-pads D to lane alignment, sqrt(padded D) would be wrong.
    scale: float
    # Keys/values at positions >= kv_len are masked out (score = -inf).
    # None = no length mask (every position is real). Static: this is the
    # one TRUE sequence length of a padded-to-alignment batch, not a
    # per-example length.
    kv_len: Optional[int] = None


def _mask_scores(cfg: _FlashCfg, s, i, j, bq: int, bk: int):
    """Element-level score masking shared by all three kernels (forward
    and backward MUST mask identically): causal upper triangle and/or
    key columns >= kv_len score _NEG."""
    import jax
    import jax.numpy as jnp

    if not cfg.causal and cfg.kv_len is None:
        return s
    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + i * bq
    cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + j * bk
    keep = cols <= rows if cfg.causal else True
    if cfg.kv_len is not None:
        keep = keep & (cols < cfg.kv_len)
    return jnp.where(keep, s, _NEG)


def _live_block(cfg: _FlashCfg, i, j, bq: int, bk: int):
    """Predicate for K blocks with at least one unmasked column under the
    causal and/or kv_len masks (None = every block live). ``i``/``j`` are
    the q/k block program ids of the calling grid."""
    live = None
    if cfg.causal:
        live = j * bk <= i * bq + bq - 1
    if cfg.kv_len is not None:
        past = j * bk < cfg.kv_len
        live = past if live is None else live & past
    return live


# ---------------------------------------------------------------- kernels


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, cfg: _FlashCfg):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    i, j = pl.program_id(1), pl.program_id(2)
    bq, bk = cfg.block_q, cfg.block_k
    scale = cfg.scale

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def compute():
        q = q_ref[0]                       # [bq, D] input dtype
        k = k_ref[0]                       # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                          # [bq, bk] f32
        s = _mask_scores(cfg, s, i, j, bq, bk)
        m_prev = m_ref[:, :1]              # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)    # [bq, 1]
        p = jnp.exp(s - m_new)             # [bq, bk] f32; masked cols → 0
        l_ref[:] = jnp.broadcast_to(
            l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True),
            l_ref.shape,
        )
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                  # [bq, D] f32
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    live = _live_block(cfg, i, j, bq, bk)
    if live is None:
        compute()
    else:
        # Skip K blocks with no unmasked column: above the causal
        # diagonal, or entirely past kv_len.
        pl.when(live)(compute)

    @pl.when(j == pl.num_programs(2) - 1)
    def _finish():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # lse carries a broadcast 128-lane dim purely for TPU tiling
        # (same layout as the in-tree pallas flash kernel's l/m outputs).
        lse_ref[0] = jnp.broadcast_to(m_ref[:, :1] + jnp.log(l), lse_ref.shape[1:])


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, cfg: _FlashCfg):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    i, j = pl.program_id(1), pl.program_id(2)
    bq, bk = cfg.block_q, cfg.block_k
    scale = cfg.scale

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        s = _mask_scores(cfg, s, i, j, bq, bk)
        p = jnp.exp(s - lse_ref[0, :, :1])          # [bq, bk] f32
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0, :, :1])         # [bq, bk] f32
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    live = _live_block(cfg, i, j, bq, bk)
    if live is None:
        compute()
    else:
        pl.when(live)(compute)

    @pl.when(j == pl.num_programs(2) - 1)
    def _finish():
        dq_ref[0] = (dq_acc[:] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, cfg: _FlashCfg):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    j, i = pl.program_id(1), pl.program_id(2)  # K block outer, Q block inner
    bq, bk = cfg.block_q, cfg.block_k
    scale = cfg.scale

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        s = _mask_scores(cfg, s, i, j, bq, bk)
        p = jnp.exp(s - lse_ref[0, :, :1])          # [bq, bk] f32
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                           # p^T @ do → [bk, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0, :, :1])
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                           # ds^T @ q → [bk, D]

    live = _live_block(cfg, i, j, bq, bk)
    if live is None:
        compute()
    else:
        # Causal: this K block only sees Q blocks at or below the
        # diagonal. kv_len: K blocks past the true length are all-masked.
        pl.when(live)(compute)

    @pl.when(i == pl.num_programs(2) - 1)
    def _finish():
        dk_ref[0] = (dk_acc[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


# ---------------------------------------------------------- pallas calls


def _specs(cfg: _FlashCfg, D: int, *, kv_from_j: bool):
    """Input specs for (q, k, v, do?, lse?, delta?) given the grid layout.

    ``kv_from_j=True``: grid is (bh, q_block i, k_block j) — fwd and dq.
    ``kv_from_j=False``: grid is (bh, k_block j, q_block i) — dkv.
    """
    from jax.experimental import pallas as pl

    G = cfg.groups

    if kv_from_j:
        q_idx = lambda b, i, j: (b, i, 0)       # noqa: E731
        kv_idx = lambda b, i, j: (b // G, j, 0)  # noqa: E731
    else:
        q_idx = lambda b, j, i: (b, i, 0)       # noqa: E731
        kv_idx = lambda b, j, i: (b // G, j, 0)  # noqa: E731

    q_spec = pl.BlockSpec((1, cfg.block_q, D), q_idx)
    kv_spec = pl.BlockSpec((1, cfg.block_k, D), kv_idx)
    # lse/delta are [BH, S, 128] (value broadcast over the 128-lane dim —
    # TPU tiling needs the last two block dims (block_q, 128)).
    row_spec = pl.BlockSpec((1, cfg.block_q, 128), q_idx)
    return q_spec, kv_spec, row_spec


def _flash_fwd_call(q, k, v, cfg: _FlashCfg):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape
    grid = (BH, S // cfg.block_q, S // cfg.block_k)
    q_spec, kv_spec, row_spec = _specs(cfg, D, kv_from_j=True)

    return pl.pallas_call(
        functools.partial(_fwd_kernel, cfg=cfg),
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[
            pl.BlockSpec((1, cfg.block_q, D), lambda b, i, j: (b, i, 0)),
            row_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_q, D), jnp.float32),
            pltpu.VMEM((cfg.block_q, 128), jnp.float32),
            pltpu.VMEM((cfg.block_q, 128), jnp.float32),
        ],
        interpret=cfg.interpret,
    )(q, k, v)


def _flash_bwd_call(q, k, v, o, lse, do, cfg: _FlashCfg):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape
    # delta_i = rowsum(dO_i · O_i) — cheap, XLA fuses it. Broadcast over the
    # 128-lane dim to match the lse tiling layout.
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)[..., None],
        (BH, S, 128),
    )

    q_spec, kv_spec, row_spec = _specs(cfg, D, kv_from_j=True)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, cfg=cfg),
        grid=(BH, S // cfg.block_q, S // cfg.block_k),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=pl.BlockSpec((1, cfg.block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((cfg.block_q, D), jnp.float32)],
        interpret=cfg.interpret,
    )(q, k, v, do, lse, delta)

    q_spec, kv_spec, row_spec = _specs(cfg, D, kv_from_j=False)
    dkx, dvx = pl.pallas_call(
        functools.partial(_dkv_kernel, cfg=cfg),
        grid=(BH, S // cfg.block_k, S // cfg.block_q),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=[
            pl.BlockSpec((1, cfg.block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, cfg.block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), k.dtype),
            jax.ShapeDtypeStruct((BH, S, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_k, D), jnp.float32),
            pltpu.VMEM((cfg.block_k, D), jnp.float32),
        ],
        interpret=cfg.interpret,
    )(q, k, v, do, lse, delta)

    # Per-query-head dK/dV → per-KV-head (sum the G group members).
    G = cfg.groups
    if G > 1:
        BKV = BH // G
        dkx = dkx.reshape(BKV, G, S, D).sum(axis=1).astype(k.dtype)
        dvx = dvx.reshape(BKV, G, S, D).sum(axis=1).astype(v.dtype)
    return dq, dkx, dvx


# ---------------------------------------------------------- custom VJP


def _flash_fwd(q, k, v, cfg: _FlashCfg):
    o, lse = _flash_fwd_call(q, k, v, cfg)
    # The kernel emits lse as [BH, S, 128] (value broadcast over the lane
    # dim — TPU tiling); storing that as the fwd→bwd residual would cost
    # 128x the bytes of the [BH, S] values it holds (134 MB/layer at 8B
    # shapes). Save the slim column and re-broadcast in backward.
    return o, (q, k, v, o, lse[:, :, 0])


def _flash_bwd(cfg: _FlashCfg, res, do):
    import jax.numpy as jnp

    q, k, v, o, lse_slim = res
    lse = jnp.broadcast_to(lse_slim[..., None], lse_slim.shape + (128,))
    return _flash_bwd_call(q, k, v, o, lse, do, cfg)


_FLASH = None


def _flash(q, k, v, cfg: _FlashCfg):
    """The differentiable core on [B·H, S, D] layout (lazily built so this
    module imports without jax)."""
    global _FLASH
    if _FLASH is None:
        import jax

        @functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
        def f(q, k, v, cfg):
            return _flash_fwd(q, k, v, cfg)[0]

        f.defvjp(_flash_fwd, _flash_bwd)
        _FLASH = f
    return _FLASH(q, k, v, cfg)


# ------------------------------------------------------------- public API


def _plan_tiling(S: int, D: int, block_q: int, block_k: int, interpret: bool):
    """Resolve block sizes and padded dims for a (possibly unaligned)
    shape: returns ``(block_q, block_k, S_pad, D_pad)`` with
    ``S_pad % block_q == S_pad % block_k == 0`` and, on real TPU
    (``interpret=False``), Mosaic's tiling minima honored: q-blocks
    sublane-aligned (%8), k-blocks and D lane-aligned (%128). Pure
    arithmetic — unit-testable for the TPU branch on any backend."""
    min_bq, min_bk = (8, 128) if not interpret else (1, 1)
    D_pad = -(-D // 128) * 128 if not interpret else D
    align = max(min_bq, min_bk)
    S_min = -(-S // align) * align  # smallest aligned padded length
    block_q = -(-min(block_q, S_min) // min_bq) * min_bq
    block_k = -(-min(block_k, S_min) // min_bk) * min_bk
    lcm = block_q * block_k // math.gcd(block_q, block_k)
    if lcm > max(block_q, block_k):
        # Unequal blocks where neither divides the other would pad S up
        # to their lcm — potentially several silent extra blocks of
        # work. Collapse both to the smaller size (lane-aligned, which
        # also satisfies the sublane minimum): at most one padded block.
        lcm = block_q = block_k = max(
            min(block_q, block_k) // min_bk * min_bk, min_bk
        )
    return block_q, block_k, -(-S // lcm) * lcm, D_pad


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    block_q: int = 1024,
    block_k: int = 1024,
    kv_len: Optional[int] = None,
    mesh=None,
    interpret: Optional[bool] = None,
):
    """Blockwise (flash) attention. q ``[B,S,H,D]``; k, v ``[B,S,KH,D]``
    with ``H % KH == 0`` (GQA). Returns ``[B,S,H,D]`` in q's dtype.

    Assumes rotary/positional encoding is already applied and token order
    is the standard causal layout (positions = arange).

    Shapes that don't fit the kernel's tiling (S not divisible by the
    block sizes; on real TPU also D % 128 != 0) are zero-PADDED to
    alignment and masked: padded key columns score -inf via the kernel's
    ``kv_len`` mask, padded query rows are sliced off the output, and the
    softmax scale stays 1/sqrt(true D) — numerics equal the dense oracle
    (round 4; previously these shapes fell back to the dense O(S^2)
    path, which materializes [B,H,S,S] f32 scores). Cost honesty:
    S-padding is bounded by one extra block row/column, but D-padding
    MULTIPLIES the attention FLOPs and q/k/v/o bytes by D_pad/D (2x for
    D=64) — a win at long S where the kernel's O(S·D) HBM beats the
    dense path's O(S^2) (measured 2.9x at S=5000, BASELINE.md), NOT for
    short-S/thin-D models: ViT-B (S=197, D=64) measured 41% SLOWER
    under the padded kernel than dense XLA and keeps its dense default.

    ``kv_len``: static TRUE sequence length when the caller's batch is
    already padded to S — keys/values at positions >= kv_len are masked
    out. One length for the whole batch (per-example lengths would need
    an array operand; compose ragged batches with segment packing
    instead).

    Default block sizes were swept on a TPU v5 lite chip. Round 2's
    kernel-level sweep picked 512/1024 (matches or beats the in-tree
    pallas kernel); round 3 re-swept END-TO-END in the 0.3b train step
    (fwd+bwd under 'dots' remat), where 1024/1024 wins consistently —
    +3.4% at S=4096 to +7.4% at S=16384 (BASELINE.md) — and stays
    within VMEM with double buffering at D=128.

    ``mesh``: wrap in a partial-manual shard_map over the batch (dp, fsdp)
    and head (tp) mesh axes so the kernel composes with pjit sharding.
    ``interpret``: force pallas interpret mode; default = auto (on for CPU
    backends, where tests run; off on TPU).
    """
    import jax

    B, S, H, D = q.shape
    KH = k.shape[2]
    assert H % KH == 0, f"H={H} not a multiple of KH={KH}"
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if kv_len is not None and not 0 < kv_len <= S:
        raise ValueError(f"kv_len={kv_len} outside (0, S={S}]")

    block_q, block_k, S_pad, D_pad = _plan_tiling(
        S, D, block_q, block_k, interpret
    )
    if S_pad != S and kv_len is None:
        kv_len = S  # padded key columns must not attend
    cfg = _FlashCfg(
        causal, block_q, block_k, H // KH, interpret,
        1.0 / math.sqrt(D), kv_len,
    )

    def core(q, k, v):
        b, s, h, d = q.shape
        kh = k.shape[2]
        pad = [(0, 0), (0, S_pad - s), (0, 0), (0, D_pad - d)]
        if S_pad != s or D_pad != d:
            q, k, v = (jax.numpy.pad(x, pad) for x in (q, k, v))
        q3 = q.transpose(0, 2, 1, 3).reshape(b * h, S_pad, D_pad)
        k3 = k.transpose(0, 2, 1, 3).reshape(b * kh, S_pad, D_pad)
        v3 = v.transpose(0, 2, 1, 3).reshape(b * kh, S_pad, D_pad)
        o3 = _flash(q3, k3, v3, cfg)
        o = o3.reshape(b, h, S_pad, D_pad).transpose(0, 2, 1, 3)
        return o[:, :s, :, :d]

    def live(axes):
        return [a for a in axes if mesh is not None and a in mesh.axis_names and mesh.shape[a] > 1]

    # Take manual control only of axes that evenly divide the operand dims
    # (e.g. flax's init traces with batch=1 — leave dp/fsdp to the compiler
    # there; it replicates, which is correct for tracing).
    batch_axes = live(("dp", "fsdp"))
    if batch_axes and B % math.prod(mesh.shape[a] for a in batch_axes):
        batch_axes = []
    tp_axes = live(("tp",))
    if tp_axes and KH % mesh.shape["tp"]:
        tp_axes = []
    manual = batch_axes + tp_axes
    if not manual:
        return core(q, k, v)

    from ..jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    batch = tuple(batch_axes) or None
    if isinstance(batch, tuple) and len(batch) == 1:
        batch = batch[0]
    tp = "tp" if tp_axes else None
    q_spec = P(batch, None, tp, None)
    return shard_map(
        core,
        mesh=mesh,
        in_specs=(q_spec, q_spec, q_spec),
        out_specs=q_spec,
        axis_names=set(manual),
        # pallas_call out_shapes carry no varying-mesh-axes metadata, so
        # jax 0.9's VMA check cannot see through the kernel — disable it
        # for this wrapper (shardings are fully specified above).
        check_vma=False,
    )(q, k, v)


def _dense_reference(q, k, v, *, causal: bool):
    """XLA fallback — also the numerics oracle in tests."""
    import jax
    import jax.numpy as jnp

    B, S, H, D = q.shape
    KH = k.shape[2]
    G = H // KH
    qg = q.reshape(B, S, KH, G, D)
    s = jnp.einsum(
        "bskgd,btkd->bkgst", qg, k, preferred_element_type=jnp.float32
    ) / math.sqrt(D)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), dtype=bool))
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        "bkgst,btkd->bskgd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, S, H, D).astype(q.dtype)
