"""Memory-efficient softmax cross-entropy for large-vocab LM heads.

The dense path materializes ``[N, V]`` float32 logits (plus their gradient):
for Llama-3-8B shapes (V=128256) that is ~1 GB per 2048 tokens and it is
pure HBM traffic. This op fuses the LM-head matmul with the loss: a
``lax.scan`` over vocab chunks keeps only ``[N, chunk]`` live, carrying the
online logsumexp (running max + scaled sum — the same trick flash attention
uses along the key axis, applied to the vocab axis), and the backward pass
recomputes each chunk's logits instead of saving them.

Weight access is by ``lax.dynamic_slice_in_dim`` along the vocab axis — no
reshape/transpose relayout of the full ``[D, V]`` weight is ever created.
A vocab that does not divide into chunks is handled by clamped tail slices
with already-counted columns masked out (no padding copy either).

Reference parity note: nothing like this exists in the reference (its loss
is whatever the user container does); this is a beyond-parity TPU
optimization for the BASELINE.json:10 Llama workload.

HBM cost per step: O(N*chunk) activations instead of O(N*V); the weight
gradient is still [D, V] (it is a parameter gradient, unavoidable).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def chunked_softmax_xent(hidden, w, labels, *, chunk: int = 8192):
    """Per-token ``-log p(label)`` without materializing ``[N, V]`` logits.

    hidden ``[N, D]`` (bf16/f32), w ``[D, V]`` (the lm_head kernel),
    labels ``[N]`` int. Returns float32 ``[N]``. Gradients flow to
    ``hidden`` and ``w``; logits math is float32 regardless of input dtype
    (matching the dense path, whose head computes in f32).

    Out-of-range labels clamp to [0, V) — a DEFINED behavior where the
    dense path (optax integer-label xent) yields NaN and the previous
    chunked behavior silently returned plain lse. Padding/ignore tokens
    should be masked out of the mean, not encoded as sentinel label ids;
    the clamp just guarantees a stray id can't poison the loss.
    """
    N, D = hidden.shape
    D2, V = w.shape
    assert D == D2, f"hidden D={D} vs w D={D2}"
    c = min(chunk, V)
    n_chunks = -(-V // c)  # ceil — tail chunk is a clamped, masked slice
    labels = jnp.clip(labels.astype(jnp.int32), 0, V - 1)
    return _xent(hidden, w, labels, n_chunks, c)


def chunked_vocab_stats(hidden, w, labels, *, chunk: int = 8192, col_offset=0):
    """Online softmax partial stats of ``hidden @ w`` for a (possibly
    vocab-sharded) head chunk — the combinable form of
    :func:`chunked_softmax_xent` for the pipeline's vocab-parallel loss
    tail (models/llama.py train_value_and_grad_pp). Returns f32 ``[N]``
    triples:

    - ``m``: max logit over THIS weight's columns (stop-gradient — the
      shift is numerics-only);
    - ``s``: sum of ``exp(logit - m)``;
    - ``lab_logit``: the label's logit where the GLOBAL label id falls in
      ``[col_offset, col_offset + w.shape[1])``, else 0.

    Owners combine across shards with one pmax + two psums:
    ``M = pmax(m); lse = M + log(psum(s * exp(m - M))); loss = lse -
    psum(lab_logit)``. Plain autodiff (no custom VJP): each sub-chunk
    body is ``jax.checkpoint``'d, so backward recomputes its ``[N,
    chunk]`` logits instead of saving one residual buffer per chunk —
    same peak-memory contract as chunked_softmax_xent. Pass
    ``chunk >= w.shape[1]`` for a single dense pass over the local
    columns.
    """
    N, D = hidden.shape
    D2, Vl = w.shape
    assert D == D2, f"hidden D={D} vs w D={D2}"
    c = min(chunk, Vl)
    n_chunks = -(-Vl // c)
    hidden32 = hidden.astype(jnp.float32)
    labels = labels.astype(jnp.int32) - col_offset  # local column ids

    def body(carry, c_idx):
        m, s, lab_logit = carry
        w_c, start = _chunk_slice(w, c_idx, c)
        logits = hidden32 @ w_c.astype(jnp.float32)  # [N, c] f32
        logits = jnp.where(
            _fresh_mask(start, c_idx, c)[None, :], logits, -jnp.inf
        )
        m_new = jnp.maximum(
            m, jax.lax.stop_gradient(logits.max(axis=-1))
        )
        s = s * jnp.exp(m - m_new) + jnp.exp(logits - m_new[:, None]).sum(
            axis=-1
        )
        local = labels - start
        in_chunk = (labels >= c_idx * c) & (local < c) & (local >= 0)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, c - 1)[:, None], axis=-1
        )[:, 0]
        lab_logit = jnp.where(in_chunk, picked, lab_logit)
        return (m_new, s, lab_logit), None

    if n_chunks > 1:
        body = jax.checkpoint(body)
    init = _match_vma(
        (
            jnp.full((N,), -jnp.inf, jnp.float32),
            jnp.zeros((N,), jnp.float32),
            jnp.zeros((N,), jnp.float32),
        ),
        hidden,
    )
    (m, s, lab_logit), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    return m, s, lab_logit


def _aval(v):
    """jax.typeof with a fallback for jax versions that predate it (the
    vma machinery doesn't exist there either, so callers see no varying
    axes and degrade to identity)."""
    try:
        return jax.typeof(v)
    except AttributeError:
        return jax.core.get_aval(v)


def _match_vma(tree, ref):
    """pcast every leaf of ``tree`` to carry ``ref``'s varying manual
    axes (shard_map vma) — makes freshly-built scan carries type-stable
    when this op runs inside a manual region. Identity elsewhere (and on
    jax versions without vma/pcast, where types are never vma-annotated)."""
    vma = getattr(_aval(ref), "vma", frozenset())
    if not vma or not hasattr(jax.lax, "pcast"):
        return tree
    return jax.tree.map(
        lambda v: (
            v
            if set(getattr(_aval(v), "vma", frozenset())) >= set(vma)
            else jax.lax.pcast(v, tuple(vma), to="varying")
        ),
        tree,
    )


def _chunk_slice(w, c_idx, chunk):
    """``w[:, start : start+chunk]`` with the clamped start dynamic_slice
    uses; returns (w_chunk, start). For the tail chunk start < c_idx*chunk,
    so some columns repeat — callers mask them (global col < c_idx*chunk)."""
    V = w.shape[1]
    start = jnp.minimum(c_idx * chunk, V - chunk)
    return jax.lax.dynamic_slice_in_dim(w, start, chunk, axis=1), start


def _fresh_mask(start, c_idx, chunk):
    """True for columns not already counted by earlier chunks."""
    global_col = start + jnp.arange(chunk)
    return global_col >= c_idx * chunk


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _xent(hidden, w, labels, n_chunks: int, chunk: int):
    loss, _ = _xent_fwd(hidden, w, labels, n_chunks, chunk)
    return loss


def _xent_fwd(hidden, w, labels, n_chunks: int, chunk: int):
    N, D = hidden.shape
    hidden32 = hidden.astype(jnp.float32)

    def body(carry, c_idx):
        m, s, lab_logit = carry
        w_c, start = _chunk_slice(w, c_idx, chunk)
        logits = hidden32 @ w_c.astype(jnp.float32)  # [N, chunk] f32
        logits = jnp.where(
            _fresh_mask(start, c_idx, chunk)[None, :], logits, -jnp.inf
        )
        m_new = jnp.maximum(m, logits.max(axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.exp(logits - m_new[:, None]).sum(axis=-1)
        local = labels - start
        in_chunk = (labels >= c_idx * chunk) & (local < chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[:, None], axis=-1
        )[:, 0]
        lab_logit = jnp.where(in_chunk, picked, lab_logit)
        return (m_new, s, lab_logit), None

    init = (
        jnp.full((N,), -jnp.inf, jnp.float32),
        jnp.zeros((N,), jnp.float32),
        jnp.zeros((N,), jnp.float32),
    )
    # Inside a shard_map manual region (the 1F1B pipeline's loss tail)
    # the scan body is axis-varying via hidden/w while these fresh zeros
    # are invariant — pcast so the carry types agree. No-op outside
    # manual regions (vma is empty there).
    init = _match_vma(init, hidden)
    (m, s, lab_logit), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    lse = m + jnp.log(s)
    return lse - lab_logit, (hidden, w, labels, lse)


def _xent_bwd(n_chunks: int, chunk: int, res, ct):
    """Recompute each chunk's logits; accumulate dW in place via
    dynamic_update_slice (read-add-write on a [D, V] carry), dH via matmul."""
    hidden, w, labels, lse = res
    N, D = hidden.shape
    hidden32 = hidden.astype(jnp.float32)
    ct32 = ct.astype(jnp.float32)

    def body(carry, c_idx):
        dh, dw = carry
        w_c, start = _chunk_slice(w, c_idx, chunk)
        w_c32 = w_c.astype(jnp.float32)
        p = jnp.exp(hidden32 @ w_c32 - lse[:, None])  # softmax chunk
        local = labels - start
        in_chunk = (labels >= c_idx * chunk) & (local < chunk)
        g = p * ct32[:, None]  # [N, chunk]
        # Label correction as a scatter-add, NOT a materialized one-hot —
        # a second [N, chunk] buffer here is what blows peak HBM at the
        # batch sizes this op exists for.
        g = g.at[jnp.arange(g.shape[0]), jnp.clip(local, 0, chunk - 1)].add(
            -ct32 * in_chunk,
            # One update per row, rows ascending: let XLA skip the
            # collision-safe scatter lowering.
            unique_indices=True,
            indices_are_sorted=True,
        )
        # Tail chunk: zero the already-counted columns so the overlapped
        # read-add-write below cannot double-contribute.
        g = g * _fresh_mask(start, c_idx, chunk)[None, :]
        dh = dh + g @ w_c32.T
        dw_c = jax.lax.dynamic_slice_in_dim(dw, start, chunk, axis=1)
        dw = jax.lax.dynamic_update_slice_in_dim(
            dw, dw_c + hidden32.T @ g, start, axis=1
        )
        return (dh, dw), None

    (dh, dw), _ = jax.lax.scan(
        body,
        _match_vma(
            (jnp.zeros((N, D), jnp.float32), jnp.zeros(w.shape, jnp.float32)),
            hidden,
        ),
        jnp.arange(n_chunks),
    )
    zeros_lab = np.zeros(labels.shape, jax.dtypes.float0)
    return dh.astype(hidden.dtype), dw.astype(w.dtype), zeros_lab


_xent.defvjp(_xent_fwd, _xent_bwd)
