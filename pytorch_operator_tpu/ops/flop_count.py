"""Semantic FLOP counting by jaxpr traversal.

The torch analog is ``torch.utils.flop_counter.FlopCounterMode`` (counts
matmul/conv FLOPs under a context manager); here the traced jaxpr IS the
program, so counting is a pure tree walk — no execution, no hooks.

Why not XLA's ``compiled.cost_analysis()`` or ``jax.experimental.roofline``:
both count a ``scan``/``while`` BODY ONCE, ignoring the trip count (verified
on this install — a 10-iteration scan of a matmul reports one matmul), which
makes them useless for comparing pipelined programs whose entire compute
lives inside a 2(P-1)+M-tick scan. This walker multiplies scan bodies by
their trip count and shard_map bodies by the manual-axes device count, so
the result is TOTAL semantic FLOPs across the mesh — directly comparable
between a sharded pipeline step and a single-device reference step.

Counting rules (deliberately simple, stable under comparison since both
sides of any A/B use the same rules):

- ``dot_general``: 2 x out_elements x contracted_elements (the MXU term).
- ``conv_general_dilated``: 2 x out_elements x kernel_spatial x C_in/groups.
- control flow: ``scan`` body x length; ``cond``/branches -> max branch
  (one branch executes); ``while`` body x 1 (trip count unknowable --
  callers comparing loops should prefer scan); ``pallas_call`` body x
  grid size.
- structure/layout/communication ops: 0 FLOPs.
- everything else: 1 FLOP per output element (elementwise/reduction work;
  transcendentals deliberately not weighted -- they are a rounding error
  next to the dot terms this exists to compare).

Total-vs-useful caveat: masked/garbage work (e.g. pipeline bubble ticks)
counts at face value — that is the point: the pipeline-overhead test uses
this to bound TOTAL executed work against the sequential reference.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

# Ops that move/route/reshape data or communicate — no arithmetic.
_ZERO_FLOPS = frozenset(
    {
        "broadcast_in_dim", "reshape", "transpose", "squeeze", "slice",
        "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
        "rev", "iota", "copy", "convert_element_type", "bitcast_convert_type",
        "gather", "device_put", "stop_gradient", "pcast", "pvary",
        "psum", "pmax", "pmin", "ppermute", "all_gather", "all_to_all",
        "axis_index", "reduce_scatter", "sharding_constraint",
        "split", "select_n",
    }
)


@dataclass
class FlopCount:
    """Result of :func:`count_flops`: total + a per-primitive breakdown."""

    total: float = 0.0
    by_primitive: dict = field(default_factory=dict)

    def _add(self, name: str, flops: float, scale: float) -> None:
        self.total += flops * scale
        self.by_primitive[name] = self.by_primitive.get(name, 0.0) + flops * scale

    def _merge(self, other: "FlopCount") -> None:
        self.total += other.total
        for k, v in other.by_primitive.items():
            self.by_primitive[k] = self.by_primitive.get(k, 0.0) + v


def _size(aval) -> int:
    return math.prod(aval.shape) if aval.shape else 1


def _eqn_flops(eqn) -> float:
    """FLOPs of one non-control-flow equation."""
    name = eqn.primitive.name
    if name in _ZERO_FLOPS:
        return 0.0
    if name == "dot_general":
        (lhs_c, _rhs_c), _batch = eqn.params["dimension_numbers"]
        lhs = eqn.invars[0].aval
        contracted = math.prod(lhs.shape[d] for d in lhs_c) or 1
        return 2.0 * _size(eqn.outvars[0].aval) * contracted
    if name == "conv_general_dilated":
        rhs = eqn.invars[1].aval  # kernel
        dn = eqn.params["dimension_numbers"]
        spatial = math.prod(rhs.shape[d] for d in dn.rhs_spec[2:]) or 1
        c_in = rhs.shape[dn.rhs_spec[1]]
        return 2.0 * _size(eqn.outvars[0].aval) * spatial * c_in
    # Default: one op per output element (elementwise / reductions).
    return float(sum(_size(v.aval) for v in eqn.outvars))


def _sub_jaxpr(v):
    """Unwrap ClosedJaxpr-or-Jaxpr params to a raw Jaxpr."""
    return v.jaxpr if hasattr(v, "jaxpr") else v


def _traverse(jaxpr, scale: float, acc, visit, shard_map_mult, score) -> None:
    """One traversal skeleton for every counter in this module: scan
    bodies x trip count, pallas bodies x grid, cond -> max-scoring branch
    (one executes), while -> one iteration (documented caveat). ``visit``
    handles leaf equations; ``shard_map_mult`` decides the per-manual-
    device multiplier (mesh-total for FLOPs, per-device for comm);
    ``score`` ranks cond branches. ``acc`` needs ``_merge``."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "scan":
            _traverse(
                _sub_jaxpr(eqn.params["jaxpr"]),
                scale * eqn.params["length"],
                acc, visit, shard_map_mult, score,
            )
        elif name == "while":
            _traverse(
                _sub_jaxpr(eqn.params["body_jaxpr"]), scale,
                acc, visit, shard_map_mult, score,
            )
            _traverse(
                _sub_jaxpr(eqn.params["cond_jaxpr"]), scale,
                acc, visit, shard_map_mult, score,
            )
        elif name == "cond":
            branch_accs = []
            for b in eqn.params["branches"]:
                sub = type(acc)()
                _traverse(_sub_jaxpr(b), scale, sub, visit, shard_map_mult, score)
                branch_accs.append(sub)
            if branch_accs:
                acc._merge(max(branch_accs, key=score))
        elif name == "shard_map":
            mesh = eqn.params["mesh"]
            manual = eqn.params.get("manual_axes") or ()
            n_dev = math.prod(mesh.shape[a] for a in manual) or 1
            _traverse(
                _sub_jaxpr(eqn.params["jaxpr"]),
                scale * shard_map_mult(n_dev),
                acc, visit, shard_map_mult, score,
            )
        elif name == "pallas_call":
            # The kernel body runs once per grid cell.
            grid = getattr(eqn.params["grid_mapping"], "grid", ())
            n_cells = math.prod(g for g in grid if isinstance(g, int)) or 1
            _traverse(
                _sub_jaxpr(eqn.params["jaxpr"]), scale * n_cells,
                acc, visit, shard_map_mult, score,
            )
        elif "jaxpr" in eqn.params:
            # pjit / remat2 / closed_call / custom_* wrappers.
            _traverse(
                _sub_jaxpr(eqn.params["jaxpr"]), scale,
                acc, visit, shard_map_mult, score,
            )
        elif "call_jaxpr" in eqn.params:
            _traverse(
                _sub_jaxpr(eqn.params["call_jaxpr"]), scale,
                acc, visit, shard_map_mult, score,
            )
        else:
            visit(acc, eqn, scale)


_COMM = frozenset(
    {
        "psum", "pmax", "pmin", "ppermute", "all_gather", "all_to_all",
        "reduce_scatter", "psum_scatter", "pbroadcast",
    }
)


@dataclass
class CollectiveCount:
    """Result of :func:`count_collectives`: per-primitive call counts and
    payload bytes (operand bytes per device per call — "bytes sent", not
    link-level wire cost, which depends on the algorithm/topology)."""

    calls: dict = field(default_factory=dict)
    bytes: dict = field(default_factory=dict)

    @property
    def total_calls(self) -> float:
        return sum(self.calls.values())

    @property
    def total_bytes(self) -> float:
        return sum(self.bytes.values())

    def _add(self, name: str, n_bytes: float, scale: float) -> None:
        self.calls[name] = self.calls.get(name, 0.0) + scale
        self.bytes[name] = self.bytes.get(name, 0.0) + n_bytes * scale

    def _merge(self, other: "CollectiveCount") -> None:
        for k, v in other.calls.items():
            self.calls[k] = self.calls.get(k, 0.0) + v
        for k, v in other.bytes.items():
            self.bytes[k] = self.bytes.get(k, 0.0) + v


def _comm_bytes(eqn) -> float:
    total = 0.0
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if aval is not None and hasattr(aval, "shape"):
            total += _size(aval) * aval.dtype.itemsize
    return total


def _visit_comm(acc: CollectiveCount, eqn, scale: float) -> None:
    if eqn.primitive.name in _COMM:
        acc._add(eqn.primitive.name, _comm_bytes(eqn), scale)


def count_collectives(fn, *args, **kwargs) -> CollectiveCount:
    """Per-device collective-communication profile of ``fn(*args)``:
    how many times each collective primitive executes (scan-aware) and
    the payload bytes it moves. Traces abstractly — nothing executes, so
    counting a 32k-sequence program is free. The companion to
    :func:`count_flops` for comparing communication regimes (e.g. ring
    vs Ulysses sequence parallelism)."""
    import jax

    closed = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    out = CollectiveCount()
    _traverse(
        closed.jaxpr, 1.0, out, _visit_comm,
        # Per-DEVICE accounting (unlike count_flops' mesh total): "bytes
        # this chip puts on the ICI" is the comparable metric.
        shard_map_mult=lambda n_dev: 1,
        score=lambda c: c.total_bytes,
    )
    return out


def count_flops(fn, *args, **kwargs) -> FlopCount:
    """Total semantic FLOPs of ``fn(*args, **kwargs)`` across the mesh.

    Traces with ``jax.make_jaxpr`` (abstract — nothing executes) and walks
    the jaxpr with the module-level rules. Returns a :class:`FlopCount`
    whose ``total`` is comparable between differently-sharded versions of
    the same computation.
    """
    import jax

    closed = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    out = FlopCount()
    _traverse(
        closed.jaxpr, 1.0, out,
        lambda acc, eqn, scale: acc._add(
            eqn.primitive.name, _eqn_flops(eqn), scale
        ),
        shard_map_mult=lambda n_dev: n_dev,
        score=lambda c: c.total,
    )
    return out
