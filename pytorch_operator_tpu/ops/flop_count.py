"""Semantic FLOP counting by jaxpr traversal.

The torch analog is ``torch.utils.flop_counter.FlopCounterMode`` (counts
matmul/conv FLOPs under a context manager); here the traced jaxpr IS the
program, so counting is a pure tree walk — no execution, no hooks.

Why not XLA's ``compiled.cost_analysis()`` or ``jax.experimental.roofline``:
both count a ``scan``/``while`` BODY ONCE, ignoring the trip count (verified
on this install — a 10-iteration scan of a matmul reports one matmul), which
makes them useless for comparing pipelined programs whose entire compute
lives inside a 2(P-1)+M-tick scan. This walker multiplies scan bodies by
their trip count and shard_map bodies by the manual-axes device count, so
the result is TOTAL semantic FLOPs across the mesh — directly comparable
between a sharded pipeline step and a single-device reference step.

Counting rules (deliberately simple, stable under comparison since both
sides of any A/B use the same rules):

- ``dot_general``: 2 x out_elements x contracted_elements (the MXU term).
- ``conv_general_dilated``: 2 x out_elements x kernel_spatial x C_in/groups.
- control flow: ``scan`` body x length; ``cond``/branches -> max branch
  (one branch executes); ``while`` body x 1 (trip count unknowable --
  callers comparing loops should prefer scan); ``pallas_call`` body x
  grid size.
- structure/layout/communication ops: 0 FLOPs.
- everything else: 1 FLOP per output element (elementwise/reduction work;
  transcendentals deliberately not weighted -- they are a rounding error
  next to the dot terms this exists to compare).

Total-vs-useful caveat: masked/garbage work (e.g. pipeline bubble ticks)
counts at face value — that is the point: the pipeline-overhead test uses
this to bound TOTAL executed work against the sequential reference.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

# Ops that move/route/reshape data or communicate — no arithmetic.
_ZERO_FLOPS = frozenset(
    {
        "broadcast_in_dim", "reshape", "transpose", "squeeze", "slice",
        "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
        "rev", "iota", "copy", "convert_element_type", "bitcast_convert_type",
        "gather", "device_put", "stop_gradient", "pcast", "pvary",
        "psum", "pmax", "pmin", "ppermute", "all_gather", "all_to_all",
        "axis_index", "reduce_scatter", "sharding_constraint",
        "split", "select_n",
    }
)


@dataclass
class FlopCount:
    """Result of :func:`count_flops`: total + a per-primitive breakdown."""

    total: float = 0.0
    by_primitive: dict = field(default_factory=dict)

    def _add(self, name: str, flops: float, scale: float) -> None:
        self.total += flops * scale
        self.by_primitive[name] = self.by_primitive.get(name, 0.0) + flops * scale


def _size(aval) -> int:
    return math.prod(aval.shape) if aval.shape else 1


def _eqn_flops(eqn) -> float:
    """FLOPs of one non-control-flow equation."""
    name = eqn.primitive.name
    if name in _ZERO_FLOPS:
        return 0.0
    if name == "dot_general":
        (lhs_c, _rhs_c), _batch = eqn.params["dimension_numbers"]
        lhs = eqn.invars[0].aval
        contracted = math.prod(lhs.shape[d] for d in lhs_c) or 1
        return 2.0 * _size(eqn.outvars[0].aval) * contracted
    if name == "conv_general_dilated":
        rhs = eqn.invars[1].aval  # kernel
        dn = eqn.params["dimension_numbers"]
        spatial = math.prod(rhs.shape[d] for d in dn.rhs_spec[2:]) or 1
        c_in = rhs.shape[dn.rhs_spec[1]]
        return 2.0 * _size(eqn.outvars[0].aval) * spatial * c_in
    # Default: one op per output element (elementwise / reductions).
    return float(sum(_size(v.aval) for v in eqn.outvars))


def _sub_jaxpr(v):
    """Unwrap ClosedJaxpr-or-Jaxpr params to a raw Jaxpr."""
    return v.jaxpr if hasattr(v, "jaxpr") else v


def _walk(jaxpr, scale: float, out: FlopCount) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "scan":
            _walk(
                _sub_jaxpr(eqn.params["jaxpr"]),
                scale * eqn.params["length"],
                out,
            )
        elif name == "while":
            # Trip count is data-dependent; count one iteration of body
            # + cond (documented caveat).
            _walk(_sub_jaxpr(eqn.params["body_jaxpr"]), scale, out)
            _walk(_sub_jaxpr(eqn.params["cond_jaxpr"]), scale, out)
        elif name == "cond":
            branch_counts = []
            for b in eqn.params["branches"]:
                sub = FlopCount()
                _walk(_sub_jaxpr(b), scale, sub)
                branch_counts.append(sub)
            if branch_counts:
                biggest = max(branch_counts, key=lambda c: c.total)
                out.total += biggest.total
                for k, v in biggest.by_primitive.items():
                    out.by_primitive[k] = out.by_primitive.get(k, 0.0) + v
        elif name == "shard_map":
            mesh = eqn.params["mesh"]
            manual = eqn.params.get("manual_axes") or ()
            n_dev = math.prod(mesh.shape[a] for a in manual) or 1
            _walk(_sub_jaxpr(eqn.params["jaxpr"]), scale * n_dev, out)
        elif name == "pallas_call":
            # The kernel body runs once per grid cell.
            grid = getattr(eqn.params["grid_mapping"], "grid", ())
            n_cells = math.prod(g for g in grid if isinstance(g, int)) or 1
            _walk(_sub_jaxpr(eqn.params["jaxpr"]), scale * n_cells, out)
        elif "jaxpr" in eqn.params:
            # pjit / remat2 / closed_call / custom_* wrappers.
            _walk(_sub_jaxpr(eqn.params["jaxpr"]), scale, out)
        elif "call_jaxpr" in eqn.params:
            _walk(_sub_jaxpr(eqn.params["call_jaxpr"]), scale, out)
        else:
            out._add(name, _eqn_flops(eqn), scale)


def count_flops(fn, *args, **kwargs) -> FlopCount:
    """Total semantic FLOPs of ``fn(*args, **kwargs)`` across the mesh.

    Traces with ``jax.make_jaxpr`` (abstract — nothing executes) and walks
    the jaxpr with the module-level rules. Returns a :class:`FlopCount`
    whose ``total`` is comparable between differently-sharded versions of
    the same computation.
    """
    import jax

    closed = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    out = FlopCount()
    _walk(closed.jaxpr, 1.0, out)
    return out
