"""Weight-only int8 quantization for the inference (decode) path.

Reference analog: none — the reference is a training operator and any
quantization lives in its user containers. The rebuild motivation is
BASELINE.md's own decode analysis: at 0.3b scale the decode step is
bound by a per-step issue floor (bf16 weights measured only +4% over
f32), but the step becomes weight-STREAMING bound as the model grows —
and at 8B the bf16 weights alone (16 GB) exceed a v5e chip's HBM, so
the flagship config cannot decode on one chip at all without shrinking
the bytes. Symmetric per-channel int8 cuts the streamed weight bytes
4x vs f32 (2x vs bf16) at ~0.4% RMS weight error.

TPU-first mechanics, and why this is NOT a "dequantize then run" wrapper:

- Quantized leaves stay **int8 in HBM**. ``dequantize_tree`` is traced
  *inside* the jitted decode step, so the emitted HLO is
  ``convert(s8) * scale`` feeding each matmul — XLA fuses that
  elementwise chain into the dot's operand read (the same fusion this
  tree already leans on for its f32-param → bf16-compute casts
  everywhere), so no full-size bf16/f32 copy of the weights ever
  materializes; the per-step HBM traffic is the int8 bytes.
- Inside ``lax.scan`` decode loops the dequant is loop-invariant, but
  XLA's while-loop code motion declines to hoist size-inflating ops
  (a convert s8→f32 quadruples bytes), so the fusion — and the memory
  win — survives the scan. Verified empirically by the 8B-on-one-chip
  measurement in BASELINE.md (a hoisted dequant would OOM instantly).
- Scales are per-OUTPUT-channel over each weight's contraction axis
  (the axis the matmul reduces), the standard accuracy/shape trade:
  one f32 per output column, broadcast along the reduction.

Scope: inference only. Training keeps full-precision master weights
(``--param-dtype`` covers the bf16-params recipe); int8 *activation*
quantization (for MXU int8 matmul throughput) is a different trade and
deliberately out of scope — decode is bandwidth-bound, not FLOP-bound,
so weight-only captures the win without touching numerics of the
activations.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QuantizedTensor:
    """An int8-quantized weight: ``w ≈ q.astype(f32) * scale``.

    ``q`` keeps the original weight's shape; ``scale`` is f32 with the
    same rank, extent 1 along the quantization (contraction) axis —
    broadcastable, so ``dequantize`` is one fused convert+multiply.
    """

    q: jax.Array
    scale: jax.Array

    @property
    def shape(self):
        return self.q.shape

    def dequantize(self, dtype=jnp.float32) -> jax.Array:
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def quantize(w: jax.Array, axis: int) -> QuantizedTensor:
    """Symmetric per-channel int8: scale = max|w| / 127 over ``axis``."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, jnp.finfo(jnp.float32).tiny) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return QuantizedTensor(q=q.astype(jnp.int8), scale=scale)


def contract_axis(path: tuple, leaf: Any) -> int | None:
    """Which axis a matmul reduces for this param leaf, or None to keep
    the leaf unquantized.

    Name-based on the llama/bert param vocabulary, with NEGATIVE axes so
    scan-stacked leaves (leading ``layers`` axis) and unstacked leaves
    share one rule:

    - ``q/k/v_proj kernel`` ``[..., embed, heads, head_dim]`` → -3
    - any other ``kernel``  ``[..., in, out]``                → -2
      (o_proj, gate/up/down_proj, lm_head)
    - ``embedding``         ``[..., vocab, embed]`` → -1 (per-row: the
      lookup "reduces" nothing, but decode streams the whole table for
      the head-tied case and rows are the natural channel)
    - MoE expert banks ``w_in``/``w_out`` ``[..., E, in, out]`` → -2
    - everything else (norm ``scale``s, MoE router ``gate``, biases):
      None — tiny, and the router's argmax is precision-sensitive.
    """
    name = str(path[-1]) if path else ""
    parent = str(path[-2]) if len(path) > 1 else ""
    if name == "embedding":
        axis = -1
    elif name == "kernel":
        axis = -3 if parent in ("q_proj", "k_proj", "v_proj") else -2
    elif name in ("w_in", "w_out"):
        axis = -2
    else:
        return None
    if getattr(leaf, "ndim", 0) < -axis:
        return None
    return axis


def quantize_tree(params, *, rule=contract_axis):
    """Quantize a (plain, unboxed) params tree's matmul weights to
    :class:`QuantizedTensor` leaves; non-weight leaves pass through.
    Jit-friendly (``jax.jit(quantize_tree)`` quantizes on-device).
    """

    def walk(node, path):
        if isinstance(node, Mapping):
            return type(node)(
                {k: walk(v, path + (k,)) for k, v in node.items()}
            )
        axis = rule(path, node)
        return node if axis is None else quantize(node, axis)

    return walk(params, ())


def dequantize_tree(tree, dtype=jnp.float32):
    """Map :class:`QuantizedTensor` leaves back to arrays (identity on
    plain trees). Call this INSIDE the jitted consumer — see module
    docstring — so the dequant fuses into the matmul operand reads
    instead of materializing a full-precision weight copy."""
    return jax.tree.map(
        lambda leaf: (
            leaf.dequantize(dtype) if isinstance(leaf, QuantizedTensor) else leaf
        ),
        tree,
        is_leaf=lambda leaf: isinstance(leaf, QuantizedTensor),
    )


def tree_bytes(tree) -> int:
    """Total payload bytes (QuantizedTensor counts q + scale)."""
    total = 0
    for leaf in jax.tree.leaves(
        tree, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    ):
        arrs = (leaf.q, leaf.scale) if isinstance(leaf, QuantizedTensor) else (leaf,)
        total += sum(a.size * a.dtype.itemsize for a in arrs)
    return total
