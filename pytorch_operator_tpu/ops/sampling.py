"""Token sampling for the decode paths (greedy / temperature / top-k /
nucleus), shared by the single-stream generate workload and the
continuous-batching serving engine.

Reference analog: none (the reference is a training operator). The
TPU-relevant shape choice: top-k and top-p mask off ONE shared
descending sort — the sort is the dominant sampling cost on the decode
hot path, so the knobs compose on a single O(V log V) pass instead of
two.
"""

from __future__ import annotations


def validate_sampling(temperature: float, top_k: int, top_p: float) -> None:
    """The shared front-door checks (ValueError on bad knobs)."""
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p={top_p} not in (0, 1]")
    if top_k < 0:
        raise ValueError(f"top_k={top_k} must be 0 (off) or >= 1")
    if temperature == 0.0 and (top_k > 0 or top_p < 1.0):
        # T=0 short-circuits to argmax; silently ignoring the knobs
        # would hand every row the identical greedy rollout.
        raise ValueError(
            "top_k/top_p require temperature > 0 (temperature=0 is greedy)"
        )


def make_sampler(
    temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0
):
    """Build ``sample(logits [..., V], rng) -> tokens [...] int32``.

    Greedy at T=0, else categorical over the temperature-scaled logits
    with optional top-k and/or nucleus (top-p) truncation — static-shape
    masks off one shared descending sort. Nucleus composes on the
    top-k-truncated distribution (HF-style sequential semantics).
    """
    import jax
    import jax.numpy as jnp

    validate_sampling(temperature, top_k, top_p)

    def sample(logits, rng):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / temperature
        neg = jnp.finfo(logits.dtype).min
        V = logits.shape[-1]
        if (0 < top_k < V) or top_p < 1.0:
            sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
            if 0 < top_k < V:
                # Keep the k highest logits: threshold at the k-th value
                # (ties at the threshold survive).
                kth = sorted_desc[..., top_k - 1 : top_k]
                logits = jnp.where(logits < kth, neg, logits)
                sorted_desc = jnp.where(
                    jnp.arange(V) >= top_k, neg, sorted_desc
                )
            if top_p < 1.0:
                # Smallest token set whose cumulative probability
                # reaches top_p; the top token always survives.
                probs = jax.nn.softmax(sorted_desc, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                keep = jnp.sum(cum < top_p, axis=-1, keepdims=True)
                # float cumsum can fail to reach a top_p near 1.0 (and
                # saturates early under a composed top_k), making keep
                # == V; the always-keep-top-token invariant must not
                # rest on gather's implicit index clamping.
                keep = jnp.minimum(keep, V - 1)
                cutoff = jnp.take_along_axis(sorted_desc, keep, axis=-1)
                logits = jnp.where(logits < cutoff, neg, logits)
        return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)

    return sample
