"""Checkpoint integrity: checksum sidecars + last-verified-good scan.

The reference's resume story is "restart the pod, reload the
checkpoint" — which silently assumes the checkpoint on disk is intact.
Preempted hosts and torn writes break that assumption exactly when
recovery matters most. This module gives every step-keyed checkpoint
layout (``<root>/<step>/...files...``) a content-checksum sidecar
(``<root>/<step>.digest``) written AFTER the step commits, and a
restore-side scan that walks steps newest-first and returns the first
one whose bytes still match — the "last verified-good" fallback.

Verification is three-valued:

- ``True``   sidecar present and the digest matches — verified good;
- ``False``  sidecar present but the bytes changed — CORRUPT, skip it;
- ``None``   no sidecar (legacy checkpoint / non-blocking save) —
  unknown; accepted by default so pre-sidecar checkpoints keep
  restoring, but callers may demand strict verification.

Deliberately orbax-free and jax-free: the orbax-backed manager
(``manager.py``) and the lightweight JSON step files test workloads
write (``workloads/exit_with.py``) share these exact bytes-level code
paths, so the corruption detection tier-1 exercises without orbax is
the same detection production checkpoints get.
"""

from __future__ import annotations

import hashlib
import os
from pathlib import Path
from typing import Callable, Iterable, List, Optional

_CHUNK = 1 << 20


def step_digest(step_dir) -> str:
    """Order-independent-of-walk digest of every file under a step dir:
    blake2b over (relative path, size, content) in sorted path order."""
    step_dir = Path(step_dir)
    h = hashlib.blake2b(digest_size=16)
    files = sorted(
        p for p in step_dir.rglob("*") if p.is_file()
    )
    for p in files:
        rel = p.relative_to(step_dir).as_posix()
        h.update(rel.encode())
        h.update(b"\0")
        h.update(str(p.stat().st_size).encode())
        h.update(b"\0")
        with p.open("rb") as f:
            while True:
                chunk = f.read(_CHUNK)
                if not chunk:
                    break
                h.update(chunk)
    return h.hexdigest()


def sidecar_path(root, step: int) -> Path:
    return Path(root) / f"{int(step)}.digest"


def inflight_path(root, step: int) -> Path:
    return Path(root) / f"{int(step)}.inflight"


def mark_inflight(root, step: int) -> Path:
    """Fence a step whose async commit is in flight: until the sidecar
    lands (which clears the fence), the step is NOT committed — a crash
    mid-commit leaves the marker behind and the restore-side scan skips
    the step no matter how complete its bytes look. Atomic for the same
    reason sidecars are: a torn fence must still fence."""
    path = inflight_path(root, step)
    tmp = path.with_name(f"{path.name}.{os.getpid()}.tmp")
    tmp.write_text("inflight\n")
    tmp.replace(path)
    return path


def clear_inflight(root, step: int) -> None:
    inflight_path(root, step).unlink(missing_ok=True)


def write_sidecar(root, step: int) -> str:
    """Digest ``root/<step>`` and commit the sidecar atomically (a torn
    SIDECAR must never condemn a good checkpoint). Returns the digest.

    Also clears the step's inflight fence — the sidecar IS the commit
    record, so a stale fence from a previous life's interrupted async
    save must not condemn the step a new life just re-saved. Ordering
    (sidecar first, then unfence) errs conservative: a crash between
    the two leaves a good step fenced, and recovery falls back one
    step rather than trusting an ambiguous one."""
    digest = step_digest(Path(root) / str(int(step)))
    path = sidecar_path(root, step)
    tmp = path.with_name(f"{path.name}.{os.getpid()}.tmp")
    tmp.write_text(digest + "\n")
    tmp.replace(path)
    clear_inflight(root, step)
    return digest


def verify_step(root, step: int) -> Optional[bool]:
    """True = verified good; False = corrupt/uncommitted; None = no
    sidecar (legacy checkpoint — accepted by default)."""
    if inflight_path(root, step).exists():
        # An async commit started and never finished (the writer clears
        # the fence when the sidecar lands): the step is uncommitted,
        # whatever bytes the crash left behind.
        return False
    path = sidecar_path(root, step)
    try:
        expected = path.read_text().strip()
    except OSError:
        return None
    if not expected:
        return False  # torn sidecar: treat as corrupt, never as "unknown"
    step_dir = Path(root) / str(int(step))
    if not step_dir.is_dir():
        return False  # sidecar survived its checkpoint: gone = corrupt
    return step_digest(step_dir) == expected


def list_steps(root) -> List[int]:
    """Integer-named step directories under a checkpoint root, sorted."""
    root = Path(root)
    if not root.is_dir():
        return []
    out = []
    for p in root.iterdir():
        if p.is_dir() and p.name.isdigit():
            out.append(int(p.name))
    return sorted(out)


def latest_verified_step(
    root,
    steps: Optional[Iterable[int]] = None,
    *,
    require_sidecar: bool = False,
    on_corrupt: Optional[Callable[[int], None]] = None,
) -> Optional[int]:
    """Newest step that passes verification, scanning newest-first.

    Corrupt steps (and, under ``require_sidecar``, unverifiable ones)
    are skipped after calling ``on_corrupt(step)`` — the hook the
    restore path uses to surface a "skipped corrupt checkpoint" event.
    """
    steps = list_steps(root) if steps is None else sorted(steps)
    for step in reversed(list(steps)):
        ok = verify_step(root, step)
        if ok is True or (ok is None and not require_sidecar):
            return step
        if on_corrupt is not None:
            on_corrupt(step)
    return None


def prune_stale_sidecars(root) -> None:
    """Drop sidecars and inflight fences whose step directory is gone
    (max_to_keep GC, or a commit that failed after cleanup)."""
    root = Path(root)
    live = {str(s) for s in list_steps(root)}
    for suffix in (".digest", ".inflight"):
        for p in root.glob("*" + suffix):
            if p.name[: -len(suffix)] not in live:
                p.unlink(missing_ok=True)


def corrupt_step(root, step: int, *, mode: str = "flip") -> Path:
    """Damage a committed step IN PLACE, leaving its sidecar stale — the
    torn-write simulator shared by the ``torn_checkpoint_write`` fault
    and the corruption tests. ``mode``: ``flip`` inverts a byte mid-file;
    ``truncate`` cuts the file in half. Returns the damaged path."""
    step_dir = Path(root) / str(int(step))
    files = sorted(p for p in step_dir.rglob("*") if p.is_file())
    if not files:
        raise FileNotFoundError(f"no files under {step_dir}")
    # Deterministic victim: the largest file, ties broken by path.
    victim = max(files, key=lambda p: (p.stat().st_size, str(p)))
    data = bytearray(victim.read_bytes())
    if mode == "truncate":
        # invariant: waived — deliberate in-place corruption; this simulator exists to defeat atomicity
        victim.write_bytes(bytes(data[: len(data) // 2]))
    elif mode == "flip":
        if not data:
            data = bytearray(b"\xff")
        else:
            data[len(data) // 2] ^= 0xFF
        # invariant: waived — deliberate in-place corruption; this simulator exists to defeat atomicity
        victim.write_bytes(bytes(data))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    return victim
