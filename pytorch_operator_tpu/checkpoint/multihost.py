"""Multi-host async-save dryrun: primary-host commit + per-process
writer barriers.

On a real pod, orbax's multi-process save has every process write its
addressable shards and ONE primary host commit the metadata — the
commit is valid only after every contributor's bytes are durable. The
async writer (``async_writer.py``) pipelines saves per process, which
re-opens the classic distributed-commit hazard: process 0's commit
thread may reach the sidecar while process 3's shard write is still in
flight, and a crash in that window leaves a "committed" step missing a
shard. This module supplies the coordination layer, TPU-free, so the
protocol is exercised by multi-process tier-1 tests exactly as a pod
would run it:

- :class:`CommitBarrier` — a named rendezvous between the job's writer
  processes over the shared status-channel directory: ``arrive()``
  drops an atomic per-process marker file, ``wait_all()`` polls until
  every process's marker for that (step, phase) exists. Markers are
  single files created by atomic rename — the same discipline as the
  inflight fence — so a torn arrival never counts.
- :func:`make_multihost_commit` — wraps a per-process shard-write
  callable into a commit callable for ``AsyncCheckpointWriter``:

  1. every process writes its own shard bytes for the step;
  2. every process arrives at the ``written`` barrier and
     ``wait_all()``\\ s — after this, ALL shards are durable;
  3. the PRIMARY (process 0) alone finalizes — checksum sidecar over
     the assembled step directory, fence cleared — and arrives at
     ``committed``; secondaries ``wait_all()`` on the primary's
     ``committed`` marker before retiring the save.

  A process killed mid-protocol leaves the step fenced on the primary
  (never sidecar-verified), and every surviving process's
  ``wait_all()`` times out and FAILS the save (recorded on its writer,
  reported as ``checkpoint_save_failed``) instead of committing a
  torn step — restore falls back to the last verified step.

Because each process runs its own :class:`AsyncCheckpointWriter`, the
barrier composes with staged snapshots for free: gather and shard write
overlap per process, and only the commit tail rendezvouses.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Callable, Optional

from . import integrity

# Subdirectory of the checkpoint root holding barrier markers; swept by
# the primary after each commit so a long run does not accumulate files.
BARRIER_DIR = ".barriers"


class BarrierTimeout(TimeoutError):
    """``wait_all()`` gave up: at least one process never arrived."""


class CommitBarrier:
    """File-rendezvous between a job's writer processes.

    ``root`` must be a directory every process shares (the per-job
    checkpoint dir the supervisor injects). Marker files are
    ``<root>/.barriers/<phase>-<step>.p<process_id>`` — one per
    process per (phase, step), created atomically.
    """

    def __init__(
        self,
        root,
        process_id: int,
        num_processes: int,
        *,
        poll_s: float = 0.02,
        report: Optional[Callable[..., None]] = None,
    ):
        if not 0 <= process_id < num_processes:
            raise ValueError(
                f"process_id {process_id} outside world of {num_processes}"
            )
        self.root = Path(root) / BARRIER_DIR
        self.process_id = int(process_id)
        self.num_processes = int(num_processes)
        self.poll_s = poll_s
        # Optional status-channel hook (rendezvous.report): barrier
        # arrivals/timeouts become visible to `tpujob why` and the
        # supervisor's event fold.
        self._report = report

    @property
    def is_primary(self) -> bool:
        return self.process_id == 0

    def _marker(self, phase: str, step: int, pid: int) -> Path:
        return self.root / f"{phase}-{int(step)}.p{pid}"

    def arrive(self, phase: str, step: int) -> None:
        """Atomically publish this process's arrival at (phase, step).
        Idempotent — re-arrival overwrites the same marker."""
        self.root.mkdir(parents=True, exist_ok=True)
        path = self._marker(phase, step, self.process_id)
        tmp = path.with_name(f"{path.name}.{os.getpid()}.tmp")
        tmp.write_text(f"{time.time()}\n")
        tmp.replace(path)

    def wait_all(
        self,
        phase: str,
        step: int,
        timeout: Optional[float] = 30.0,
        procs=None,
    ) -> None:
        """Block until every process in ``procs`` (default: the whole
        world) has arrived at (phase, step). Raises
        :class:`BarrierTimeout` — it does NOT return partially —
        because a commit past a missing writer is a torn checkpoint
        wearing a sidecar."""
        deadline = None if timeout is None else time.monotonic() + timeout
        missing = set(range(self.num_processes) if procs is None else procs)
        while missing:
            missing = {
                p for p in missing
                if not self._marker(phase, step, p).exists()
            }
            if not missing:
                return
            if deadline is not None and time.monotonic() >= deadline:
                if self._report is not None:
                    try:
                        self._report(
                            "ckpt_barrier_timeout", step=step, phase=phase,
                            missing=sorted(missing),
                        )
                    except Exception:
                        # invariant: waived — a broken telemetry hook must not mask the BarrierTimeout raised below
                        pass
                raise BarrierTimeout(
                    f"commit barrier {phase}-{step}: processes "
                    f"{sorted(missing)} never arrived within {timeout}s"
                )
            time.sleep(self.poll_s)

    def sweep(self, step: int) -> None:
        """Drop this step's markers (primary calls it after finalizing
        — the rendezvous is complete, the files are noise)."""
        for p in self.root.glob(f"*-{int(step)}.p*"):
            p.unlink(missing_ok=True)

    def sweep_older(self, phase: str, step: int) -> None:
        """Drop ``phase`` markers for steps strictly older than
        ``step``. Per-process commits are ordered, so by the time the
        primary commits ``step`` every secondary has consumed the
        ``committed`` marker of every earlier step — safe to GC."""
        prefix = f"{phase}-"
        for p in self.root.glob(f"{phase}-*.p*"):
            stem = p.name[len(prefix):].split(".p", 1)[0]
            if stem.isdigit() and int(stem) < int(step):
                p.unlink(missing_ok=True)


def make_multihost_commit(
    root,
    write_shard: Callable[[int, object, Optional[str]], None],
    *,
    process_id: int,
    num_processes: int,
    barrier_timeout: float = 30.0,
    poll_s: float = 0.02,
    report: Optional[Callable[..., None]] = None,
    on_abort: Optional[Callable[[int], None]] = None,
) -> Callable[[int, object, Optional[str]], None]:
    """Build the commit callable a multi-process world hands its
    :class:`~pytorch_operator_tpu.checkpoint.async_writer.AsyncCheckpointWriter`.

    ``write_shard(step, payload, fault)`` is the per-process half: it
    must leave THIS process's bytes for ``step`` durable (and may raise
    — retries/faults are its business, exactly like a single-host
    commit callable). The returned callable adds the primary-host
    commit protocol described in the module docstring. Only the PRIMARY
    writes the checksum sidecar; secondaries never touch integrity
    files, so there is exactly one commit record per step.

    Fencing note: every process's writer fences the step in the SHARED
    root at submit (``<step>.inflight`` is one file — mark_inflight is
    atomic and idempotent across processes), and only the primary's
    sidecar write clears it; a secondary that dies pre-barrier leaves
    the step fenced because the primary's ``wait_all`` fails before the
    sidecar lands.
    """
    barrier = CommitBarrier(
        root, process_id, num_processes, poll_s=poll_s, report=report
    )

    def commit(step: int, payload, fault: Optional[str]) -> None:
        try:
            write_shard(step, payload, fault)
            barrier.arrive("written", step)
            if barrier.is_primary:
                # Only the primary collects the written barrier — it is
                # the one about to assert "all shards durable" with a
                # sidecar. Secondaries gate on the committed marker
                # below (which implies it), so the primary may sweep
                # written markers without racing a slow peer's poll.
                barrier.wait_all("written", step, timeout=barrier_timeout)
        except BaseException:
            # A shard write failure or a peer that never arrived: this
            # process's bytes must not survive to masquerade as part of
            # a committed step (the writer records the failure and
            # reports checkpoint_save_failed — same contract as a
            # single-host ENOSPC).
            if on_abort is not None:
                try:
                    on_abort(step)
                except Exception:
                    # invariant: waived — abort-callback failure must not mask the original commit failure re-raised below
                    pass
            raise
        if barrier.is_primary:
            # All shards durable: the sidecar is the commit record, and
            # writing it clears the shared inflight fence.
            integrity.write_sidecar(root, step)
            barrier.arrive("committed", step)
            # Secondaries may still be polling for THIS step's
            # committed marker; sweep the consumed written markers now
            # and older committed markers (per-process commit order
            # guarantees every secondary is past them).
            for p in range(num_processes):
                barrier._marker("written", step, p).unlink(missing_ok=True)
            barrier.sweep_older("committed", step)
        else:
            # Only the primary publishes `committed` — that one marker
            # IS the commit record's existence signal.
            barrier.wait_all(
                "committed", step, timeout=barrier_timeout, procs=(0,)
            )
            if report is not None:
                try:
                    report("ckpt_commit_ack", step=step, process=process_id)
                except Exception:
                    # invariant: waived — the ack is telemetry; the commit itself is already durable
                    pass

    return commit
