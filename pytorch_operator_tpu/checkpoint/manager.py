"""Checkpoint save/restore shared by all workloads.

Reference mapping (SURVEY.md §5 "Checkpoint / resume"): checkpointing is NOT
an operator feature in the reference — resume semantics are "restart the pod,
the user script reloads its own checkpoint." The rebuild keeps that division
of labor but supplies the workload half natively: orbax-backed save/restore
keyed by step, and a per-job checkpoint directory injected by the supervisor
(``TPUJOB_CHECKPOINT_DIR``) that survives gang restarts and job resubmission
(job-level resume = rerun the spec against the existing dir). Workloads opt
in by calling :meth:`CheckpointManager.restore_or_none` at startup; a fresh
run of a different experiment under a reused job name must either purge
(``tpujob delete --purge``) or use a new job name.

TPU-native notes:

- orbax writes are multi-process-aware (single primary host commits the
  metadata; every process contributes its addressable shards), so the same
  code path serves 1-process TPU runs and N-process CPU test worlds.
- restore takes a "state like" pytree (the freshly initialized train state):
  orbax restores onto the SAME shardings, so a resumed FSDP world comes back
  sharded without a gather/rescatter round trip.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Optional


def job_checkpoint_dir() -> Optional[Path]:
    """The supervisor-injected per-job checkpoint directory, if any."""
    d = os.environ.get("TPUJOB_CHECKPOINT_DIR")
    return Path(d) if d else None


class CheckpointManager:
    """Step-keyed checkpoints of an arbitrary pytree (train state).

    Thin, stable facade over ``orbax.checkpoint.CheckpointManager`` so
    workloads never import orbax directly and the backend can be swapped.
    """

    def __init__(
        self,
        directory: Path | str,
        max_to_keep: int = 3,
        create: bool = True,
        *,
        staged: bool = False,
    ):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        # Staged async saves defer the device→host gather to the
        # writer's snapshot-stage thread: save(block=False) writes the
        # inflight fence, copies only mutable host leaves, and returns.
        # OPT-IN because the deferred gather holds references to the
        # live device arrays — sound only while the step does NOT
        # donate them (a donating caller must keep the eager PR-3
        # snapshot; llama's --donate path passes staged=False).
        self._staged = staged
        self.directory = Path(directory).absolute()
        if create:
            # One creation mechanism only: parents=True is load-bearing
            # (the supervisor nests checkpoint dirs several levels under
            # the state dir), which orbax's
            # CheckpointManagerOptions(create=True) does not guarantee —
            # so the explicit mkdir owns creation.
            self.directory.mkdir(parents=True, exist_ok=True)
        elif not self.directory.is_dir():
            # Read-only openers (generate --restore) must not leave a
            # stray directory behind a typo'd path.
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )
        # Lazily created on the first non-blocking save. One writer =
        # one commit thread = async saves serialize in submission order.
        self._writer = None

    def _drain(self) -> None:
        """Barrier: every async commit submitted so far is finished
        (committed or failed-and-reported). All read-side entry points
        and blocking saves pass through here, so orbax is only ever
        touched from one thread at a time and no caller observes a
        half-committed step."""
        if self._writer is not None:
            self._writer.wait()

    def latest_step(self) -> Optional[int]:
        self._drain()
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        self._drain()
        return sorted(self._mgr.all_steps())

    def last_committed_step(self) -> Optional[int]:
        """Newest step whose ASYNC commit (sidecar included) finished —
        without draining; the live-telemetry peek."""
        return None if self._writer is None else self._writer.last_committed_step()

    def _commit_step(self, step: int, state: Any, fault) -> None:
        """One durable, VERIFIED step commit — the shared tail of both
        save paths (blocking on the caller's thread, async on the
        writer's commit thread).

        Transient I/O failures are retried on the shared backoff
        schedule (a preempted NFS mount mid-save must not kill a
        training step the restart policy would happily replay); each
        retry first clears the partial step so orbax starts clean, and
        retry exhaustion (e.g. an ``enospc`` fault — persistent, every
        attempt fails) cleans the partial step before re-raising so a
        half-written directory can never be mistaken for a legacy
        unverified checkpoint. The checksum sidecar commits LAST — for
        async saves too, closing the old "non-blocking saves verify as
        unknown" hole.
        """
        import shutil

        from ..backoff import Backoff, retry_call
        from . import integrity

        def attempt():
            nonlocal fault
            if fault == "fail":
                fault = None  # transient: only the first attempt fails
                raise OSError("injected transient checkpoint write failure")
            if fault == "enospc":
                # Persistent: EVERY attempt fails — disk-full does not
                # heal on a retry schedule.
                import errno

                raise OSError(
                    errno.ENOSPC, "injected: no space left on device"
                )
            self._mgr.save(step, args=self._ocp.args.StandardSave(state))
            self._mgr.wait_until_finished()

        def clear_partial(_exc, _attempt):
            shutil.rmtree(self.directory / str(step), ignore_errors=True)

        try:
            retry_call(
                attempt,
                backoff=Backoff(base_s=0.05, cap_s=2.0, seed=step),
                attempts=3,
                retry_on=(OSError,),
                on_retry=clear_partial,
            )
        except OSError:
            # Final failure: leave NO partial step behind (a sidecar-less
            # directory would restore as a legacy "unknown" step) and let
            # the caller decide whether the loop survives.
            clear_partial(None, None)
            raise
        integrity.write_sidecar(self.directory, step)
        if fault == "torn":
            # Damage the committed bytes UNDER the fresh sidecar —
            # the deterministic stand-in for a torn write that the
            # verified-good restore scan must catch and skip.
            integrity.corrupt_step(self.directory, step)
        integrity.prune_stale_sidecars(self.directory)

    def _report_save_failed(self, step: int, err) -> None:
        from ..runtime.rendezvous import report

        print(
            f"[tpujob] warning: checkpoint save of step {step} failed "
            f"after retries ({err}); training continues, recovery will "
            "fall back to the last verified step",
            flush=True,
        )
        report("checkpoint_save_failed", step=step, error=str(err))

    def save(
        self,
        step: int,
        state: Any,
        *,
        block: bool = True,
        staged: Optional[bool] = None,
    ) -> None:
        """Save ``state`` at ``step``. ``block=True`` waits for the commit —
        the safe default for preemption-recovery tests; ``block=False``
        commits (checksum sidecar included) on the async writer's
        background pipeline. All paths produce VERIFIED steps; the only
        difference is where the wait happens.

        Two async flavors (``staged`` defaults to the manager-level
        setting):

        - **eager** (PR-3, ``staged=False``): the full device→host
          snapshot runs on the caller's thread before returning — after
          that the caller may donate/overwrite the live state.
        - **staged** (``staged=True``): only the inflight fence write
          and copies of MUTABLE host leaves happen here; the device
          gather runs chunked per-leaf on the writer's snapshot-stage
          thread, overlapping the previous step's commit. The caller
          must NOT donate the device arrays (they are read after this
          returns) — in-place numpy mutation stays safe.

        The fault-injection decision (``checkpoint_write_fault``) is
        evaluated HERE, in call order, so a replayed plan fires the
        identical saves on either path; the fault's effect lands inside
        the commit itself. An async commit that exhausts its retries is
        reported (``checkpoint_save_failed``) and recorded on the
        writer, never raised into the step loop.
        """
        from .. import faults, obs

        fault = faults.checkpoint_write_fault()
        if block:
            self._drain()  # commits stay in submission order
            with obs.span("ckpt_blocking_save", cat="ckpt", step=step):
                self._commit_step(step, state, fault)
            return
        from .async_writer import (
            AsyncCheckpointWriter,
            snapshot_to_host,
            stage_mutable_leaves,
        )

        if self._writer is None:
            from ..runtime.rendezvous import report_checkpoint_committed

            self._writer = AsyncCheckpointWriter(
                self._commit_step,
                root=self.directory,
                on_error=self._report_save_failed,
                on_commit=report_checkpoint_committed,
            )
        if self._staged if staged is None else staged:
            # Submit-time stall = fence write + mutable-leaf copies; the
            # gather itself is the snapshot stage's job.
            with obs.span("ckpt_stage_submit", cat="ckpt", step=step):
                held = stage_mutable_leaves(state)
            self._writer.submit_staged(
                step, lambda: snapshot_to_host(held), fault
            )
            return
        # Eager: the host snapshot is the ONLY stall the step loop pays;
        # after this line the caller may donate/overwrite the live state.
        with obs.span("ckpt_snapshot", cat="ckpt", step=step):
            snap = snapshot_to_host(state)
        self._writer.submit(step, snap, fault)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Public barrier: drain pending async commits. Returns ``True``
        when drained; ``False`` (after a logged warning — the caller is
        about to proceed past undrained saves) when ``timeout`` expired
        with commits still pending."""
        if self._writer is None:
            return True
        drained = self._writer.wait(timeout)
        if not drained:
            print(
                f"[tpujob] warning: checkpoint drain timed out after "
                f"{timeout}s with commits still pending "
                f"({self._writer.stats()}); proceeding — the newest saves "
                "may not be durable yet",
                flush=True,
            )
        return drained

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore onto the structure/shardings of ``state_like`` (pass the
        freshly initialized, already-sharded train state).

        ``state_like``'s mesh need NOT match the one the checkpoint was
        saved on: orbax re-lays the saved shards out onto the target
        shardings, so an elastic world that shrank or grew between lives
        (fsdp=4 save -> fsdp=2 restore) resumes losslessly — the
        world-size-change case preemption recovery exists for
        (tests/test_checkpoint.py::test_restore_reshards_across_mesh_shapes
        and the shrink e2e in test_elastic_e2e.py pin this)."""
        self._drain()
        return self._mgr.restore(
            self._resolve_step(step),
            args=self._ocp.args.StandardRestore(state_like),
        )

    def _resolve_step(self, step: Optional[int]) -> int:
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        return step

    def restore_tree(self, step: Optional[int] = None) -> tuple[int, Any]:
        """Restore the ENTIRE checkpoint AS SAVED — no target tree
        required (host numpy arrays, saved structure). For inspection
        and structure-editing callers that need the whole state; peak
        host memory is the FULL state's bytes, so serve-side loading of
        one subtree should use :meth:`restore_subtree` instead (the
        generate workload does). Returns ``(step, tree)``."""
        self._drain()
        step = self._resolve_step(step)
        return step, self._mgr.restore(step)

    def restore_subtree(self, key: str, step: Optional[int] = None) -> tuple[int, Any]:
        """Restore ONLY the top-level subtree ``key`` (e.g. ``"params"``)
        from the checkpoint as saved — host numpy arrays, saved
        structure. Returns ``(step, subtree)``.

        This is the serve-side loader (ADVICE r4 medium):
        :meth:`restore_tree` materializes the ENTIRE saved train state in
        host RAM before the caller pops ``params`` — for an 8B adamw
        checkpoint that is ~96 GB of transient residency on a ~125 GB
        host. A partial restore reads only the requested shards, so peak
        host memory is bounded by the subtree's bytes (~32 GB for 8B f32
        params).

        Implementation rides orbax's ``PyTreeRestore(partial_restore=
        True)`` on the step directory directly: the manager's registered
        Standard handlers reject placeholder/partial targets, and the
        step layout (``<dir>/<step>/default``) is this facade's own
        save format (StandardSave under the default item name), pinned
        by tests/test_checkpoint.py."""
        import jax
        import numpy as np

        self._drain()
        step = self._resolve_step(step)
        step_dir = self.directory / str(step) / "default"
        with self._ocp.Checkpointer(
            self._ocp.PyTreeCheckpointHandler()
        ) as ckptr:
            # The manager's item_metadata() is None on a freshly opened
            # manager (no save/restore registered a handler yet); the
            # raw checkpointer reads the step's metadata directly.
            # Orbax API drift: older releases return the tree dict
            # directly, newer ones wrap it in item_metadata.tree.
            meta = ckptr.metadata(step_dir)
            if not isinstance(meta, dict):
                meta = meta.item_metadata.tree
            if key not in meta:
                raise KeyError(
                    f"checkpoint at step {step} has no top-level {key!r} "
                    f"(keys: {sorted(meta)})"
                )
            restore_args = {
                key: jax.tree.map(
                    lambda _: self._ocp.RestoreArgs(restore_type=np.ndarray),
                    meta[key],
                )
            }
            # Orbax API drift: newer releases spell partial restoration
            # `PyTreeRestore(partial_restore=True)`; older ones (this
            # image ships 0.7.0) take an item covering ONLY the wanted
            # subtree plus `transforms={}` (= drop checkpoint keys the
            # item omits). Same read behavior: only the requested
            # subtree's shards are fetched.
            import inspect

            pr = self._ocp.args.PyTreeRestore
            if "partial_restore" in inspect.signature(pr.__init__).parameters:
                tree = ckptr.restore(
                    step_dir,
                    args=pr(item=restore_args, partial_restore=True),
                )
            else:
                item = {
                    key: jax.tree.map(lambda _: 0, meta[key])
                }
                tree = ckptr.restore(
                    step_dir,
                    args=pr(
                        item=item,
                        restore_args=restore_args,
                        transforms={},
                    ),
                )
        return step, tree[key]

    def _report_corrupt(self, step: int, fallback=None, err=None) -> None:
        """Surface a skipped corrupt step on the status channel — the
        supervisor folds ``checkpoint_corrupt`` records into job events
        (CheckpointCorrupt in ``tpujob describe``)."""
        from ..runtime.rendezvous import report

        msg = (
            f"[tpujob] warning: checkpoint step {step} failed verification"
            + (f" ({err})" if err else "")
            + (
                f"; falling back toward step {fallback}"
                if fallback is not None
                else "; no older step to fall back to"
            )
        )
        print(msg, flush=True)
        report("checkpoint_corrupt", step=step, fallback=fallback)

    def latest_verified_step(self) -> Optional[int]:
        """Newest step whose checksum sidecar still matches (steps
        without a sidecar — legacy / non-blocking saves — count as
        acceptable). Corrupt steps are reported and skipped."""
        from . import integrity

        steps = self.all_steps()
        return integrity.latest_verified_step(
            self.directory,
            steps,
            on_corrupt=lambda s: self._report_corrupt(
                s, fallback=max((x for x in steps if x < s), default=None)
            ),
        )

    def restore_or_none(
        self, state_like: Any, *, verify: bool = True
    ) -> Optional[tuple[int, Any]]:
        """(step, state) from the newest RESTORABLE checkpoint, or None —
        the one-call resume idiom for workloads.

        With ``verify`` (the default) steps are walked newest-first:
        checksum-mismatched steps are skipped up front, and a step whose
        restore raises (truncated files orbax chokes on) is treated the
        same — report, fall back to the next older step, keep going.
        Restart-based recovery must degrade to an OLDER checkpoint, not
        die on the newest write the crash itself tore."""
        from . import integrity

        steps = self.all_steps()
        if not verify:
            step = self.latest_step()
            return None if step is None else (step, self.restore(state_like, step))
        for i, step in enumerate(reversed(steps)):
            older = steps[-(i + 2)] if i + 2 <= len(steps) else None
            if integrity.verify_step(self.directory, step) is False:
                self._report_corrupt(step, fallback=older)
                continue
            try:
                return step, self.restore(state_like, step)
            except Exception as e:  # noqa: BLE001 — any restore failure
                # of THIS step must fall back, not kill the recovery.
                self._report_corrupt(step, fallback=older, err=e)
        return None

    def close(self) -> None:
        # Workload exit drains through here: every async save submitted
        # before close is durable (or reported failed) when this returns.
        if self._writer is not None:
            self._writer.close()
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
