"""Checkpoint save/restore shared by all workloads.

Reference mapping (SURVEY.md §5 "Checkpoint / resume"): checkpointing is NOT
an operator feature in the reference — resume semantics are "restart the pod,
the user script reloads its own checkpoint." The rebuild keeps that division
of labor but supplies the workload half natively: orbax-backed save/restore
keyed by step, and a per-job checkpoint directory injected by the supervisor
(``TPUJOB_CHECKPOINT_DIR``) that survives gang restarts and job resubmission
(job-level resume = rerun the spec against the existing dir). Workloads opt
in by calling :meth:`CheckpointManager.restore_or_none` at startup; a fresh
run of a different experiment under a reused job name must either purge
(``tpujob delete --purge``) or use a new job name.

TPU-native notes:

- orbax writes are multi-process-aware (single primary host commits the
  metadata; every process contributes its addressable shards), so the same
  code path serves 1-process TPU runs and N-process CPU test worlds.
- restore takes a "state like" pytree (the freshly initialized train state):
  orbax restores onto the SAME shardings, so a resumed FSDP world comes back
  sharded without a gather/rescatter round trip.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Optional


def job_checkpoint_dir() -> Optional[Path]:
    """The supervisor-injected per-job checkpoint directory, if any."""
    d = os.environ.get("TPUJOB_CHECKPOINT_DIR")
    return Path(d) if d else None


class CheckpointManager:
    """Step-keyed checkpoints of an arbitrary pytree (train state).

    Thin, stable facade over ``orbax.checkpoint.CheckpointManager`` so
    workloads never import orbax directly and the backend can be swapped.
    """

    def __init__(
        self, directory: Path | str, max_to_keep: int = 3, create: bool = True
    ):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = Path(directory).absolute()
        if create:
            # One creation mechanism only: parents=True is load-bearing
            # (the supervisor nests checkpoint dirs several levels under
            # the state dir), which orbax's
            # CheckpointManagerOptions(create=True) does not guarantee —
            # so the explicit mkdir owns creation.
            self.directory.mkdir(parents=True, exist_ok=True)
        elif not self.directory.is_dir():
            # Read-only openers (generate --restore) must not leave a
            # stray directory behind a typo'd path.
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def save(self, step: int, state: Any, *, block: bool = True) -> None:
        """Save ``state`` at ``step``. ``block=True`` waits for the commit —
        the safe default for preemption-recovery tests; ``block=False``
        overlaps the write with the next training steps."""
        self._mgr.save(step, args=self._ocp.args.StandardSave(state))
        if block:
            self._mgr.wait_until_finished()

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore onto the structure/shardings of ``state_like`` (pass the
        freshly initialized, already-sharded train state).

        ``state_like``'s mesh need NOT match the one the checkpoint was
        saved on: orbax re-lays the saved shards out onto the target
        shardings, so an elastic world that shrank or grew between lives
        (fsdp=4 save -> fsdp=2 restore) resumes losslessly — the
        world-size-change case preemption recovery exists for
        (tests/test_checkpoint.py::test_restore_reshards_across_mesh_shapes
        and the shrink e2e in test_elastic_e2e.py pin this)."""
        return self._mgr.restore(
            self._resolve_step(step),
            args=self._ocp.args.StandardRestore(state_like),
        )

    def _resolve_step(self, step: Optional[int]) -> int:
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        return step

    def restore_tree(self, step: Optional[int] = None) -> tuple[int, Any]:
        """Restore the checkpoint AS SAVED — no target tree required
        (host numpy arrays, saved structure). The serve-side loader:
        ``tpujob``'s generate workload restores a TRAIN checkpoint this
        way and picks out ``["params"]`` without needing to reconstruct
        the training run's optimizer-state structure. Returns
        ``(step, tree)``."""
        step = self._resolve_step(step)
        return step, self._mgr.restore(step)

    def restore_or_none(self, state_like: Any) -> Optional[tuple[int, Any]]:
        """(step, state) from the latest checkpoint, or None if there is none
        — the one-call resume idiom for workloads."""
        step = self.latest_step()
        if step is None:
            return None
        return step, self.restore(state_like, step)

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
