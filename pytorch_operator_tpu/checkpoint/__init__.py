"""Checkpoint/resume layer (orbax-backed).

Reference: none in the operator (SURVEY.md §5 — resume is "restart the pod,
user script reloads its checkpoint"); this package supplies the workload half
the reference left to user containers.
"""

from .async_writer import AsyncCheckpointWriter, snapshot_to_host
from .manager import CheckpointManager, job_checkpoint_dir

__all__ = [
    "AsyncCheckpointWriter",
    "CheckpointManager",
    "job_checkpoint_dir",
    "snapshot_to_host",
]
