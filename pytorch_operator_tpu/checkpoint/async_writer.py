"""Staged async checkpointing: snapshot and commit as pipelined stages,
both off the step path, every step still *verified*.

PR 3 took the WRITE off the step path but left the device→host gather
inline: ``save(block=False)`` paid the full ``device_get`` of the train
state on the caller's thread before returning — for a multi-GB state
that snapshot IS the remaining stall (TorchTitan ships staged async
distributed checkpointing as a headline feature for exactly this,
PAPERS.md). This revision splits the writer into two pipelined stages:

1. **Submit (caller thread)**: write the ``<step>.inflight`` fence,
   copy only the MUTABLE host leaves (numpy arrays a donating or
   in-place-updating caller could overwrite — device arrays are
   immutable and safe to hold), and return. The step loop's stall is
   the fence write plus a few host memcpys.
2. **Snapshot stage (one background thread)**: the device→host gather
   runs chunked PER LEAF in submission order — while step N's leaves
   gather, the COMMIT of step N-1 proceeds concurrently on the commit
   thread; a large pytree overlaps instead of serializing the pipeline.
3. **Commit stage (one background thread)**: unchanged from PR 3 —
   strictly ordered commits through the shared backoff retry with
   partial-step cleanup, checksum sidecar written AT COMMIT, fence
   cleared when the sidecar lands.

Every PR-3 invariant carries over: snapshots are bounded at submit
(``max_pending`` slots — backpressure, not unbounded host memory),
commits land in submission order, a crash mid-snapshot OR mid-commit
leaves a fenced (never torn) step that restore-side scans skip, and
``wait()``/``close()`` barriers drain BOTH stages. New obs surfaces:
a ``ckpt_snapshot_wait`` span when a submitted step waited behind the
snapshot stage, and a ``snapshot_depth`` stat (``ckpt_stage_depth``
gauge) counting submitted-but-not-yet-gathered steps.

The one caller obligation the deferred gather adds: a jit step that
DONATES the state invalidates the device buffers the snapshot thread
would read — donating callers must keep the PR-3 eager snapshot
(``CheckpointManager.save(..., staged=False)``); the manager documents
and defaults this per workload.

A failed snapshot or commit (e.g. a persistent ENOSPC after the retry
budget) does NOT kill the step loop: the partial step is cleaned, the
failure is recorded in :attr:`AsyncCheckpointWriter.errors` and
reported on the status channel as ``checkpoint_save_failed``, and later
saves proceed — restart-based recovery then falls back to the last
verified step.

Deliberately jax-free and orbax-free: the commit callable owns the
backend, so the orbax manager (``manager.py``) and the JSON step files
the chaos workload writes (``workloads/exit_with.py``) share this exact
protocol — the crash-consistency tier-1 exercises without orbax is the
crash-consistency production checkpoints get.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, List, Optional, Tuple


def snapshot_to_host(tree: Any) -> Any:
    """Deep host copy of a pytree of arrays, safe to hand to a
    background commit while the caller keeps mutating (donating) the
    originals.

    jax arrays come back as host numpy via a chunked PER-LEAF
    ``jax.device_get`` (a real transfer — the returned buffer is
    fresh); gathering leaf-at-a-time instead of one whole-tree call is
    what lets the staged snapshot thread interleave with a concurrent
    commit (and with the step loop's own transfers) on a large pytree.
    numpy arrays are COPIED (``device_get`` would return them aliased,
    and an aliased snapshot is exactly the torn-write bug this function
    exists to prevent). Non-array leaves pass through.
    """
    import numpy as np

    def snap(x):
        if isinstance(x, np.ndarray):
            return np.array(x, copy=True)
        if hasattr(x, "devices") or hasattr(x, "device_buffer"):
            import jax

            out = jax.device_get(x)
            if isinstance(out, np.ndarray) and not out.flags.owndata:
                # On the CPU backend device_get can return a ZERO-COPY
                # view of the device buffer — exactly the aliasing that
                # lets a donating step overwrite an in-flight commit.
                # The snapshot must own its bytes.
                out = np.array(out, copy=True)
            return out
        return x

    try:
        import jax

        # tree.map visits leaves one at a time: each device_get is its
        # own chunk, so the GIL (and the transfer engine) is yielded
        # between leaves — the "chunked per-leaf" overlap contract.
        return jax.tree.map(snap, tree)
    except ImportError:
        # jax-free callers (the JSON chaos workload): plain containers.
        if isinstance(tree, dict):
            return {k: snapshot_to_host(v) for k, v in tree.items()}
        if isinstance(tree, (list, tuple)):
            return type(tree)(snapshot_to_host(v) for v in tree)
        return snap(tree)


def stage_mutable_leaves(tree: Any) -> Any:
    """The SUBMIT-TIME half of a staged snapshot: copy every leaf a
    caller could mutate under the deferred gather (host numpy arrays —
    in-place optimizer updates, reused buffers), pass immutable device
    arrays and scalars through by reference. The returned tree is safe
    to hand to the snapshot thread, which finishes the job with
    :func:`snapshot_to_host` (jax arrays are immutable, so holding the
    reference is sound as long as the caller does not DONATE them)."""
    import numpy as np

    def stage(x):
        if isinstance(x, np.ndarray):
            return np.array(x, copy=True)
        return x

    try:
        import jax

        return jax.tree.map(stage, tree)
    except ImportError:
        if isinstance(tree, dict):
            return {k: stage_mutable_leaves(v) for k, v in tree.items()}
        if isinstance(tree, (list, tuple)):
            return type(tree)(stage_mutable_leaves(v) for v in tree)
        return stage(tree)


class AsyncCheckpointWriter:
    """Commits checkpoint payloads through a two-stage background
    pipeline — snapshot (device→host gather) then commit — strictly in
    submission order, with verified-at-commit semantics.

    ``commit(step, payload, fault)`` runs on the commit thread and must
    leave the step fully durable INCLUDING its checksum sidecar (the
    manager and exit_with both delegate to their existing fault-aware
    commit helpers). ``fault`` is the injection decision evaluated at
    submit time — occurrence counting happens in call order on the
    caller's thread, so a replayed plan fires the identical saves even
    though the I/O itself is asynchronous.

    :meth:`submit` enqueues an already-materialized payload (the PR-3
    eager-snapshot path — still the right call for donating steps);
    :meth:`submit_staged` enqueues a zero-arg ``snapshot()`` callable
    the snapshot thread runs. Both kinds flow through the SAME
    snapshot→commit queue chain, so mixed submissions still commit in
    exact submission order.

    ``root`` enables inflight fencing (integrity.mark_inflight at
    submit; integrity.write_sidecar clears it at commit).

    ``max_pending`` bounds how many snapshots are alive at once across
    BOTH stages (submit blocks when the budget is spent — backpressure,
    not unbounded host memory).
    """

    def __init__(
        self,
        commit: Callable[[int, Any, Optional[str]], None],
        *,
        root=None,
        max_pending: int = 2,
        on_error: Optional[Callable[[int, BaseException], None]] = None,
        on_commit: Optional[Callable[..., None]] = None,
        clear_fence_on_error: bool = True,
    ):
        if max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        self._commit = commit
        self._root = root
        # Single-host commits own their fence: a failed commit cleans
        # its partial step, so clearing the fence is safe and avoids a
        # phantom fence condemning a never-written step. A MULTI-HOST
        # commit (checkpoint/multihost.py) must keep the fence on
        # failure — peer shards this process cannot see may exist, and
        # "fenced, not torn" is the crash invariant.
        self._clear_fence_on_error = clear_fence_on_error
        self._on_error = on_error
        # Commit-telemetry hook: (step, commit_seconds, queue_depth_after,
        # oldest_inflight_age_seconds, stage_depth) after each successful
        # commit — the manager and exit_with report it on the status
        # channel so the supervisor's checkpoint-lag/queue/stage surfaces
        # stay live. Legacy 4-arg hooks are called without stage_depth.
        self._on_commit = on_commit
        self._on_commit_takes_stage = False
        if on_commit is not None:
            import inspect

            try:
                params = inspect.signature(on_commit).parameters.values()
                self._on_commit_takes_stage = any(
                    p.kind == inspect.Parameter.VAR_POSITIONAL for p in params
                ) or sum(
                    p.kind in (
                        inspect.Parameter.POSITIONAL_ONLY,
                        inspect.Parameter.POSITIONAL_OR_KEYWORD,
                    )
                    for p in params
                ) >= 5
            except (TypeError, ValueError):
                pass  # builtins/C callables: stay on the 4-arg contract
        # step -> submit wall time of in-flight (submitted, undecided)
        # commits; drives the oldest-inflight-age gauge.
        self._inflight_ts: dict = {}
        self._slots = threading.Semaphore(max_pending)
        # Stage 1 queue: (step, payload_or_snapshot_fn, staged, fault,
        # submit_perf_ts). Stage 2 queue: (step, payload, fault).
        self._snap_q: "queue.Queue" = queue.Queue()
        self._q: "queue.Queue" = queue.Queue()
        self._idle = threading.Event()
        self._idle.set()
        self._outstanding = 0  # submitted, not yet committed/failed
        self._in_snapshot = 0  # submitted, not yet handed to commit
        self._lock = threading.Lock()
        self._snap_thread: Optional[threading.Thread] = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._last_committed: Optional[int] = None
        self.committed: List[int] = []  # commit order (serialization pin)
        self.errors: List[Tuple[int, BaseException]] = []

    # ---- submit side (caller thread) ----

    def _enqueue(self, step: int, work, staged: bool, fault) -> None:
        if self._closed:
            raise RuntimeError("writer is closed")
        from .. import obs

        t0 = time.perf_counter()
        self._slots.acquire()
        waited = time.perf_counter() - t0
        if waited > 1e-4:
            # Backpressure made the STEP LOOP wait on the commit queue —
            # exactly the stall the flight recorder exists to show.
            rec = obs.tracer()
            if rec is not None:
                rec.emit(
                    "ckpt_queue_wait", "ckpt",
                    time.time() - waited, waited, step=step,
                )
        if self._root is not None:
            from . import integrity

            integrity.mark_inflight(self._root, step)
        with self._lock:
            # Outstanding count — not queue emptiness — drives the idle
            # barrier: the queues are briefly empty while a thread is
            # mid-snapshot/mid-commit, and wait() must not return then.
            self._outstanding += 1
            self._in_snapshot += 1
            self._inflight_ts[step] = time.time()
            self._idle.clear()
            self._ensure_threads()
        self._snap_q.put((step, work, staged, fault, time.perf_counter()))

    def submit(self, step: int, payload: Any, fault: Optional[str] = None) -> None:
        """Enqueue one commit of an ALREADY-MATERIALIZED payload (the
        eager-snapshot path). Blocks only when ``max_pending`` snapshots
        are already in flight. The inflight fence for ``step`` is on
        disk before this returns."""
        self._enqueue(step, payload, False, fault)

    def submit_staged(
        self, step: int, snapshot: Callable[[], Any], fault: Optional[str] = None
    ) -> None:
        """Enqueue one STAGED commit: ``snapshot()`` runs on the
        snapshot-stage thread (device→host gather, chunked per leaf),
        then the result commits in submission order like any other
        payload. Only the fence write happens on the caller's thread.

        The snapshot closure must be safe to run concurrently with the
        caller's next steps — the manager builds it over immutable
        device arrays plus submit-time copies of mutable host leaves
        (:func:`stage_mutable_leaves`)."""
        self._enqueue(step, snapshot, True, fault)

    def _ensure_threads(self) -> None:
        if self._snap_thread is None or not self._snap_thread.is_alive():
            self._snap_thread = threading.Thread(
                target=self._run_snapshots, name="ckpt-async-snapshot",
                daemon=True,
            )
            self._snap_thread.start()
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="ckpt-async-commit", daemon=True
            )
            self._thread.start()

    # ---- snapshot stage (background thread) ----

    def _fail(self, step: int, e: BaseException) -> None:
        """Shared failure tail for both stages: record, unfence, report
        — the step loop never sees the exception."""
        with self._lock:
            self.errors.append((step, e))
            self._inflight_ts.pop(step, None)
        if self._root is not None and self._clear_fence_on_error:
            from . import integrity

            integrity.clear_inflight(self._root, step)
        if self._on_error is not None:
            try:
                self._on_error(step, e)
            except Exception:
                # invariant: waived — a broken error-callback must not mask the original write failure being reported
                pass

    def _retire(self) -> None:
        self._slots.release()
        with self._lock:
            self._outstanding -= 1
            if self._outstanding == 0:
                self._idle.set()

    def _run_snapshots(self) -> None:
        from .. import obs

        while True:
            item = self._snap_q.get()
            if item is None:
                return
            step, work, staged, fault, t_submit = item
            if staged:
                waited = time.perf_counter() - t_submit
                if waited > 1e-4:
                    # The gather sat behind an earlier snapshot — the
                    # stage-depth pressure signal, span-recorded so a
                    # trace shows WHICH save paid it.
                    rec = obs.tracer()
                    if rec is not None:
                        rec.emit(
                            "ckpt_snapshot_wait", "ckpt",
                            time.time() - waited, waited, step=step,
                        )
                try:
                    with obs.span("ckpt_snapshot", cat="ckpt", step=step):
                        work = work()
                except BaseException as e:  # noqa: BLE001 — a failed gather
                    # must not take the stage down; record and move on.
                    with self._lock:
                        self._in_snapshot -= 1
                    self._fail(step, e)
                    self._retire()
                    continue
            with self._lock:
                self._in_snapshot -= 1
            self._q.put((step, work, fault))

    # ---- commit stage (background thread) ----

    def _run(self) -> None:
        from .. import obs

        while True:
            item = self._q.get()
            if item is None:
                return
            step, payload, fault = item
            try:
                t0 = time.perf_counter()
                with obs.span("ckpt_commit", cat="ckpt", step=step):
                    self._commit(step, payload, fault)
                commit_s = time.perf_counter() - t0
                with self._lock:
                    self._last_committed = step
                    self.committed.append(step)
                    self._inflight_ts.pop(step, None)
                    depth = self._outstanding - 1
                    stage_depth = self._in_snapshot
                    oldest = min(self._inflight_ts.values(), default=None)
                if self._on_commit is not None:
                    args = [
                        step,
                        commit_s,
                        max(depth, 0),
                        (time.time() - oldest) if oldest else 0.0,
                    ]
                    if self._on_commit_takes_stage:
                        args.append(stage_depth)
                    try:
                        self._on_commit(*args)
                    except Exception:
                        # invariant: waived — telemetry must never fail a committed checkpoint
                        pass
            except BaseException as e:  # noqa: BLE001 — a failed commit
                # must never take the commit thread (and with it every
                # queued save) down; the failure is recorded and the
                # step loop keeps training.
                self._fail(step, e)
            finally:
                self._retire()

    # ---- barriers ----

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted commit has finished (committed or
        failed-and-recorded). Returns ``True`` when drained, ``False``
        on timeout WITH COMMITS STILL PENDING — callers that proceed on
        False are reading/exiting past undrained state and must say so
        (the manager's read barriers and workload exit log a warning).
        Does NOT raise on commit failure — check :attr:`errors` /
        re-save blocking if durability is mandatory."""
        return self._idle.wait(timeout)

    def last_committed_step(self) -> Optional[int]:
        """Newest step whose commit (including sidecar) finished."""
        with self._lock:
            return self._last_committed

    def pending(self) -> bool:
        return not self._idle.is_set()

    def stats(self) -> dict:
        """Live queue telemetry: submitted-undecided depth, the age of
        the oldest in-flight commit (0 when idle), and the snapshot-
        stage depth (submitted steps whose gather has not finished —
        the ``ckpt_stage_depth`` gauge source)."""
        with self._lock:
            oldest = min(self._inflight_ts.values(), default=None)
            return {
                "queue_depth": self._outstanding,
                "oldest_inflight_age_s": (
                    time.time() - oldest if oldest else 0.0
                ),
                "snapshot_depth": self._in_snapshot,
            }

    def close(self, timeout: Optional[float] = None) -> bool:
        """Drain, stop both stage threads, refuse further submits.
        Returns ``True`` when the drain completed; ``False`` (after a
        warning — an exit that abandons pending commits is exactly the
        silent data loss the barrier exists to prevent) when ``timeout``
        expired with commits still pending."""
        if self._closed:
            return True
        self._closed = True
        drained = self.wait(timeout)
        if not drained:
            with self._lock:
                left = self._outstanding
            print(
                f"[tpujob] warning: async checkpoint drain timed out "
                f"after {timeout}s with {left} commit(s) still pending — "
                "the newest saves may not be durable; recovery will fall "
                "back to the last sidecar-verified step",
                flush=True,
            )
        if self._snap_thread is not None and self._snap_thread.is_alive():
            self._snap_q.put(None)
            self._snap_thread.join(timeout)
        if self._thread is not None and self._thread.is_alive():
            self._q.put(None)
            self._thread.join(timeout)
        return drained

    def __enter__(self) -> "AsyncCheckpointWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
