"""Async checkpoint commits as a first-class, *verified* path.

The blocking save stalls the training loop for the full device→host
gather plus the backend write — at pod scale that stall IS the step-time
budget (TorchTitan ships async distributed checkpointing as a headline
feature for exactly this reason, PAPERS.md). The old ``block=False``
path overlapped the write but skipped the checksum sidecar, so
async-saved steps verified as "unknown" forever — second-class
checkpoints the integrity scan could not vouch for.

This module closes that hole with a commit protocol:

1. **Snapshot at save-call time** (:func:`snapshot_to_host`): the state
   is copied device→host (or host→host for numpy leaves) on the caller's
   thread BEFORE the call returns, so a later in-place donation or
   optimizer update cannot tear the bytes an in-flight commit is
   reading. The snapshot cost — a device_get — is the only stall the
   step loop pays.
2. **Single commit thread**: snapshots commit strictly in submission
   order on one background thread (save-while-save-in-flight
   serializes by construction), each through the shared backoff retry
   with partial-step cleanup, exactly like a blocking save.
3. **Sidecar at commit time**: the checksum sidecar is written when the
   bytes are durable — an async-saved step verifies ``True`` the moment
   :func:`~pytorch_operator_tpu.checkpoint.integrity.latest_verified_step`
   can see it.
4. **Inflight fencing**: an ``<step>.inflight`` marker is written at
   submit and cleared when the sidecar lands. A replica killed
   mid-commit leaves the marker behind, and the restore-side scan
   treats a fenced step as uncommitted — recovery resumes from the last
   sidecar-verified step instead of whatever bytes the crash left.
5. **Barriers**: ``wait()`` drains pending commits; ``close()`` drains
   and joins. The manager routes every read-side entry point
   (``restore*``, ``latest_step``, ``all_steps``) and workload exit
   through them, so nothing ever observes a half-committed directory.

A failed commit (e.g. a persistent ENOSPC after the retry budget) does
NOT kill the step loop: the partial step is cleaned, the failure is
recorded in :attr:`AsyncCheckpointWriter.errors` and reported on the
status channel as ``checkpoint_save_failed``, and later saves proceed —
restart-based recovery then falls back to the last verified step.

Deliberately jax-free and orbax-free: the commit callable owns the
backend, so the orbax manager (``manager.py``) and the JSON step files
the chaos workload writes (``workloads/exit_with.py``) share this exact
commit protocol — the crash-consistency tier-1 exercises without orbax
is the crash-consistency production checkpoints get.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, List, Optional, Tuple


def snapshot_to_host(tree: Any) -> Any:
    """Deep host copy of a pytree of arrays, safe to hand to a
    background commit while the caller keeps mutating (donating) the
    originals.

    jax arrays come back as host numpy via ``jax.device_get`` (a real
    transfer — the returned buffer is fresh); numpy arrays are COPIED
    (``device_get`` would return them aliased, and an aliased snapshot
    is exactly the torn-write bug this function exists to prevent).
    Non-array leaves pass through.
    """
    import numpy as np

    def snap(x):
        if isinstance(x, np.ndarray):
            return np.array(x, copy=True)
        if hasattr(x, "devices") or hasattr(x, "device_buffer"):
            import jax

            out = jax.device_get(x)
            if isinstance(out, np.ndarray) and not out.flags.owndata:
                # On the CPU backend device_get can return a ZERO-COPY
                # view of the device buffer — exactly the aliasing that
                # lets a donating step overwrite an in-flight commit.
                # The snapshot must own its bytes.
                out = np.array(out, copy=True)
            return out
        return x

    try:
        import jax

        return jax.tree.map(snap, tree)
    except ImportError:
        # jax-free callers (the JSON chaos workload): plain containers.
        if isinstance(tree, dict):
            return {k: snapshot_to_host(v) for k, v in tree.items()}
        if isinstance(tree, (list, tuple)):
            return type(tree)(snapshot_to_host(v) for v in tree)
        return snap(tree)


class AsyncCheckpointWriter:
    """Commits checkpoint payloads on ONE background thread, in
    submission order, with verified-at-commit semantics.

    ``commit(step, payload, fault)`` runs on the commit thread and must
    leave the step fully durable INCLUDING its checksum sidecar (the
    manager and exit_with both delegate to their existing fault-aware
    commit helpers). ``fault`` is the injection decision evaluated at
    submit time — occurrence counting happens in call order on the
    caller's thread, so a replayed plan fires the identical saves even
    though the I/O itself is asynchronous.

    ``root`` enables inflight fencing (integrity.mark_inflight at
    submit; integrity.write_sidecar clears it at commit).

    ``max_pending`` bounds how many host snapshots are alive at once
    (submit blocks when the budget is spent — backpressure, not
    unbounded host memory).
    """

    def __init__(
        self,
        commit: Callable[[int, Any, Optional[str]], None],
        *,
        root=None,
        max_pending: int = 2,
        on_error: Optional[Callable[[int, BaseException], None]] = None,
        on_commit: Optional[Callable[[int, float, int, float], None]] = None,
    ):
        if max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        self._commit = commit
        self._root = root
        self._on_error = on_error
        # Commit-telemetry hook: (step, commit_seconds, queue_depth_after,
        # oldest_inflight_age_seconds) after each successful commit — the
        # manager and exit_with report it on the status channel so the
        # supervisor's checkpoint-lag/queue surfaces stay live.
        self._on_commit = on_commit
        # step -> submit wall time of in-flight (submitted, undecided)
        # commits; drives the oldest-inflight-age gauge.
        self._inflight_ts: dict = {}
        self._slots = threading.Semaphore(max_pending)
        self._q: "queue.Queue" = queue.Queue()
        self._idle = threading.Event()
        self._idle.set()
        self._outstanding = 0  # submitted, not yet committed/failed
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._last_committed: Optional[int] = None
        self.committed: List[int] = []  # commit order (serialization pin)
        self.errors: List[Tuple[int, BaseException]] = []

    # ---- submit side (caller thread) ----

    def submit(self, step: int, payload: Any, fault: Optional[str] = None) -> None:
        """Enqueue one commit. Blocks only when ``max_pending`` snapshots
        are already in flight. The inflight fence for ``step`` is on
        disk before this returns."""
        if self._closed:
            raise RuntimeError("writer is closed")
        from .. import obs

        t0 = time.perf_counter()
        self._slots.acquire()
        waited = time.perf_counter() - t0
        if waited > 1e-4:
            # Backpressure made the STEP LOOP wait on the commit queue —
            # exactly the stall the flight recorder exists to show.
            rec = obs.tracer()
            if rec is not None:
                rec.emit(
                    "ckpt_queue_wait", "ckpt",
                    time.time() - waited, waited, step=step,
                )
        if self._root is not None:
            from . import integrity

            integrity.mark_inflight(self._root, step)
        with self._lock:
            # Outstanding count — not queue emptiness — drives the idle
            # barrier: the queue is briefly empty while the thread is
            # mid-commit, and wait() must not return then.
            self._outstanding += 1
            self._inflight_ts[step] = time.time()
            self._idle.clear()
            self._ensure_thread()
        self._q.put((step, payload, fault))

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="ckpt-async-commit", daemon=True
            )
            self._thread.start()

    # ---- commit side (background thread) ----

    def _run(self) -> None:
        from .. import obs

        while True:
            item = self._q.get()
            if item is None:
                return
            step, payload, fault = item
            try:
                t0 = time.perf_counter()
                with obs.span("ckpt_commit", cat="ckpt", step=step):
                    self._commit(step, payload, fault)
                commit_s = time.perf_counter() - t0
                with self._lock:
                    self._last_committed = step
                    self.committed.append(step)
                    self._inflight_ts.pop(step, None)
                    depth = self._outstanding - 1
                    oldest = min(self._inflight_ts.values(), default=None)
                if self._on_commit is not None:
                    try:
                        self._on_commit(
                            step,
                            commit_s,
                            max(depth, 0),
                            (time.time() - oldest) if oldest else 0.0,
                        )
                    except Exception:
                        pass  # telemetry must never fail a commit
            except BaseException as e:  # noqa: BLE001 — a failed commit
                # must never take the commit thread (and with it every
                # queued save) down; the failure is recorded and the
                # step loop keeps training.
                with self._lock:
                    self.errors.append((step, e))
                    self._inflight_ts.pop(step, None)
                if self._root is not None:
                    from . import integrity

                    integrity.clear_inflight(self._root, step)
                if self._on_error is not None:
                    try:
                        self._on_error(step, e)
                    except Exception:
                        pass
            finally:
                self._slots.release()
                with self._lock:
                    self._outstanding -= 1
                    if self._outstanding == 0:
                        self._idle.set()

    # ---- barriers ----

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until every submitted commit has finished (committed or
        failed-and-recorded). Does NOT raise on commit failure — check
        :attr:`errors` / re-save blocking if durability is mandatory."""
        self._idle.wait(timeout)

    def last_committed_step(self) -> Optional[int]:
        """Newest step whose commit (including sidecar) finished."""
        with self._lock:
            return self._last_committed

    def pending(self) -> bool:
        return not self._idle.is_set()

    def stats(self) -> dict:
        """Live queue telemetry: submitted-undecided depth and the age
        of the oldest in-flight commit (0 when idle)."""
        with self._lock:
            oldest = min(self._inflight_ts.values(), default=None)
            return {
                "queue_depth": self._outstanding,
                "oldest_inflight_age_s": (
                    time.time() - oldest if oldest else 0.0
                ),
            }

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain, stop the commit thread, refuse further submits."""
        if self._closed:
            return
        self._closed = True
        self.wait(timeout)
        if self._thread is not None and self._thread.is_alive():
            self._q.put(None)
            self._thread.join(timeout)

    def __enter__(self) -> "AsyncCheckpointWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
