"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

The reference has no long-context machinery (SURVEY.md §5 "Long-context /
sequence parallelism": absent); this is the TPU-native capability the
rebuild adds so sequences longer than one chip's HBM can be trained: shard
the sequence over ``sp``, keep Q local, and rotate K/V shards around the
ring with ``jax.lax.ppermute`` while accumulating attention in the
streaming (online-softmax / flash) form. Peak memory per chip is
O(S/sp · S/sp) for scores instead of O(S · S), and the ppermute rides ICI
neighbor links — the cheapest collective a TPU torus has.

Layout matches ``models/llama.py`` grouped-query attention:

- q: ``[B, S, K, G, D]`` (K kv-heads × G query groups)
- k, v: ``[B, S, K, D]``
- positions: ``[B, S]`` global token positions (drive the causal mask, so
  shards need no index arithmetic — masking keys on ``k_pos <= q_pos`` is
  correct regardless of which shard a block came from).

``ring_attention_shard`` is the per-shard body (usable under any manual
``shard_map``); ``ring_self_attention`` is the user-facing wrapper that
applies ``shard_map`` manual over ``sp`` only, leaving batch/head axes to
the compiler (partial-manual ``axis_names={'sp'}``).
"""

from __future__ import annotations

import functools


def ring_attention_shard(
    q,
    k,
    v,
    q_positions,
    kv_positions,
    *,
    axis_name: str = "sp",
    causal: bool = True,
):
    """Streaming attention over K/V shards rotated around ``axis_name``.

    Shapes (per shard): q ``[B,Sq,K,G,D]``, k/v ``[B,Skv,K,D]``,
    q_positions ``[B,Sq]``, kv_positions ``[B,Skv]``. Returns
    ``[B,Sq,K,G,D]`` in q's dtype.

    Accumulation is float32 online softmax: running max ``m``, denominator
    ``l``, numerator ``o``; each incoming K/V block rescales the
    accumulators by ``exp(m - m_new)``. Fully-masked blocks contribute
    exactly zero (their ``exp(scores - m_new)`` underflows to 0 against the
    finite mask value), and causal masking guarantees every query row sees
    at least its own diagonal in the step-0 (local) block, so ``m`` is
    finite from the first step and no NaN guards are needed.
    """
    import jax
    import jax.numpy as jnp

    B, Sq, K, G, D = q.shape
    from ..jaxcompat import axis_size

    n = axis_size(axis_name)
    scale = 1.0 / (D**0.5)
    neg = jnp.finfo(jnp.float32).min

    q32 = q.astype(jnp.float32) * scale

    def block(carry, kv_block):
        m, l, o = carry
        k_blk, v_blk, kv_pos = kv_block
        # [B,K,G,Sq,Skv] scores in f32 (MXU-friendly contraction).
        s = jnp.einsum(
            "bskgd,btkd->bkgst", q32, k_blk, preferred_element_type=jnp.float32
        )
        if causal:
            ok = kv_pos[:, None, None, None, :] <= q_positions[:, None, None, :, None]
            s = jnp.where(ok, s, neg)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)  # [B,K,G,Sq]
        p = jnp.exp(s - m_new[..., None])  # [B,K,G,Sq,Skv]
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bkgst,btkd->bkgsd", p, v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l, o

    # Remat the block: without it, grad through the ring loop saves every
    # step's [B,K,G,Sq,Skv] softmax intermediates as scan residuals —
    # O(Sq_local * S_total) per chip, the exact quadratic blowup this
    # module exists to avoid. Recomputing p in backward keeps residuals
    # at the carry + the rotated K/V blocks (linear in S).
    block = jax.checkpoint(block)

    # Accumulators start as (replicated) constants but become device-varying
    # after the first block; mark them varying over the ring axis up front so
    # the fori_loop carry type is stable (shard_map VMA typing).
    def varying(x):
        from ..jaxcompat import pcast_varying

        return pcast_varying(x, axis_name)

    m0 = varying(jnp.full((B, K, G, Sq), neg, jnp.float32))
    l0 = varying(jnp.zeros((B, K, G, Sq), jnp.float32))
    o0 = varying(jnp.zeros((B, K, G, Sq, D), jnp.float32))

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        del i
        (m, l, o), (k_cur, v_cur, pos_cur) = carry
        m, l, o = block((m, l, o), (k_cur, v_cur, pos_cur))
        # Rotate K/V (and their positions) one hop around the ring. The
        # final rotation is redundant work but keeps the loop body uniform
        # (and XLA overlaps the ppermute with the block math above).
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        pos_nxt = jax.lax.ppermute(pos_cur, axis_name, perm)
        return (m, l, o), (k_nxt, v_nxt, pos_nxt)

    # K/V rotate in their input dtype (bf16 in production) — halving ppermute
    # bytes over ICI; the einsums' preferred_element_type gives f32 accumulate.
    (m, l, o), _ = jax.lax.fori_loop(
        0, n, step, ((m0, l0, o0), (k, v, kv_positions))
    )
    # [B,K,G,Sq,D] → [B,Sq,K,G,D]; l is > 0 (causal diagonal) everywhere.
    out = o / l[..., None]
    return jnp.transpose(out, (0, 3, 1, 2, 4)).astype(q.dtype)


def ring_self_attention(
    q,
    k,
    v,
    positions,
    mesh,
    *,
    axis_name: str = "sp",
    causal: bool = True,
):
    """Global-view ring attention: shard the seq dim over ``axis_name``.

    q ``[B,S,K,G,D]``, k/v ``[B,S,K,D]``, positions ``[B,S]`` are global
    arrays (typically already seq-sharded by pjit); shard_map is manual over
    ``axis_name`` ONLY — batch and head dims stay compiler-managed so dp /
    fsdp / tp sharding composes without re-specifying it here.
    """
    import jax

    from ..jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    if (
        axis_name not in mesh.axis_names
        or mesh.shape[axis_name] == 1
        # A sequence that doesn't divide the ring cannot be sharded —
        # fall back to the single-shard path instead of a trace-time
        # shard_map error (same one-code-path promise as the degenerate
        # mesh case).
        or q.shape[1] % mesh.shape[axis_name]
    ):
        return _single_shard(q, k, v, positions, causal=causal)
    body = functools.partial(
        ring_attention_shard, axis_name=axis_name, causal=causal
    )
    return shard_map(
        lambda q, k, v, p: body(q, k, v, p, p),
        mesh=mesh,
        in_specs=(
            P(None, axis_name, None, None, None),
            P(None, axis_name, None, None),
            P(None, axis_name, None, None),
            P(None, axis_name),
        ),
        out_specs=P(None, axis_name, None, None, None),
        axis_names={axis_name},
    )(q, k, v, positions)


def _single_shard(q, k, v, positions, *, causal: bool):
    """Reference (non-ring) streaming attention on one shard — also the
    numerics oracle the ring path is tested against."""
    import jax.numpy as jnp

    D = q.shape[-1]
    s = jnp.einsum(
        "bskgd,btkd->bkgst",
        q.astype(jnp.float32),
        k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) / (D**0.5)
    if causal:
        ok = positions[:, None, None, None, :] <= positions[:, None, None, :, None]
        s = jnp.where(ok, s, jnp.finfo(jnp.float32).min)
    import jax

    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        "bkgst,btkd->bskgd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)
