"""Sharding rules: logical axes → mesh axes → NamedSharding.

TPU-native replacement for the reference's delegation to DDP/NCCL
(SURVEY.md §2 "Parallelism strategies..."): parameters and activations are
annotated with *logical* axis names; a rule table maps logical axes onto
mesh axes; XLA then inserts the collectives. This is the t5x/flax
"logical axis rules" pattern, kept dependency-light.

Also provides generic FSDP/ZeRO-3 parameter sharding that needs no
per-model annotations: shard each large parameter's largest
evenly-divisible dimension over the ``fsdp`` axis.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

MeshAxes = Union[str, Tuple[str, ...], None]
LogicalRules = Sequence[Tuple[str, MeshAxes]]

# Default rule table, mirroring common transformer layouts. Entries earlier
# in the table win. None = replicate. A tuple means "shard over these mesh
# axes jointly" (e.g. the global batch over BOTH dp and fsdp — fsdp is a
# data axis too in ZeRO-style sharding).
DEFAULT_RULES: LogicalRules = (
    ("batch", ("dp", "fsdp")),
    ("seq", "sp"),
    ("embed", "fsdp"),      # fsdp shards the embed dim of params
    ("mlp", "tp"),
    ("heads", "tp"),
    ("kv_heads", "tp"),
    ("vocab", "tp"),
    ("expert", "ep"),
    ("stage", "pp"),
    ("head_dim", None),
    ("norm", None),
    ("layers", None),       # scan-over-layers axis stays unsharded (pp later)
)


def filter_axis_for_mesh(mesh_ax: MeshAxes, mesh_axes: Optional[set]) -> MeshAxes:
    """Drop mesh axes absent from ``mesh_axes`` (None = keep everything);
    tuple entries are filtered member-wise and collapse to a bare string
    (one member) or None (empty). The ONE place this policy lives — both
    logical_to_spec and the flax-rules path (logical.rules_for_mesh) use it."""
    if mesh_ax is None or mesh_axes is None:
        return mesh_ax
    if isinstance(mesh_ax, tuple):
        kept = tuple(a for a in mesh_ax if a in mesh_axes)
        if not kept:
            return None
        return kept[0] if len(kept) == 1 else kept
    return mesh_ax if mesh_ax in mesh_axes else None


def logical_to_spec(logical_axes: Sequence[Optional[str]], rules: LogicalRules = DEFAULT_RULES, mesh=None):
    """Map a tuple of logical axis names to a PartitionSpec.

    Axes whose mesh axis is absent from the mesh fall back to replication
    (tuple entries are filtered member-wise), so the same annotations serve
    every mesh shape.
    """
    from jax.sharding import PartitionSpec

    table = {}
    for name, mesh_ax in rules:  # earlier entries win, as documented
        table.setdefault(name, mesh_ax)
    mesh_axes = set(mesh.axis_names) if mesh is not None else None

    out = [
        filter_axis_for_mesh(table.get(ax), mesh_axes) if ax is not None else None
        for ax in logical_axes
    ]
    # Trim trailing Nones (canonical PartitionSpec form).
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def named_sharding(mesh, *logical_axes: Optional[str], rules: LogicalRules = DEFAULT_RULES):
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, logical_to_spec(logical_axes, rules, mesh))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


# ---- generic FSDP (ZeRO-3) parameter sharding ----


def fsdp_spec(shape: Sequence[int], mesh, axis: str = "fsdp", min_elements: int = 2**16):
    """PartitionSpec sharding the largest evenly-divisible dim over ``axis``.

    Small params (below ``min_elements``) replicate — sharding tiny tensors
    costs more in collective latency than it saves in HBM.
    """
    from jax.sharding import PartitionSpec

    if axis not in mesh.axis_names:
        return PartitionSpec()
    size = mesh.shape[axis]
    n = 1
    for d in shape:
        n *= d
    if size <= 1 or n < min_elements:
        return PartitionSpec()
    # Largest dim divisible by the axis size wins; ties → earliest dim.
    best = None
    for i, d in enumerate(shape):
        if d % size == 0:
            if best is None or d > shape[best]:
                best = i
    if best is None:
        return PartitionSpec()
    spec = [None] * len(shape)
    spec[best] = axis
    while spec and spec[-1] is None:
        spec.pop()
    return PartitionSpec(*spec)


def fsdp_shardings(params: Any, mesh, axis: str = "fsdp", min_elements: int = 2**16):
    """Tree of NamedShardings implementing ZeRO-3 over ``axis`` for any
    parameter pytree."""
    import jax
    from jax.sharding import NamedSharding

    return jax.tree.map(
        lambda p: NamedSharding(mesh, fsdp_spec(p.shape, mesh, axis, min_elements)),
        params,
    )


def shard_tree(tree: Any, shardings: Any):
    """device_put a pytree onto a matching tree of shardings."""
    import jax

    return jax.device_put(tree, shardings)
