"""Ulysses-style sequence parallelism — all-to-all head/sequence swap.

The second of the two standard long-context parallelism schemes (the
reference has neither — SURVEY.md §5 "Long-context / sequence
parallelism": absent). Complementary to ring attention
(``parallel/ring.py``):

- **ring**: K/V shards rotate P times over ICI neighbor links; scores
  stay blockwise O(S/P x S/P) — minimal memory, P communication steps
  that must each hide behind a block of attention math.
- **ulysses** (this module, after DeepSpeed-Ulysses): ONE all-to-all
  re-shards activations from sequence-sharded to head-sharded, attention
  runs with the FULL sequence but 1/P of the kv-heads per device, and a
  second all-to-all swaps back. Two collectives total regardless of P
  (all-to-all is cheap on a TPU torus), at the price of full-S score
  blocks per local head — the right trade when heads are plentiful and
  S is moderate; ring wins when S is extreme.

Layout matches ``models/llama.py`` grouped-query attention (q
``[B,S,K,G,D]``, k/v ``[B,S,K,D]``, positions ``[B,S]``); requires
``n_kv_heads % sp == 0`` (heads are the resharding currency). Exposed in
the flagship model as ``attn_impl="ulysses"``.
"""

from __future__ import annotations


def _attend_full_seq(q, k, v, positions, *, causal: bool):
    """Dense softmax attention over the full sequence for the LOCAL head
    subset (heads are embarrassingly parallel, so per-device numerics are
    identical to the unsharded computation). Shares ring.py's oracle so
    the two sp schemes cannot drift numerically."""
    from .ring import _single_shard

    return _single_shard(q, k, v, positions, causal=causal)


def ulysses_attention_shard(
    q,
    k,
    v,
    positions_full,
    *,
    axis_name: str = "sp",
    causal: bool = True,
):
    """Per-shard body (usable under any manual ``shard_map``): q
    ``[B,S/P,K,G,D]``, k/v ``[B,S/P,K,D]`` sequence-sharded;
    ``positions_full`` ``[B,S]`` (every device needs the global positions
    for the causal mask). Returns ``[B,S/P,K,G,D]``."""
    import jax

    # seq-sharded -> head-sharded: split the kv-head axis P ways, gather
    # the sequence axis. tiled=True keeps plain array semantics.
    qh = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = _attend_full_seq(qh, kh, vh, positions_full, causal=causal)
    # head-sharded -> seq-sharded (the inverse swap).
    return jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_self_attention(
    q,
    k,
    v,
    positions,
    mesh,
    *,
    axis_name: str = "sp",
    causal: bool = True,
):
    """Global-view Ulysses attention: seq dim sharded over ``axis_name``.

    Mirrors ``ring_self_attention``'s contract: global arrays in/out,
    shard_map manual over ``axis_name`` ONLY (batch/head dims stay
    compiler-managed so dp/fsdp/tp sharding composes). Falls back to the
    single-shard path when the axis is absent/size-1 or the RUNTIME
    shape doesn't divide (S % P) — same one-code-path promise as ring's
    degenerate handling. A kv-head count that doesn't divide the sp
    extent is a STATIC config error and raises: silently running dense
    full-S attention at the long contexts ulysses exists for would lose
    the entire memory/perf win while the operator believes sp is active.
    """
    import functools

    from ..jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    from .ring import _single_shard

    n = mesh.shape.get(axis_name, 1) if axis_name in mesh.axis_names else 1
    if n > 1 and q.shape[2] % n:
        raise ValueError(
            f"attn_impl='ulysses' needs n_kv_heads % {axis_name} == 0 "
            f"(kv heads are the resharding currency): got "
            f"{q.shape[2]} kv heads, {axis_name}={n}. Use a config with "
            f"divisible kv heads, a smaller {axis_name}, or attn_impl='ring'."
        )
    if n == 1 or q.shape[1] % n:
        return _single_shard(q, k, v, positions, causal=causal)

    body = functools.partial(
        ulysses_attention_shard, axis_name=axis_name, causal=causal
    )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(None, axis_name, None, None, None),
            P(None, axis_name, None, None),
            P(None, axis_name, None, None),
            P(),  # positions replicated: the mask needs the global view
        ),
        out_specs=P(None, axis_name, None, None, None),
        axis_names={axis_name},
    )(q, k, v, positions)
