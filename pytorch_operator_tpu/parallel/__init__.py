"""Parallelism: device meshes, sharding rules, collectives.

The TPU-native stand-in for the NCCL/c10d layer the reference wires up but
does not implement (SURVEY.md §2 parallelism table): jax.sharding meshes +
XLA collectives over ICI/DCN.
"""

from .mesh import (  # noqa: F401
    MESH_AXIS_ORDER,
    make_hybrid_mesh,
    make_mesh,
    mesh_from_env,
    parse_mesh_spec,
    resolve_axis_sizes,
)
from .sharding import (  # noqa: F401
    DEFAULT_RULES,
    fsdp_shardings,
    fsdp_spec,
    logical_to_spec,
    named_sharding,
    replicated,
    shard_tree,
)
from .logical import (  # noqa: F401
    activation_rules,
    init_sharded,
    logical_shardings,
    rules_for_mesh,
)
from .data import (  # noqa: F401
    epoch_batches,
    global_batch,
    put_global,
    shard_batch_size,
)
from .moe import moe_mlp  # noqa: F401
from .pipeline import pipeline_apply, pipeline_value_and_grad  # noqa: F401
from .ring import (  # noqa: F401
    ring_attention_shard,
    ring_self_attention,
)
from .ulysses import (  # noqa: F401
    ulysses_attention_shard,
    ulysses_self_attention,
)
from . import collectives  # noqa: F401
