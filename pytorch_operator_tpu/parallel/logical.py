"""Logical-axis → mesh plumbing for annotated flax models.

The transformer models (models/llama.py, models/bert.py) tag every parameter
with logical axis names via ``nn.with_logical_partitioning``. This module
turns those tags into concrete ``NamedSharding``s for a given mesh (missing
mesh axes degrade to replication, so one set of annotations serves every
mesh shape) and runs a sharded init — parameters are *born* on their target
devices/shards; no host-side init + scatter round trip.

Reference analog: none — the reference delegates all of this to DDP/NCCL
inside user containers (SURVEY.md §2 "Parallelism strategies"); this is the
XLA-collectives-over-ICI replacement.
"""

from __future__ import annotations

from typing import Any, Callable

from .sharding import DEFAULT_RULES, LogicalRules, filter_axis_for_mesh


def rules_for_mesh(mesh, rules: LogicalRules = DEFAULT_RULES) -> LogicalRules:
    """Filter a rule table down to axes the mesh actually has.

    flax's ``logical_to_mesh_sharding`` (and ``with_logical_constraint``)
    require every referenced mesh axis to exist; dropping absent axes here is
    what makes annotations portable across mesh shapes.
    """
    names = set(mesh.axis_names)
    return tuple(
        (logical, filter_axis_for_mesh(ax, names)) for logical, ax in rules
    )


def logical_shardings(abstract_tree: Any, mesh, rules: LogicalRules = DEFAULT_RULES):
    """NamedShardings for a (possibly abstract) tree of flax ``Partitioned``
    leaves — pass ``jax.eval_shape(model.init, ...)`` output.

    Leaves whose rank is LOWER than their inherited spec fall back to
    replicated: optimizer states that reduce over param axes (adafactor's
    factored ``v_row``/``v_col`` vectors for a matrix param) inherit the
    param's logical axes through the state pytree but cannot carry a
    higher-rank PartitionSpec — and as reduced statistics they are small
    enough that replication is the right layout.
    """
    import flax.linen as nn
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    specs = nn.get_partition_spec(abstract_tree)
    shardings = nn.logical_to_mesh_sharding(
        specs, mesh, rules_for_mesh(mesh, rules)
    )
    replicated = NamedSharding(mesh, PartitionSpec())

    def fix(leaf, sh):
        target = sh.value if hasattr(sh, "value") else sh
        if (
            isinstance(target, NamedSharding)
            and hasattr(leaf, "ndim")
            and leaf.ndim < len(target.spec)
        ):
            return sh.replace_boxed(replicated) if hasattr(sh, "replace_boxed") else replicated
        return sh

    leaves = jax.tree.leaves(
        abstract_tree, is_leaf=lambda x: hasattr(x, "unbox")
    )
    sh_leaves = jax.tree.leaves(
        shardings, is_leaf=lambda x: hasattr(x, "unbox") or isinstance(x, NamedSharding)
    )
    if len(leaves) == len(sh_leaves):
        fixed = [
            fix(l.unbox() if hasattr(l, "unbox") else l, s)
            for l, s in zip(leaves, sh_leaves)
        ]
        treedef = jax.tree.structure(
            shardings,
            is_leaf=lambda x: hasattr(x, "unbox") or isinstance(x, NamedSharding),
        )
        return jax.tree.unflatten(treedef, fixed)
    return shardings


def init_sharded(
    init_fn: Callable, mesh, *init_args, rules: LogicalRules = DEFAULT_RULES
):
    """jit ``init_fn`` with out_shardings derived from logical annotations.

    Returns ``(variables, shardings)`` with metadata boxes removed —
    variables are plain arrays already laid out on the mesh.
    """
    import flax.linen as nn
    import jax

    abstract = jax.eval_shape(init_fn, *init_args)
    shardings = logical_shardings(abstract, mesh, rules)
    variables = jax.jit(init_fn, out_shardings=shardings)(*init_args)
    return nn.meta.unbox(variables), nn.meta.unbox(shardings)


def activation_rules(mesh, rules: LogicalRules = DEFAULT_RULES):
    """Context manager making ``nn.with_logical_constraint`` inside model
    code bind to this mesh's axes: run apply/train steps under
    ``with mesh, activation_rules(mesh): ...``."""
    import flax.linen as nn

    return nn.logical_axis_rules(rules_for_mesh(mesh, rules))
