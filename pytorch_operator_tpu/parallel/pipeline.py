"""GPipe-style pipeline parallelism over the ``pp`` mesh axis.

Reference parity note: the reference has no pipeline support at all
(SURVEY.md §2 parallelism table) — this is beyond-parity, completing the
mesh-axis vocabulary (dp/fsdp/tp/sp/ep/pp) with an executable pp path.

TPU-first design: no per-stage processes or NCCL send/recv. The whole
pipeline is ONE jitted SPMD program under ``shard_map``: every stage holds
its slice of the layer-stacked params (leading axis sharded over ``pp``),
a ``lax.scan`` walks the M + P - 1 schedule ticks, and activations hop to
the next stage with ``lax.ppermute`` riding ICI. Autodiff through the scan
+ ppermute yields the reverse pipeline schedule for free (ppermute's
transpose is the reverse rotation), so backward needs no hand scheduling.

The bubble fraction is the textbook (P-1)/(M+P-1) — raise ``microbatches``
to amortize. Stages compute on every tick (bubble ticks process garbage
that is masked out), which keeps the program shape static for XLA.
"""

from __future__ import annotations

from typing import Callable


def pipeline_apply(
    fn: Callable,
    stage_params,
    x,
    *,
    mesh,
    microbatches: int,
    axis: str = "pp",
):
    """Run ``y = fn(params_P-1, fn(..., fn(params_0, x)))`` as a pipeline.

    ``stage_params``: pytree whose leaves have leading axis P (one slice
    per stage) — the layout ``nn.scan``-stacked layer params already have.
    ``fn(params_slice, act) -> act`` is one stage's computation and must
    preserve the activation shape (transformer-block style).
    ``x``: the global batch ``[B, ...]``; ``B % microbatches == 0``.
    Returns the pipeline output, replicated over the ``pp`` axis.

    Pure and composable: call it under your own ``jit``/``grad`` (inputs
    are resharded to the pipeline layout by the surrounding jit; autodiff
    produces the reverse pipeline schedule).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    n_stages = mesh.shape[axis]
    M = microbatches
    B = x.shape[0]
    if M < 1:
        raise ValueError("microbatches must be >= 1")
    if B % M:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")

    leading = {leaf.shape[0] for leaf in jax.tree.leaves(stage_params)}
    if leading != {n_stages}:
        raise ValueError(
            f"stage_params leading axes {leading} != pp extent {n_stages}"
        )

    # Params: leading (stage) axis sharded over pp; activations replicated
    # across pp (each stage sees the full microbatch stream, uses its turn).
    param_spec = jax.tree.map(lambda _: P(axis), stage_params)

    def per_stage(params_local, x_local):
        # params_local leaves: [1, ...] (this stage's slice).
        params_local = jax.tree.map(lambda l: l[0], params_local)
        s = jax.lax.axis_index(axis)
        xm = x_local.reshape((M, B // M) + x_local.shape[1:])
        zero_mb = jnp.zeros_like(xm[0])

        def tick(carry, t):
            act_in, outs = carry
            # Stage 0 ingests microbatch t (drain ticks t >= M reuse the
            # last microbatch; their outputs never reach the valid output
            # window); later stages take the handoff.
            mb = jax.lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, M - 1), 0, keepdims=False
            )
            inp = jnp.where(s == 0, mb, act_in)
            y = fn(params_local, inp)
            # The last stage emits microbatch t-(P-1) on tick t.
            out_idx = t - (n_stages - 1)
            valid = (s == n_stages - 1) & (out_idx >= 0)
            safe_idx = jnp.clip(out_idx, 0, M - 1)
            current = jax.lax.dynamic_index_in_dim(
                outs, safe_idx, 0, keepdims=False
            )
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(valid, y, current), safe_idx, 0
            )
            # Rotate activations one stage forward around the ring.
            act_next = jax.lax.ppermute(
                y, axis, [(i, (i + 1) % n_stages) for i in range(n_stages)]
            )
            return (act_next, outs), None

        # The carry becomes pp-varying after the first tick (axis_index /
        # ppermute); mark the zero-initialized carry varying up front so
        # scan's carry types line up.
        init = jax.tree.map(
            lambda a: jax.lax.pcast(a, (axis,), to="varying"),
            (zero_mb, jnp.zeros_like(xm)),
        )
        (_, outs), _ = jax.lax.scan(tick, init, jnp.arange(M + n_stages - 1))
        # Only the last stage holds real outputs; zero-mask + psum
        # replicates them to every stage (loss code runs everywhere).
        outs = jax.lax.psum(
            jnp.where(s == n_stages - 1, outs, jnp.zeros_like(outs)), axis
        )
        return outs.reshape(x_local.shape)

    return shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(param_spec, P()),
        out_specs=P(),
    )(stage_params, x)
