"""GPipe-style pipeline parallelism over the ``pp`` mesh axis.

Reference parity note: the reference has no pipeline support at all
(SURVEY.md §2 parallelism table) — this is beyond-parity, completing the
mesh-axis vocabulary (dp/fsdp/tp/sp/ep/pp) with an executable pp path.

TPU-first design: no per-stage processes or NCCL send/recv. The whole
pipeline is ONE jitted SPMD program under ``shard_map``: every stage holds
its slice of the layer-stacked params (leading axis sharded over ``pp``),
a ``lax.scan`` walks the M + P - 1 schedule ticks, and activations hop to
the next stage with ``lax.ppermute`` riding ICI. Autodiff through the scan
+ ppermute yields the reverse pipeline schedule for free (ppermute's
transpose is the reverse rotation), so backward needs no hand scheduling.

Memory model (round-2 rewrite): activations are **stage-local**. The
input's microbatch stream is sharded over ``pp`` (each device owns
M/P microbatches of input and M/P of output), and exactly ONE microbatch
is in flight per stage: tick t moves mb t from its owner to stage 0
(masked psum), stages compute, the result hops one stage down the ring,
and the last stage's finished microbatch returns to its owner (masked
psum). Per-device forward residency is therefore O(B/P) input/output
shard + O(microbatch) transit — not the O(B) fully-replicated stream of
the round-1 version (VERDICT weak #3). Backward keeps the GPipe-standard
per-stage residual of its own M microbatch activations; wrap ``fn`` in
``jax.checkpoint`` to cut that to O(microbatch) recompute.

The bubble fraction is the textbook (P-1)/(M+P-1) — raise ``microbatches``
to amortize. Stages compute on every tick (bubble ticks process garbage
that is masked out), which keeps the program shape static for XLA.
"""

from __future__ import annotations

from typing import Callable


def pipeline_apply(
    fn: Callable,
    stage_params,
    x,
    *,
    mesh,
    microbatches: int,
    axis: str = "pp",
):
    """Run ``y = fn(params_P-1, fn(..., fn(params_0, x)))`` as a pipeline.

    ``stage_params``: pytree whose leaves have leading axis P (one slice
    per stage) — the layout ``nn.scan``-stacked layer params already have.
    ``fn(params_slice, act) -> act`` is one stage's computation and must
    preserve the activation shape (transformer-block style).
    ``x``: the global batch ``[B, ...]``; ``B % microbatches == 0`` and
    ``microbatches % P == 0`` (the stream is sharded over ``pp``).
    Returns the pipeline output as a global ``[B, ...]`` array whose
    microbatch groups are sharded over ``pp``; under the surrounding
    ``jit`` any consumer (loss, optimizer) reshards as needed.

    Pure and composable: call it under your own ``jit``/``grad`` (inputs
    are resharded to the pipeline layout by the surrounding jit; autodiff
    produces the reverse pipeline schedule).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    n_stages = mesh.shape[axis]
    M = microbatches
    B = x.shape[0]
    if M < 1:
        raise ValueError("microbatches must be >= 1")
    if B % M:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    if M % n_stages:
        raise ValueError(
            f"microbatches {M} not divisible by pp extent {n_stages} "
            "(the microbatch stream is sharded over pp)"
        )

    leading = {leaf.shape[0] for leaf in jax.tree.leaves(stage_params)}
    if leading != {n_stages}:
        raise ValueError(
            f"stage_params leading axes {leading} != pp extent {n_stages}"
        )

    param_spec = jax.tree.map(lambda _: P(axis), stage_params)
    mb_per_dev = M // n_stages
    # [B, ...] -> [M, B/M, ...]; the microbatch axis is sharded over pp so
    # each device owns only its M/P microbatches of input and output.
    xm = x.reshape((M, B // M) + x.shape[1:])

    def per_stage(params_local, xm_local):
        # params_local leaves: [1, ...] (this stage's slice);
        # xm_local: [M/P, B/M, ...] (this device's input microbatches).
        params_local = jax.tree.map(lambda l: l[0], params_local)
        s = jax.lax.axis_index(axis)
        zero_mb = jnp.zeros_like(xm_local[0])

        def tick(carry, t):
            act_in, outs_local = carry
            # Feed: microbatch t lives on device t // (M/P) at local index
            # t % (M/P). Its owner contributes it, everyone else zeros;
            # the psum lands it on every stage but only stage 0 ingests.
            # (One O(mb) collective per tick — activation-hop sized, the
            # price of not replicating the O(B) stream on every stage.)
            t_in = jnp.clip(t, 0, M - 1)  # drain ticks reuse the last mb
            feed = jnp.where(
                s == t_in // mb_per_dev,
                jax.lax.dynamic_index_in_dim(
                    xm_local, t_in % mb_per_dev, 0, keepdims=False
                ),
                zero_mb,
            )
            mb = jax.lax.psum(feed, axis)
            inp = jnp.where(s == 0, mb, act_in)
            y = fn(params_local, inp)
            # The last stage finishes microbatch j = t-(P-1) on tick t;
            # ship it back to j's owner (masked psum again) and store it
            # in the owner's local output shard.
            j = t - (n_stages - 1)
            j_safe = jnp.clip(j, 0, M - 1)
            done = jax.lax.psum(
                jnp.where(s == n_stages - 1, y, jnp.zeros_like(y)), axis
            )
            write = (j >= 0) & (s == j_safe // mb_per_dev)
            slot = j_safe % mb_per_dev
            current = jax.lax.dynamic_index_in_dim(
                outs_local, slot, 0, keepdims=False
            )
            outs_local = jax.lax.dynamic_update_index_in_dim(
                outs_local, jnp.where(write, done, current), slot, 0
            )
            # Rotate activations one stage forward around the ring.
            act_next = jax.lax.ppermute(
                y, axis, [(i, (i + 1) % n_stages) for i in range(n_stages)]
            )
            return (act_next, outs_local), None

        # The carry is pp-varying from the start: both elements derive
        # from the pp-sharded input (unlike the round-1 replicated-x
        # design, which needed an explicit pcast).
        init = (zero_mb, jnp.zeros_like(xm_local))
        (_, outs_local), _ = jax.lax.scan(
            tick, init, jnp.arange(M + n_stages - 1)
        )
        return outs_local

    outs = shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(param_spec, P(axis)),
        out_specs=P(axis),
        # Partial-manual: only pp is taken over; other mesh axes (dp,
        # fsdp, tp, ...) stay with the compiler, so a dp×pp mesh still
        # data-parallelizes the per-microbatch compute inside each stage.
        axis_names={axis},
    )(stage_params, xm)
    return outs.reshape(x.shape)
