"""GPipe-style pipeline parallelism over the ``pp`` mesh axis.

Reference parity note: the reference has no pipeline support at all
(SURVEY.md §2 parallelism table) — this is beyond-parity, completing the
mesh-axis vocabulary (dp/fsdp/tp/sp/ep/pp) with an executable pp path.

TPU-first design: no per-stage processes or NCCL send/recv. The whole
pipeline is ONE jitted SPMD program under ``shard_map``: every stage holds
its slice of the layer-stacked params (leading axis sharded over ``pp``),
a ``lax.scan`` walks the M + P - 1 schedule ticks, and activations hop to
the next stage with ``lax.ppermute`` riding ICI. Autodiff through the scan
+ ppermute yields the reverse pipeline schedule for free (ppermute's
transpose is the reverse rotation), so backward needs no hand scheduling.

Memory model (round-2 rewrite): activations are **stage-local**. The
input's microbatch stream is sharded over ``pp`` (each device owns
M/P microbatches of input and M/P of output), and exactly ONE microbatch
is in flight per stage: tick t moves mb t from its owner to stage 0
(masked psum), stages compute, the result hops one stage down the ring,
and the last stage's finished microbatch returns to its owner (masked
psum). Per-device forward residency is therefore O(B/P) input/output
shard + O(microbatch) transit — not the O(B) fully-replicated stream of
the round-1 version (VERDICT weak #3). Backward keeps the GPipe-standard
per-stage residual of its own M microbatch activations; wrap ``fn`` in
``jax.checkpoint`` to cut that to O(microbatch) recompute.

The bubble fraction is the textbook (P-1)/(M+P-1) — raise ``microbatches``
to amortize. Stages compute on every tick (bubble ticks process garbage
that is masked out), which keeps the program shape static for XLA.
"""

from __future__ import annotations

from typing import Callable


def pipeline_apply(
    fn: Callable,
    stage_params,
    x,
    *,
    mesh,
    microbatches: int,
    axis: str = "pp",
):
    """Run ``y = fn(params_P-1, fn(..., fn(params_0, x)))`` as a pipeline.

    ``stage_params``: pytree whose leaves have leading axis P (one slice
    per stage) — the layout ``nn.scan``-stacked layer params already have.
    ``fn(params_slice, act) -> act`` is one stage's computation and must
    preserve the activation shape (transformer-block style).
    ``x``: the global batch ``[B, ...]``; ``B % microbatches == 0`` and
    ``microbatches % P == 0`` (the stream is sharded over ``pp``).
    Returns the pipeline output as a global ``[B, ...]`` array whose
    microbatch groups are sharded over ``pp``; under the surrounding
    ``jit`` any consumer (loss, optimizer) reshards as needed.

    Pure and composable: call it under your own ``jit``/``grad`` (inputs
    are resharded to the pipeline layout by the surrounding jit; autodiff
    produces the reverse pipeline schedule).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ..jaxcompat import shard_map

    n_stages = mesh.shape[axis]
    M = microbatches
    B = x.shape[0]
    if M < 1:
        raise ValueError("microbatches must be >= 1")
    if B % M:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    if M % n_stages:
        raise ValueError(
            f"microbatches {M} not divisible by pp extent {n_stages} "
            "(the microbatch stream is sharded over pp)"
        )

    leading = {leaf.shape[0] for leaf in jax.tree.leaves(stage_params)}
    if leading != {n_stages}:
        raise ValueError(
            f"stage_params leading axes {leading} != pp extent {n_stages}"
        )

    param_spec = jax.tree.map(lambda _: P(axis), stage_params)
    mb_per_dev = M // n_stages
    # [B, ...] -> [M, B/M, ...]; the microbatch axis is sharded over pp so
    # each device owns only its M/P microbatches of input and output.
    xm = x.reshape((M, B // M) + x.shape[1:])

    def per_stage(params_local, xm_local):
        # params_local leaves: [1, ...] (this stage's slice);
        # xm_local: [M/P, B/M, ...] (this device's input microbatches).
        params_local = jax.tree.map(lambda l: l[0], params_local)
        s = jax.lax.axis_index(axis)
        zero_mb = jnp.zeros_like(xm_local[0])

        def tick(carry, t):
            act_in, outs_local = carry
            # Feed: microbatch t lives on device t // (M/P) at local index
            # t % (M/P). Its owner contributes it, everyone else zeros;
            # the psum lands it on every stage but only stage 0 ingests.
            # (One O(mb) collective per tick — activation-hop sized, the
            # price of not replicating the O(B) stream on every stage.)
            t_in = jnp.clip(t, 0, M - 1)  # drain ticks reuse the last mb
            feed = jnp.where(
                s == t_in // mb_per_dev,
                jax.lax.dynamic_index_in_dim(
                    xm_local, t_in % mb_per_dev, 0, keepdims=False
                ),
                zero_mb,
            )
            mb = jax.lax.psum(feed, axis)
            inp = jnp.where(s == 0, mb, act_in)
            y = fn(params_local, inp)
            # The last stage finishes microbatch j = t-(P-1) on tick t;
            # ship it back to j's owner (masked psum again) and store it
            # in the owner's local output shard.
            j = t - (n_stages - 1)
            j_safe = jnp.clip(j, 0, M - 1)
            done = jax.lax.psum(
                jnp.where(s == n_stages - 1, y, jnp.zeros_like(y)), axis
            )
            write = (j >= 0) & (s == j_safe // mb_per_dev)
            slot = j_safe % mb_per_dev
            current = jax.lax.dynamic_index_in_dim(
                outs_local, slot, 0, keepdims=False
            )
            outs_local = jax.lax.dynamic_update_index_in_dim(
                outs_local, jnp.where(write, done, current), slot, 0
            )
            # Rotate activations one stage forward around the ring.
            act_next = jax.lax.ppermute(
                y, axis, [(i, (i + 1) % n_stages) for i in range(n_stages)]
            )
            return (act_next, outs_local), None

        # The carry is pp-varying from the start: both elements derive
        # from the pp-sharded input (unlike the round-1 replicated-x
        # design, which needed an explicit pcast).
        init = (zero_mb, jnp.zeros_like(xm_local))
        (_, outs_local), _ = jax.lax.scan(
            tick, init, jnp.arange(M + n_stages - 1)
        )
        return outs_local

    outs = shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(param_spec, P(axis)),
        out_specs=P(axis),
        # Partial-manual: only pp is taken over; other mesh axes (dp,
        # fsdp, tp, ...) stay with the compiler, so a dp×pp mesh still
        # data-parallelizes the per-microbatch compute inside each stage.
        axis_names={axis},
    )(stage_params, xm)
    return outs.reshape(x.shape)


def pipeline_value_and_grad(
    fn: Callable,
    loss_fn: Callable,
    stage_params,
    loss_params,
    x,
    targets,
    *,
    mesh,
    microbatches: int,
    axis: str = "pp",
    schedule: str = "1f1b",
    sharded_loss: bool = False,
    backward: str = "recompute",
):
    """Fused pipelined train-step gradients: returns
    ``(loss, (d_stage_params, d_loss_params, dx))`` for

        L = mean_j loss_fn(loss_params, fn(params_{P-1}, ... fn(params_0,
            x_j)), targets_j)

    over ``microbatches`` microbatches j.

    ``schedule="gpipe"`` is ``jax.value_and_grad`` over
    :func:`pipeline_apply` (autodiff's reverse pipeline): simple, but
    every stage's backward holds residuals for ALL M of its microbatches
    — per-stage activation residency O(M·mb).

    ``schedule="1f1b"`` interleaves one-forward-one-backward in a single
    ``lax.scan``: at tick t stage s forwards microbatch ``t - s`` and
    backwards microbatch ``t - 2(P-1) + s`` (the last stage backwards a
    microbatch the same tick its forward finishes — the 1F1B signature).
    Only the stage INPUT of each in-flight microbatch is saved, in a ring
    buffer of depth 2P whose size is set by the schedule's in-flight
    window 2(P-1-s)+1 <= 2P-1 ticks — per-stage residency O(P·mb),
    INDEPENDENT of M (the memory regression test pins this), with the
    stage body recomputed from the saved input during backward
    (remat-equivalent FLOPs). Numerics match "gpipe" exactly: same fn,
    same loss, same masked-psum stream layout — only the execution order
    differs. Cotangents ride the reverse ring (``ppermute`` i -> i-1)
    while forward activations ride i -> i+1, so steady-state ticks carry
    1F + 1B concurrently and the schedule finishes in M + 2(P-1) ticks.

    ``loss_fn(loss_params, y_mb, target_mb) -> scalar`` (mean over the
    microbatch); its gradients are accumulated at the last stage and
    psum-replicated out. With ``sharded_loss=False`` the loss body is
    computed per-stage inside the manual-pp region (masked to the last
    stage's result), so its FLOPs duplicate P-fold over pp — fine ONLY
    when loss_fn is a genuinely cheap tail. For an LM tail (head matmul
    over a large vocab + xent) that duplication is a cliff: use
    ``sharded_loss=True``.

    ``sharded_loss=True`` partitions the loss itself over the pp axis
    (the round-4 fix for the P-fold duplication): ``loss_params`` leaves
    must carry a leading axis P (stage s owns slice s — e.g. a vocab-
    chunked LM head ``[P, d, V/P]``; replicate tiny leaves by stacking P
    copies), and ``loss_fn(lp_slice, y_mb, target_mb)`` runs SPMD on
    EVERY stage each tick over the LAST stage's finished microbatch
    (broadcast to all stages by one masked O(mb) psum). loss_fn must
    combine its per-chunk partials with collectives over ``axis`` (psum
    / pmax — e.g. the standard vocab-parallel log-sum-exp) and return
    the combined scalar, identical on every stage (pp-invariant; the
    vma checker rejects a loss_fn that forgets to combine). Total loss
    FLOPs drop from P× to (M+2P-2)/M ≈ 1× and the work is load-balanced
    across stages instead of riding the last one. Returned
    ``d_loss_params`` then also carries the leading P axis: chunked
    leaves get their own chunk's gradient; stacked-replicated leaves
    must be summed over the leading axis by the caller (the total
    gradient of a shared leaf is the sum of its per-stage partials).

    ``backward`` (1f1b only) picks what the per-stage ring buffer holds:

    - ``"recompute"`` (default, always correct): save each in-flight
      microbatch's stage INPUT and re-run the stage forward during its
      backward tick — full-remat 1F1B. Minimal memory, but one extra
      stage forward per microbatch versus GPipe (which reuses the
      forward pass's saved residuals).
    - ``"stored"`` (Megatron-style compute parity): save the stage
      forward's VJP RESIDUALS (``jax.vjp``'s function pytree — honoring
      any ``jax.checkpoint`` policy inside ``fn``) for in-flight
      microbatches, so backward reuses them — no recompute, FLOPs equal
      GPipe's per application, residency still O(P) microbatches.
      Residual leaves whose shapes do NOT change with the microbatch
      size (weights, casted weights, position tables) are taken from the
      current tick's forward instead of the ring — they are assumed
      input-independent. That assumption is a shape heuristic: a ``fn``
      whose residuals depend on input VALUES but not input SHAPES (no
      transformer block does this; a batch-mean would) must use
      ``"recompute"``.

    Like :func:`pipeline_apply`: pure, call under your own ``jit``;
    only ``axis`` is taken manual, other mesh axes stay with the
    compiler. ``targets`` must lead with the same batch axis as ``x``.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ..jaxcompat import shard_map

    if backward not in ("recompute", "stored"):
        raise ValueError(
            f"backward={backward!r} not in ('recompute', 'stored')"
        )

    def _check_loss_chunks(lp_tree, n):
        lead = {
            leaf.shape[0] if leaf.ndim else None
            for leaf in jax.tree.leaves(lp_tree)
        }
        if lead != {n}:
            raise ValueError(
                f"sharded_loss=True: loss_params leading axes {lead} != "
                f"pp extent {n} (every leaf must be stage-chunked)"
            )

    if schedule == "gpipe":

        def total_loss(sp, lp, xx):
            y = pipeline_apply(
                fn, sp, xx, mesh=mesh, microbatches=microbatches, axis=axis
            )
            ym = y.reshape((microbatches, y.shape[0] // microbatches) + y.shape[1:])
            tm = targets.reshape(
                (microbatches, targets.shape[0] // microbatches)
                + targets.shape[1:]
            )
            if sharded_loss:
                # Same contract as the 1f1b sharded path: lp is stage-
                # chunked and loss_fn combines over ``axis`` internally,
                # so it must run inside a manual-pp region. Each stage
                # gathers the full microbatch stream (the pipeline
                # output is pp-sharded over microbatch groups) and
                # computes its chunk for every microbatch.
                def per_stage_loss(lp_local, ym_local, tm_local):
                    lp_local = jax.tree.map(lambda l: l[0], lp_local)
                    y_all = jax.lax.all_gather(ym_local, axis, axis=0, tiled=True)
                    t_all = jax.lax.all_gather(tm_local, axis, axis=0, tiled=True)
                    return jnp.mean(
                        jax.vmap(lambda a, b: loss_fn(lp_local, a, b))(
                            y_all, t_all
                        )
                    )

                lspec = jax.tree.map(lambda _: P(axis), lp)
                return shard_map(
                    per_stage_loss,
                    mesh=mesh,
                    in_specs=(lspec, P(axis), P(axis)),
                    out_specs=P(),
                    axis_names={axis},
                )(lp, ym, tm)

            def one(j):
                return loss_fn(lp, ym[j], tm[j])

            return jnp.mean(jax.vmap(one)(jnp.arange(microbatches)))

        if sharded_loss:
            _check_loss_chunks(loss_params, mesh.shape[axis])
        loss, grads = jax.value_and_grad(total_loss, argnums=(0, 1, 2))(
            stage_params, loss_params, x
        )
        return loss, grads
    if schedule != "1f1b":
        raise ValueError(f"schedule={schedule!r} not in ('gpipe', '1f1b')")

    n_stages = mesh.shape[axis]
    M = microbatches
    B = x.shape[0]
    if M < 1:
        raise ValueError("microbatches must be >= 1")
    if B % M:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    if M % n_stages:
        raise ValueError(
            f"microbatches {M} not divisible by pp extent {n_stages} "
            "(the microbatch stream is sharded over pp)"
        )
    leading = {leaf.shape[0] for leaf in jax.tree.leaves(stage_params)}
    if leading != {n_stages}:
        raise ValueError(
            f"stage_params leading axes {leading} != pp extent {n_stages}"
        )

    param_spec = jax.tree.map(lambda _: P(axis), stage_params)
    if sharded_loss:
        _check_loss_chunks(loss_params, n_stages)
    loss_spec = jax.tree.map(
        lambda _: P(axis) if sharded_loss else P(), loss_params
    )
    mb_per_dev = M // n_stages
    D = 2 * n_stages  # saved-input ring depth: covers the 2(P-1)+1 window
    xm = x.reshape((M, B // M) + x.shape[1:])
    tm = targets.reshape((M, B // M) + targets.shape[1:])

    def per_stage(params_local, lp, xm_local, tm_local):
        params_local = jax.tree.map(lambda l: l[0], params_local)
        s = jax.lax.axis_index(axis)
        zero_mb = jnp.zeros_like(xm_local[0])
        last = n_stages - 1

        def _varying(v):
            # jax-version shim: no typeof/pcast (pre-vma jax) -> types
            # are never vma-annotated, pcast neither exists nor matters.
            typeof = getattr(jax, "typeof", None)
            if typeof is None or not hasattr(jax.lax, "pcast"):
                return v
            if axis in getattr(typeof(v), "vma", ()):
                return v
            return jax.lax.pcast(v, (axis,), to="varying")

        if sharded_loss:
            # Stage-chunked loss params: drop the leading slice axis like
            # stage params. Already pp-varying (sharded in_spec).
            lp = jax.tree.map(lambda l: l[0], lp)
        else:
            # CRITICAL: lp arrives pp-INVARIANT (replicated in_spec), and
            # jax.vjp inside a manual region inserts an automatic psum on
            # the cotangent of an invariant primal — which would sum every
            # stage's dlp (including the P-1 stages' garbage contributions)
            # BEFORE the at_last mask can drop them. pcast to varying so
            # the loss vjp stays stage-local; the masked accumulate +
            # final psum then count exactly the last stage's real
            # contributions.
            lp = jax.tree.map(_varying, lp)

        if backward == "stored":
            # Trace two throwaway vjps (different microbatch widths) to
            # learn the residual pytree's treedef and which leaves are
            # input-shape-dependent (must ride the ring) versus
            # input-independent (weights/tables — taken fresh each tick).
            # Their outputs feed nothing but zeros_like, so XLA DCEs the
            # phantom forwards.
            _, _vjp0 = jax.vjp(fn, params_local, _varying(zero_mb))
            _, _vjp2 = jax.vjp(
                fn,
                params_local,
                _varying(
                    jnp.zeros(
                        (2 * zero_mb.shape[0],) + zero_mb.shape[1:],
                        zero_mb.dtype,
                    )
                ),
            )
            res_leaves0 = jax.tree.leaves(_vjp0)
            res_leaves2 = jax.tree.leaves(_vjp2)
            if len(res_leaves0) != len(res_leaves2):
                raise ValueError(
                    "backward='stored': fn's vjp residual structure "
                    "depends on the microbatch size — use 'recompute'"
                )
            ring_stored = tuple(
                a.shape != b.shape
                for a, b in zip(res_leaves0, res_leaves2)
            )

        def tick(carry, t):
            act_in, cot_in, bufs, dp_acc, dlp_acc, loss_acc, dx_local = carry

            # ---- forward half (the GPipe wavefront) ----
            t_in = jnp.clip(t, 0, M - 1)
            feed = jnp.where(
                s == t_in // mb_per_dev,
                jax.lax.dynamic_index_in_dim(
                    xm_local, t_in % mb_per_dev, 0, keepdims=False
                ),
                zero_mb,
            )
            mb = jax.lax.psum(feed, axis)
            inp = jnp.where(s == 0, mb, act_in)
            jf = t - s  # the microbatch this stage forwards this tick
            f_valid = (jf >= 0) & (jf < M)
            # Ring slot jf mod D; the slot is free again after 2P ticks >
            # the in-flight window.
            slot_f = jnp.clip(jf, 0, M - 1) % D
            if backward == "stored":
                # ONE forward produces the wavefront output AND the
                # backward residuals (jax.vjp's function IS a pytree);
                # shape-varying residual leaves ride the ring.
                y, f_vjp = jax.vjp(fn, params_local, inp)
                # The treedef embeds backward jaxprs (identity-compared),
                # so canary-vs-live treedefs never compare equal; leaf
                # ORDER is what must line up, and tracing the same fn at
                # the same avals is deterministic. Guard on the leaf
                # shapes; unflatten with THIS tick's treedef.
                cur_leaves, vjp_treedef = jax.tree.flatten(f_vjp)
                if [l.shape for l in cur_leaves] != [
                    l.shape for l in res_leaves0
                ]:
                    raise ValueError(
                        "backward='stored': vjp residual shapes changed "
                        "between traces — use 'recompute'"
                    )
                # bufs holds only the stored leaves, in leaf order.
                new_bufs = []
                bi = 0
                for leaf, st in zip(cur_leaves, ring_stored):
                    if not st:
                        continue
                    buf = bufs[bi]
                    bi += 1
                    prev = jax.lax.dynamic_index_in_dim(
                        buf, slot_f, 0, keepdims=False
                    )
                    new_bufs.append(
                        jax.lax.dynamic_update_index_in_dim(
                            buf, jnp.where(f_valid, leaf, prev), slot_f, 0
                        )
                    )
                bufs = tuple(new_bufs)
            else:
                # Save the stage INPUT for the backward recompute — the
                # only per-microbatch state full-remat 1F1B keeps.
                (inbuf,) = bufs
                prev = jax.lax.dynamic_index_in_dim(
                    inbuf, slot_f, 0, keepdims=False
                )
                bufs = (
                    jax.lax.dynamic_update_index_in_dim(
                        inbuf, jnp.where(f_valid, inp, prev), slot_f, 0
                    ),
                )
                y = fn(params_local, inp)
            act_next = jax.lax.ppermute(
                y, axis, [(i, (i + 1) % n_stages) for i in range(n_stages)]
            )

            # ---- loss at the last stage (for the mb finishing there) ----
            jt = jnp.clip(t - last, 0, M - 1)
            tfeed = jnp.where(
                s == jt // mb_per_dev,
                jax.lax.dynamic_index_in_dim(
                    tm_local, jt % mb_per_dev, 0, keepdims=False
                ),
                jnp.zeros_like(tm_local[0]),
            )
            tgt = jax.lax.psum(tfeed, axis)
            if sharded_loss:
                # Vocab-parallel-style tail: broadcast the last stage's
                # finished microbatch to every stage (one masked O(mb)
                # psum) and run the CHUNKED loss on all stages — loss_fn
                # combines partials over ``axis`` internally. The mask is
                # tick-validity only (uniform across stages): every
                # stage's dlp chunk is real work, accumulated locally.
                def _lw(l, yy):
                    y_b = jax.lax.psum(
                        jnp.where(s == last, yy, jnp.zeros_like(yy)), axis
                    )
                    return loss_fn(l, y_b, tgt)

                lval, loss_vjp = jax.vjp(_lw, lp, y)
                dlp, dy = loss_vjp(jnp.ones_like(lval))
                tick_valid = (t - last >= 0) & (t - last < M)
                loss_acc = loss_acc + jnp.where(tick_valid, lval, 0.0)
                dlp_acc = jax.tree.map(
                    lambda a, g: a
                    + jnp.where(tick_valid, g, jnp.zeros_like(g)),
                    dlp_acc,
                    dlp,
                )
            else:
                lval, loss_vjp = jax.vjp(
                    lambda l, yy: loss_fn(l, yy, tgt), lp, y
                )
                dlp, dy = loss_vjp(jnp.ones_like(lval))
                at_last = (s == last) & (t - last >= 0) & (t - last < M)
                loss_acc = loss_acc + jnp.where(at_last, lval, 0.0)
                dlp_acc = jax.tree.map(
                    lambda a, g: a + jnp.where(at_last, g, jnp.zeros_like(g)),
                    dlp_acc,
                    dlp,
                )

            # ---- backward half (1F1B: starts while forwards still run) ----
            jb = t - 2 * last + s  # the microbatch this stage backwards
            b_valid = (jb >= 0) & (jb < M)
            cot = jnp.where(s == last, dy, cot_in)
            slot_b = jnp.clip(jb, 0, M - 1) % D
            if backward == "stored":
                # Rebuild mb jb's vjp from its ringed residuals; input-
                # independent leaves come from this tick's forward.
                merged = []
                bi = 0
                for leaf, st in zip(cur_leaves, ring_stored):
                    if st:
                        merged.append(
                            jax.lax.dynamic_index_in_dim(
                                bufs[bi], slot_b, 0, keepdims=False
                            )
                        )
                        bi += 1
                    else:
                        merged.append(leaf)
                stage_vjp = jax.tree.unflatten(vjp_treedef, merged)
                dparams, dx = stage_vjp(cot)
            else:
                saved = jax.lax.dynamic_index_in_dim(
                    bufs[0], slot_b, 0, keepdims=False
                )
                _, stage_vjp = jax.vjp(fn, params_local, saved)
                dparams, dx = stage_vjp(cot)
            dp_acc = jax.tree.map(
                lambda a, g: a + jnp.where(b_valid, g, jnp.zeros_like(g)),
                dp_acc,
                dparams,
            )
            # Stage 0 finishes the INPUT cotangent of mb j0 = t - 2(P-1)
            # this tick: ship it back to j0's owner (masked psum,
            # mirroring the forward feed) for the caller's embedding/
            # input grads. NB: the owner/slot must come from j0 (stage
            # 0's backward index), not this stage's jb.
            j0 = t - 2 * last
            j0_valid = (j0 >= 0) & (j0 < M)
            j0s = jnp.clip(j0, 0, M - 1)
            done_cot = jax.lax.psum(
                jnp.where((s == 0) & b_valid, dx, jnp.zeros_like(dx)), axis
            )
            write = j0_valid & (s == j0s // mb_per_dev)
            slot_o = j0s % mb_per_dev
            cur_o = jax.lax.dynamic_index_in_dim(
                dx_local, slot_o, 0, keepdims=False
            )
            dx_local = jax.lax.dynamic_update_index_in_dim(
                dx_local, jnp.where(write, done_cot, cur_o), slot_o, 0
            )
            cot_next = jax.lax.ppermute(
                dx, axis, [(i, (i - 1) % n_stages) for i in range(n_stages)]
            )
            return (
                act_next, cot_next, bufs, dp_acc, dlp_acc, loss_acc, dx_local
            ), None

        # Freshly-constructed zeros start axis-invariant, but every carry
        # leaf becomes pp-varying inside the tick (stage-index masks) —
        # pcast the whole init so the scan carry types are stable. Leaves
        # already varying (derived from sharded params/inputs) must pass
        # through untouched — pcast rejects varying->varying. Exception:
        # under sharded_loss the loss accumulator stays pp-INVARIANT
        # (loss_fn returns the collective-combined scalar and the
        # validity mask is uniform), so it must not be pcast.
        loss0 = jnp.zeros((), jnp.float32)
        if not sharded_loss:
            loss0 = _varying(loss0)
        if backward == "stored":
            rings0 = tuple(
                jnp.zeros((D,) + leaf.shape, leaf.dtype)
                for leaf, st in zip(res_leaves0, ring_stored)
                if st
            )
        else:
            rings0 = (jnp.zeros((D,) + zero_mb.shape, zero_mb.dtype),)
        act0, cot0, buf0, dp0, dlp0, dx0 = jax.tree.map(
            _varying,
            (
                zero_mb,
                zero_mb,
                rings0,
                jax.tree.map(jnp.zeros_like, params_local),
                jax.tree.map(jnp.zeros_like, lp),
                jnp.zeros_like(xm_local),
            ),
        )
        init = (act0, cot0, buf0, dp0, dlp0, loss0, dx0)
        (_, _, _, dp_acc, dlp_acc, loss_acc, dx_local), _ = jax.lax.scan(
            tick, init, jnp.arange(M + 2 * last)
        )
        dp_out = jax.tree.map(lambda a: a[None] / M, dp_acc)
        if sharded_loss:
            # Loss is already combined + invariant; dlp chunks stay
            # stage-local with the leading slice axis restored.
            loss_out = loss_acc / M
            dlp_out = jax.tree.map(lambda a: a[None] / M, dlp_acc)
        else:
            # Mean over microbatches; loss/dlp live only on the last
            # stage, psum replicates them (making the replicated
            # out_specs valid).
            loss_out = jax.lax.psum(loss_acc, axis) / M
            dlp_out = jax.tree.map(
                lambda a: jax.lax.psum(a, axis) / M, dlp_acc
            )
        return loss_out, dp_out, dlp_out, dx_local / M

    loss, d_stage, d_loss, dxm = shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(param_spec, loss_spec, P(axis), P(axis)),
        out_specs=(P(), param_spec, loss_spec, P(axis)),
        axis_names={axis},
    )(stage_params, loss_params, xm, tm)
    return loss, (d_stage, d_loss, dxm.reshape(x.shape))
