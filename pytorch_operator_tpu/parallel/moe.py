"""Expert-parallel Mixture-of-Experts MLP over the ``ep`` mesh axis.

Reference parity note: absent from the reference (SURVEY.md §2 parallelism
table) — beyond-parity, completing the mesh-axis vocabulary with an
executable ``ep`` path (dp/fsdp/tp/sp/pp are covered elsewhere).

TPU-first design: experts live sharded over ``ep`` (each device owns
``E / ep`` experts' FFN weights) inside one ``shard_map`` program. Routing
is the dense-dispatch formulation: every device runs its local experts
over the full token batch and scales each token's output by its gate
weight for that expert (zero for unrouted tokens), then a single ``psum``
over ``ep`` combines expert contributions. No gather/scatter of tokens,
no capacity factors, no dropped tokens — compute per device scales with
local expert count, and the only collective is one psum riding ICI.
(A capacity-based sparse dispatch trades exactness for FLOPs; this layer
prioritizes exactness and XLA-friendly static shapes.)
"""

from __future__ import annotations


def moe_mlp(
    params,
    x,
    *,
    mesh,
    top_k: int = 2,
    axis: str = "ep",
):
    """Top-k gated MoE feed-forward. x ``[N, D]`` → ``[N, D]``.

    ``params``::

        {"gate": [D, E],                      # router (replicated)
         "w_in": [E, D, F], "w_out": [E, F, D]}  # experts (sharded over ep)

    Gate probabilities are softmax over the top-k experts per token
    (standard renormalized top-k routing); expert FFN is gelu.
    """
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n_exp = params["w_in"].shape[0]
    ep = mesh.shape[axis]
    if n_exp % ep:
        raise ValueError(f"experts {n_exp} not divisible by ep={ep}")
    if not (1 <= top_k <= n_exp):
        raise ValueError(f"top_k={top_k} outside [1, {n_exp}]")

    # Router runs replicated (it is tiny); per-token weights for every
    # expert, zero for experts outside the token's top-k.
    logits = x.astype(jnp.float32) @ params["gate"].astype(jnp.float32)  # [N, E]
    top_vals, top_idx = jax.lax.top_k(logits, top_k)
    probs = jax.nn.softmax(top_vals, axis=-1)  # renormalized over the top-k
    gates = jnp.zeros_like(logits)
    gates = jnp.put_along_axis(gates, top_idx, probs, axis=-1, inplace=False)

    param_spec = {"gate": P(), "w_in": P(axis), "w_out": P(axis)}

    def per_shard(params_local, gates_local, x_local):
        # Local experts: [E/ep, D, F]; this shard's slice of the gate
        # matrix columns.
        e_local = params_local["w_in"].shape[0]
        shard = jax.lax.axis_index(axis)
        g = jax.lax.dynamic_slice_in_dim(
            gates_local, shard * e_local, e_local, axis=1
        )  # [N, E/ep]
        h = jnp.einsum("nd,edf->enf", x_local, params_local["w_in"])
        h = jax.nn.gelu(h)
        y = jnp.einsum("enf,efd->end", h, params_local["w_out"])
        out = jnp.einsum("end,ne->nd", y, g.astype(y.dtype))
        return jax.lax.psum(out, axis)

    return shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(param_spec, P(), P()),
        out_specs=P(),
    )(params, gates, x)
