"""Expert-parallel Mixture-of-Experts MLP over the ``ep`` mesh axis.

Reference parity note: absent from the reference (SURVEY.md §2 parallelism
table) — beyond-parity, completing the mesh-axis vocabulary with an
executable ``ep`` path (dp/fsdp/tp/sp/pp are covered elsewhere).

TPU-first design: experts live sharded over ``ep`` (each device owns
``E / ep`` experts' FFN weights) inside one ``shard_map`` program. Routing
is the dense-dispatch formulation: every device runs its local experts
over the full token batch and scales each token's output by its gate
weight for that expert (zero for unrouted tokens), then a single ``psum``
over ``ep`` combines expert contributions. No gather/scatter of tokens,
no capacity factors, no dropped tokens — compute per device scales with
local expert count, and the only collective is one psum riding ICI.
(A capacity-based sparse dispatch trades exactness for FLOPs; this layer
prioritizes exactness and XLA-friendly static shapes.)
"""

from __future__ import annotations


def _router_topk(params, x, top_k: int):
    """The ONE router: f32 logits, top-k, renormalized softmax. Both
    dispatch formulations consume this, so routing can never diverge
    between the dense path and the sparse path it is A/B'd against.
    Returns (logits [N, E], top_idx [N, K], probs [N, K])."""
    import jax
    import jax.numpy as jnp

    logits = x.astype(jnp.float32) @ params["gate"].astype(jnp.float32)
    top_vals, top_idx = jax.lax.top_k(logits, top_k)
    probs = jax.nn.softmax(top_vals, axis=-1)
    return logits, top_idx, probs


def _gates(params, x, top_k: int):
    """Per-token dense gate weights [N, E]: softmax over the top-k experts
    (renormalized top-k routing), zero elsewhere."""
    import jax.numpy as jnp

    logits, top_idx, probs = _router_topk(params, x, top_k)
    gates = jnp.zeros_like(logits)
    return jnp.put_along_axis(gates, top_idx, probs, axis=-1, inplace=False)


def load_balance_loss(params, x, top_k: int):
    """Switch-Transformer load-balancing auxiliary loss.

    ``E * Σ_e f_e · P_e`` where ``f_e`` is the fraction of (token,
    choice) routings landing on expert e and ``P_e`` the mean FULL-softmax
    router probability for e. Perfectly balanced routing scores 1.0; a
    router collapsed onto one expert scores ~E. Differentiable through
    ``P_e`` (the f_e term is a straight-through count), which is exactly
    the gradient that spreads the router out — without it, top-k training
    (especially capacity-factor sparse dispatch, which DROPS over-capacity
    tokens) collapses onto a few experts.
    """
    import jax
    import jax.numpy as jnp

    logits, top_idx, _ = _router_topk(params, x, top_k)
    E = logits.shape[-1]
    full_probs = jax.nn.softmax(logits, axis=-1)          # [N, E]
    counts = jax.nn.one_hot(top_idx, E, dtype=jnp.float32).sum(axis=(0, 1))
    f = counts / counts.sum()                             # routing fractions
    p = full_probs.mean(axis=0)                           # mean router prob
    return E * jnp.sum(jax.lax.stop_gradient(f) * p)


def _expert_ffn(w_in, w_out, gates, x):
    """Gated gelu FFN over an expert block: [E?, D, F] weights, [N, E?]
    gates → [N, D]. The shared compute of the sharded and dense paths."""
    import jax
    import jax.numpy as jnp

    h = jax.nn.gelu(jnp.einsum("nd,edf->enf", x, w_in))
    y = jnp.einsum("enf,efd->end", h, w_out)
    return jnp.einsum("end,ne->nd", y, gates.astype(y.dtype))


def moe_mlp_reference(params, x, *, top_k: int = 2):
    """Unsharded dense MoE — the single-device reference/fallback."""
    n_exp = params["w_in"].shape[0]
    if not (1 <= top_k <= n_exp):
        raise ValueError(f"top_k={top_k} outside [1, {n_exp}]")
    return _expert_ffn(
        params["w_in"], params["w_out"], _gates(params, x, top_k), x
    )


def _dispatch_tensors(params, x, top_k: int, capacity: int):
    """GShard-style dispatch/combine one-hots for one token group.

    Returns (dispatch [N, E, C] bool-ish, combine [N, E, C] f32): token n
    goes to slot (e, c) of its routed experts, in arrival order per
    expert; tokens beyond an expert's capacity C are DROPPED (their gate
    contribution vanishes — the capacity-factor tradeoff). Routing
    indices carry no gradient (standard); gate probabilities do.
    """
    import jax
    import jax.numpy as jnp

    logits, top_idx, probs = _router_topk(params, x, top_k)
    E = logits.shape[-1]

    counts = jnp.zeros((E,), jnp.int32)
    dispatch = jnp.zeros(logits.shape + (capacity,), jnp.float32)
    combine = jnp.zeros_like(dispatch)
    for k in range(top_k):
        onehot_k = jax.nn.one_hot(top_idx[:, k], E, dtype=jnp.int32)
        # Position of each token within its expert's arrival order.
        pos_in_e = jnp.cumsum(onehot_k, axis=0) - onehot_k + counts[None, :]
        pos_k = (pos_in_e * onehot_k).sum(-1)  # [N]
        counts = counts + onehot_k.sum(0)
        keep = pos_k < capacity
        slot = jax.nn.one_hot(pos_k, capacity, dtype=jnp.float32)
        mask = (
            onehot_k.astype(jnp.float32)[:, :, None]
            * slot[:, None, :]
            * keep.astype(jnp.float32)[:, None, None]
        )
        dispatch = dispatch + mask
        combine = combine + mask * probs[:, k][:, None, None]
    return dispatch, combine


def moe_mlp_sparse(
    params,
    x,
    *,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    group_size: int = 1024,
    mesh=None,
    axis: str = "ep",
):
    """Capacity-factor sparse MoE dispatch (GShard-style einsum form).

    Compute scales with ``top_k * capacity_factor`` instead of with the
    expert count: tokens are grouped (``group_size``), each group routes
    into per-expert capacity ``C = ceil(g * capacity_factor * top_k / E)``
    slots via one-hot dispatch matmuls, the expert FFN runs on the dense
    [groups, E, C, D] buffer, and a combine matmul scatters results back.
    Grouping keeps the dispatch matmul cost linear in N (it is quadratic
    in the group size); the actual group is the largest divisor of N not
    exceeding ``group_size``, so any token count the dense path accepts
    works here too. Tokens beyond an expert's per-group capacity are
    DROPPED — the standard capacity tradeoff; the dense-dispatch path
    (:func:`moe_mlp` / :func:`moe_mlp_reference`) stays the exact option.
    BASELINE.md records the measured chip A/B (dense 2.1x/2.8x/4.9x the
    top-k-FLOPs ideal at E=8/16/32; sparse 1.2-1.3x, flat in E): prefer
    sparse from E >= 16.

    With ``mesh``: experts shard over ``axis`` (ep) exactly like
    :func:`moe_mlp`; each device computes its local experts' capacity
    block and one psum combines contributions.
    """
    import jax
    import jax.numpy as jnp
    import math as _math

    n_exp, d_model, d_ff = params["w_in"].shape
    if not (1 <= top_k <= n_exp):
        raise ValueError(f"top_k={top_k} outside [1, {n_exp}]")
    N = x.shape[0]
    # Largest divisor of N within group_size: never reject a token count
    # the dense path accepts (a degenerate tiny group just means smaller
    # per-group capacity).
    g = next(d for d in range(min(group_size, N), 0, -1) if N % d == 0)
    capacity = _math.ceil(g * capacity_factor * top_k / n_exp)

    xg = x.reshape(N // g, g, d_model)
    dispatch, combine = jax.vmap(
        lambda xi: _dispatch_tensors(params, xi, top_k, capacity)
    )(xg)

    def ffn(w_in, w_out, dispatch_l, combine_l, xg_l):
        x_e = jnp.einsum("gnec,gnd->gecd", dispatch_l.astype(x.dtype), xg_l)
        h = jax.nn.gelu(jnp.einsum("gecd,edf->gecf", x_e, w_in))
        y = jnp.einsum("gecf,efd->gecd", h, w_out)
        out = jnp.einsum("gnec,gecd->gnd", combine_l.astype(y.dtype), y)
        return out.reshape(N, d_model)

    if mesh is None or axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        return ffn(params["w_in"], params["w_out"], dispatch, combine, xg)

    ep = mesh.shape[axis]
    if n_exp % ep:
        raise ValueError(f"experts {n_exp} not divisible by ep={ep}")
    from ..jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    def per_shard(weights, dispatch_g, combine_g, xg_g):
        w_in, w_out = weights["w_in"], weights["w_out"]
        e_local = w_in.shape[0]
        shard = jax.lax.axis_index(axis)
        d_l = jax.lax.dynamic_slice_in_dim(
            dispatch_g, shard * e_local, e_local, axis=2
        )
        c_l = jax.lax.dynamic_slice_in_dim(
            combine_g, shard * e_local, e_local, axis=2
        )
        return jax.lax.psum(ffn(w_in, w_out, d_l, c_l, xg_g), axis)

    return shard_map(
        per_shard,
        mesh=mesh,
        in_specs=({"w_in": P(axis), "w_out": P(axis)}, P(), P(), P()),
        out_specs=P(),
        axis_names={axis},
    )(
        {"w_in": params["w_in"], "w_out": params["w_out"]},
        dispatch,
        combine,
        xg,
    )


def moe_mlp(
    params,
    x,
    *,
    mesh,
    top_k: int = 2,
    axis: str = "ep",
):
    """Top-k gated MoE feed-forward. x ``[N, D]`` → ``[N, D]``.

    ``params``::

        {"gate": [D, E],                      # router (replicated)
         "w_in": [E, D, F], "w_out": [E, F, D]}  # experts (sharded over ep)

    Gate probabilities are softmax over the top-k experts per token
    (standard renormalized top-k routing); expert FFN is gelu.
    """
    import jax
    from ..jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    n_exp, d_model, d_ff = params["w_in"].shape
    ep = mesh.shape[axis]
    if n_exp % ep:
        raise ValueError(f"experts {n_exp} not divisible by ep={ep}")
    if not (1 <= top_k <= n_exp):
        raise ValueError(f"top_k={top_k} outside [1, {n_exp}]")

    def present(a):
        return a in mesh.axis_names and mesh.shape[a] > 1

    # Compose with the transformer's weight shardings instead of forcing
    # replication (which would silently all-gather the expert weights on
    # every call). Two different semantics for the two axis kinds:
    #  - tp shards the F (mlp) dim Megatron-style WITHIN each expert:
    #    gelu is elementwise over F, so w_in stays column-parallel, w_out
    #    row-parallel, and the output psum below also completes the F
    #    contraction — TP never gathers weights.
    #  - fsdp shards the D (embed) dim as STORAGE only (ZeRO-3): compute
    #    needs full D, so the weights are gathered just-in-time inside the
    #    shard_map (the standard ZeRO gather, explicit here).
    tp_ax = "tp" if present("tp") and d_ff % mesh.shape["tp"] == 0 else None
    fsdp_ax = (
        "fsdp" if present("fsdp") and d_model % mesh.shape["fsdp"] == 0 else None
    )

    # Router runs replicated (it is tiny).
    gates = _gates(params, x, top_k)
    weight_spec = {"w_in": P(axis, fsdp_ax, tp_ax), "w_out": P(axis, tp_ax, fsdp_ax)}
    # Composition with data parallelism: keep tokens sharded over present
    # batch axes (each (dp, ep) device computes its token rows × its local
    # experts) instead of replicating the batch into every ep shard.
    batch_axes = tuple(
        a for a in ("dp", "fsdp") if a in mesh.axis_names and mesh.shape[a] > 1
    )
    n_rows = 1
    for a in batch_axes:
        n_rows *= mesh.shape[a]
    if batch_axes and x.shape[0] % n_rows == 0:
        tok_spec = P(batch_axes)
    else:
        tok_spec = P()

    def per_shard(weights, gates_local, x_local):
        w_in, w_out = weights["w_in"], weights["w_out"]
        if fsdp_ax is not None:
            # ZeRO just-in-time gather of the embed-dim storage shards.
            w_in = jax.lax.all_gather(w_in, fsdp_ax, axis=1, tiled=True)
            w_out = jax.lax.all_gather(w_out, fsdp_ax, axis=2, tiled=True)
        # Local experts: [E/ep, D, F/tp]; this shard's slice of the gate
        # matrix columns.
        e_local = w_in.shape[0]
        shard = jax.lax.axis_index(axis)
        g = jax.lax.dynamic_slice_in_dim(
            gates_local, shard * e_local, e_local, axis=1
        )  # [N_local, E/ep]
        out = _expert_ffn(w_in, w_out, g, x_local)
        # One psum finishes BOTH reductions: expert contributions over ep
        # and (when tp is active) the F contraction over tp.
        return jax.lax.psum(out, (axis,) if tp_ax is None else (axis, tp_ax))

    return shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(weight_spec, tok_spec, tok_spec),
        out_specs=tok_spec,
    )({"w_in": params["w_in"], "w_out": params["w_out"]}, gates, x)
