"""Expert-parallel Mixture-of-Experts MLP over the ``ep`` mesh axis.

Reference parity note: absent from the reference (SURVEY.md §2 parallelism
table) — beyond-parity, completing the mesh-axis vocabulary with an
executable ``ep`` path (dp/fsdp/tp/sp/pp are covered elsewhere).

TPU-first design: experts live sharded over ``ep`` (each device owns
``E / ep`` experts' FFN weights) inside one ``shard_map`` program. Routing
is the dense-dispatch formulation: every device runs its local experts
over the full token batch and scales each token's output by its gate
weight for that expert (zero for unrouted tokens), then a single ``psum``
over ``ep`` combines expert contributions. No gather/scatter of tokens,
no capacity factors, no dropped tokens — compute per device scales with
local expert count, and the only collective is one psum riding ICI.
(A capacity-based sparse dispatch trades exactness for FLOPs; this layer
prioritizes exactness and XLA-friendly static shapes.)
"""

from __future__ import annotations


def _gates(params, x, top_k: int):
    """Per-token dense gate weights [N, E]: softmax over the top-k experts
    (renormalized top-k routing), zero elsewhere. Router math in f32."""
    import jax
    import jax.numpy as jnp

    logits = x.astype(jnp.float32) @ params["gate"].astype(jnp.float32)
    top_vals, top_idx = jax.lax.top_k(logits, top_k)
    probs = jax.nn.softmax(top_vals, axis=-1)
    gates = jnp.zeros_like(logits)
    return jnp.put_along_axis(gates, top_idx, probs, axis=-1, inplace=False)


def _expert_ffn(w_in, w_out, gates, x):
    """Gated gelu FFN over an expert block: [E?, D, F] weights, [N, E?]
    gates → [N, D]. The shared compute of the sharded and dense paths."""
    import jax
    import jax.numpy as jnp

    h = jax.nn.gelu(jnp.einsum("nd,edf->enf", x, w_in))
    y = jnp.einsum("enf,efd->end", h, w_out)
    return jnp.einsum("end,ne->nd", y, gates.astype(y.dtype))


def moe_mlp_reference(params, x, *, top_k: int = 2):
    """Unsharded dense MoE — the single-device reference/fallback."""
    n_exp = params["w_in"].shape[0]
    if not (1 <= top_k <= n_exp):
        raise ValueError(f"top_k={top_k} outside [1, {n_exp}]")
    return _expert_ffn(
        params["w_in"], params["w_out"], _gates(params, x, top_k), x
    )


def moe_mlp(
    params,
    x,
    *,
    mesh,
    top_k: int = 2,
    axis: str = "ep",
):
    """Top-k gated MoE feed-forward. x ``[N, D]`` → ``[N, D]``.

    ``params``::

        {"gate": [D, E],                      # router (replicated)
         "w_in": [E, D, F], "w_out": [E, F, D]}  # experts (sharded over ep)

    Gate probabilities are softmax over the top-k experts per token
    (standard renormalized top-k routing); expert FFN is gelu.
    """
    import jax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n_exp, d_model, d_ff = params["w_in"].shape
    ep = mesh.shape[axis]
    if n_exp % ep:
        raise ValueError(f"experts {n_exp} not divisible by ep={ep}")
    if not (1 <= top_k <= n_exp):
        raise ValueError(f"top_k={top_k} outside [1, {n_exp}]")

    def present(a):
        return a in mesh.axis_names and mesh.shape[a] > 1

    # Compose with the transformer's weight shardings instead of forcing
    # replication (which would silently all-gather the expert weights on
    # every call). Two different semantics for the two axis kinds:
    #  - tp shards the F (mlp) dim Megatron-style WITHIN each expert:
    #    gelu is elementwise over F, so w_in stays column-parallel, w_out
    #    row-parallel, and the output psum below also completes the F
    #    contraction — TP never gathers weights.
    #  - fsdp shards the D (embed) dim as STORAGE only (ZeRO-3): compute
    #    needs full D, so the weights are gathered just-in-time inside the
    #    shard_map (the standard ZeRO gather, explicit here).
    tp_ax = "tp" if present("tp") and d_ff % mesh.shape["tp"] == 0 else None
    fsdp_ax = (
        "fsdp" if present("fsdp") and d_model % mesh.shape["fsdp"] == 0 else None
    )

    # Router runs replicated (it is tiny).
    gates = _gates(params, x, top_k)
    weight_spec = {"w_in": P(axis, fsdp_ax, tp_ax), "w_out": P(axis, tp_ax, fsdp_ax)}
    # Composition with data parallelism: keep tokens sharded over present
    # batch axes (each (dp, ep) device computes its token rows × its local
    # experts) instead of replicating the batch into every ep shard.
    batch_axes = tuple(
        a for a in ("dp", "fsdp") if a in mesh.axis_names and mesh.shape[a] > 1
    )
    n_rows = 1
    for a in batch_axes:
        n_rows *= mesh.shape[a]
    if batch_axes and x.shape[0] % n_rows == 0:
        tok_spec = P(batch_axes)
    else:
        tok_spec = P()

    def per_shard(weights, gates_local, x_local):
        w_in, w_out = weights["w_in"], weights["w_out"]
        if fsdp_ax is not None:
            # ZeRO just-in-time gather of the embed-dim storage shards.
            w_in = jax.lax.all_gather(w_in, fsdp_ax, axis=1, tiled=True)
            w_out = jax.lax.all_gather(w_out, fsdp_ax, axis=2, tiled=True)
        # Local experts: [E/ep, D, F/tp]; this shard's slice of the gate
        # matrix columns.
        e_local = w_in.shape[0]
        shard = jax.lax.axis_index(axis)
        g = jax.lax.dynamic_slice_in_dim(
            gates_local, shard * e_local, e_local, axis=1
        )  # [N_local, E/ep]
        out = _expert_ffn(w_in, w_out, g, x_local)
        # One psum finishes BOTH reductions: expert contributions over ep
        # and (when tp is active) the F contraction over tp.
        return jax.lax.psum(out, (axis,) if tp_ax is None else (axis, tp_ax))

    return shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(weight_spec, tok_spec, tok_spec),
        out_specs=tok_spec,
    )({"w_in": params["w_in"], "w_out": params["w_out"]}, gates, x)
