"""Device meshes — the TPU-native topology layer.

Reference mapping: the reference has no mesh concept; its "topology" is the
flat RANK/WORLD_SIZE numbering injected for c10d DDP (SURVEY.md §2
"Parallelism strategies"). TPU-first, topology is a named
:class:`jax.sharding.Mesh` over which pjit/shard_map place computation and
XLA inserts collectives that ride ICI within a slice and DCN across slices.

Canonical axis names (the scaling-book vocabulary):

- ``dp``   — pure data parallel (replicated params, sharded batch)
- ``fsdp`` — data parallel with parameter/optimizer sharding (ZeRO-3)
- ``tp``   — tensor (model) parallel
- ``sp``   — sequence/context parallel (ring attention)
- ``pp``   — pipeline stages
- ``ep``   — expert parallel (MoE)

A mesh spec like ``{"fsdp": 4, "tp": 2}`` or the string ``"fsdp=4,tp=2"``
(with at most one ``-1`` wildcard) is resolved against the available device
count.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Sequence, Union

MESH_AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")
# tp innermost: tensor-parallel collectives are the most latency-sensitive,
# and innermost mesh dims map to physically-adjacent devices on TPU slices.


def split_hybrid_spec(spec: str) -> tuple[str, str]:
    """Split a string spec into ``(ici, dcn)`` halves: axes marked with the
    ``@dcn`` suffix (``"dp=2@dcn,fsdp=-1"``) go to the dcn half. This is THE
    grammar for hybrid specs; :func:`parse_mesh_spec` accepts the suffix too
    (stripping it), so validators can reuse one parser."""
    ici_parts, dcn_parts = [], []
    for part in spec.split(","):
        part = part.strip()
        if part.endswith("@dcn"):
            dcn_parts.append(part[: -len("@dcn")])
        elif part:
            ici_parts.append(part)
    return ",".join(ici_parts), ",".join(dcn_parts)


def parse_mesh_spec(spec: Union[str, Mapping[str, int]]) -> Dict[str, int]:
    """Parse ``"dp=2,tp=4"`` (or a mapping) into an ordered axis dict.
    ``@dcn`` suffixes are accepted and stripped — use
    :func:`split_hybrid_spec` to recover the ici/dcn split."""
    if isinstance(spec, str):
        out: Dict[str, int] = {}
        for part in spec.split(","):
            part = part.strip()
            if part.endswith("@dcn"):
                part = part[: -len("@dcn")]
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"mesh spec {spec!r}: expected axis=size, got {part!r}")
            name, _, size = part.partition("=")
            out[name.strip()] = int(size)
    else:
        out = dict(spec)
    for name, size in out.items():
        if name not in MESH_AXIS_ORDER:
            raise ValueError(
                f"unknown mesh axis {name!r} (valid: {', '.join(MESH_AXIS_ORDER)})"
            )
        if size != -1 and size < 1:
            raise ValueError(f"mesh axis {name}: size must be >= 1 or -1, got {size}")
    if sum(1 for s in out.values() if s == -1) > 1:
        raise ValueError("mesh spec may contain at most one -1 wildcard")
    return out


def resolve_axis_sizes(
    spec: Union[str, Mapping[str, int]], n_devices: int
) -> Dict[str, int]:
    """Resolve a mesh spec against a device count (fills the -1 wildcard,
    checks the product divides the device count exactly)."""
    axes = parse_mesh_spec(spec)
    if not axes:
        axes = {"dp": -1}
    known = 1
    wildcard = None
    for name, size in axes.items():
        if size == -1:
            wildcard = name
        else:
            known *= size
    if wildcard is not None:
        if n_devices % known != 0:
            raise ValueError(
                f"mesh spec {axes}: known axis product {known} does not divide "
                f"device count {n_devices}"
            )
        axes[wildcard] = n_devices // known
        known *= axes[wildcard]
    if known != n_devices:
        raise ValueError(
            f"mesh spec {axes}: axis product {known} != device count {n_devices}"
        )
    # Canonical order keeps collective locality sane (tp innermost).
    return {k: axes[k] for k in MESH_AXIS_ORDER if k in axes}


def make_mesh(
    spec: Union[str, Mapping[str, int], None] = None,
    devices: Optional[Sequence] = None,
):
    """Build a named Mesh from a spec (default: all devices on ``dp``).

    String specs may mark axes as inter-slice with an ``@dcn`` suffix —
    ``"dp=2@dcn,fsdp=-1,tp=2"`` builds the :func:`make_hybrid_mesh` layout
    (dp across slices over DCN, fsdp×tp on ICI within each slice). This is
    the syntax workloads accept via ``--mesh`` / ``TPUJOB_MESH``.
    """
    import jax

    if isinstance(spec, str) and "@dcn" in spec:
        ici_spec, dcn_spec = split_hybrid_spec(spec)
        return make_hybrid_mesh(ici=ici_spec, dcn=dcn_spec, devices=devices)
    if devices is None:
        devices = jax.devices()
    axes = resolve_axis_sizes(spec if spec is not None else {"dp": -1}, len(devices))
    import numpy as np

    from jax.sharding import Mesh

    dev_array = np.asarray(devices).reshape(tuple(axes.values()))
    return Mesh(dev_array, tuple(axes.keys()))


def mesh_from_env(default: str = "dp=-1"):
    """Build the mesh from ``TPUJOB_MESH`` (supervisor-injected or user-set)."""
    import os

    return make_mesh(os.environ.get("TPUJOB_MESH", default))


def make_hybrid_mesh(
    ici: Union[str, Mapping[str, int]],
    dcn: Union[str, Mapping[str, int]],
    devices: Optional[Sequence] = None,
):
    """Mesh spanning multiple slices: ``dcn`` axes cross the data-center
    network (between slices), ``ici`` axes stay on the intra-slice
    interconnect.

    The reference's analog is NCCL over the pod network for ALL traffic;
    TPU-first, the slow inter-slice hops must only carry the
    bandwidth-light collectives (data-parallel gradient reduction), while
    tp/sp/fsdp ride ICI. That's exactly what this layout encodes: dcn axes
    are OUTERMOST (consecutive devices share a slice), so e.g.
    ``make_hybrid_mesh(ici="fsdp=-1,tp=2", dcn="dp=2")`` gives per-slice
    fsdp×tp with gradient psums over dp crossing DCN once per step.

    Built on ``mesh_utils.create_hybrid_device_mesh`` when the devices
    expose slice topology (real multi-slice TPU); falls back to a plain
    reshape (CPU/test meshes, where locality is moot) — the axis semantics
    are identical either way.

    ``ici`` may use one ``-1`` wildcard, resolved against the per-slice
    device count; ``dcn`` sizes must be explicit (the number of slices is
    deployment config, not discoverable from a flat device list).
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    dcn_axes = parse_mesh_spec(dcn)
    if not dcn_axes:
        return make_mesh(ici, devices)
    if any(s == -1 for s in dcn_axes.values()):
        raise ValueError("dcn axes must have explicit sizes (no -1 wildcard)")
    n_slices = 1
    for s in dcn_axes.values():
        n_slices *= s
    if len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices do not split into {n_slices} slices"
        )
    per_slice = len(devices) // n_slices
    # An explicitly empty ici spec means "no intra-slice axes" (one device
    # per slice) — it must NOT fall into resolve_axis_sizes's dp=-1
    # default, which would mint a phantom dp axis (or a bogus overlap
    # error when dp is a dcn axis).
    if not parse_mesh_spec(ici):
        if per_slice != 1:
            raise ValueError(
                f"empty ici spec needs exactly 1 device per slice, "
                f"got {per_slice}"
            )
        ici_axes: Dict[str, int] = {}
    else:
        ici_axes = resolve_axis_sizes(ici, per_slice)
    if set(ici_axes) & set(dcn_axes):
        raise ValueError(
            f"axes {sorted(set(ici_axes) & set(dcn_axes))} appear in both "
            "ici and dcn specs"
        )

    axis_names = tuple(dcn_axes) + tuple(ici_axes)  # dcn outermost
    shape = tuple(dcn_axes.values()) + tuple(ici_axes.values())
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    is_tpu = getattr(devices[0], "platform", "") == "tpu"
    if None not in slice_ids and (len(slice_ids) > 1 or is_tpu):
        # Real TPU topology: the dcn spec must match the slice count
        # exactly — a mismatched reshape would silently put ici axes
        # across slice boundaries (fsdp/tp collectives riding DCN), and
        # a multi-slice dcn spec on a single-slice reservation would
        # fabricate a phantom dcn axis inside the slice. CPU/test
        # devices also report slice_index=0, but there the ids carry no
        # topology information — the platform check keeps the loud
        # error on hardware without breaking forced-CPU multi-host
        # worlds (the reshape below is correct for those).
        if len(slice_ids) != n_slices:
            raise ValueError(
                f"dcn spec {dcn_axes} wants {n_slices} slices but the "
                f"devices span {len(slice_ids)}"
            )
        from jax.experimental import mesh_utils

        # create_hybrid_device_mesh takes same-length per-axis shapes,
        # multiplied elementwise; an axis lives on one network, so the
        # other network's extent there is 1.
        dev_array = mesh_utils.create_hybrid_device_mesh(
            (1,) * len(dcn_axes) + tuple(ici_axes.values()),
            tuple(dcn_axes.values()) + (1,) * len(ici_axes),
            devices=devices,
        )
        return Mesh(dev_array.reshape(shape), axis_names)
    # Reshape fallback: virtual/test topologies, and multi-PROCESS worlds
    # whose devices all share one slice id (forced-CPU hosts report
    # slice_index=0 — slice topology carries no information there).
    # jax.devices() is process-major, so dcn-outermost puts the dcn axes
    # across hosts, which is the hybrid layout's intent. Genuinely
    # multi-slice device sets never reach here (matched specs take the
    # hybrid path above; mismatches raise).
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names)
