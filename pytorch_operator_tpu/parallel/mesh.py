"""Device meshes — the TPU-native topology layer.

Reference mapping: the reference has no mesh concept; its "topology" is the
flat RANK/WORLD_SIZE numbering injected for c10d DDP (SURVEY.md §2
"Parallelism strategies"). TPU-first, topology is a named
:class:`jax.sharding.Mesh` over which pjit/shard_map place computation and
XLA inserts collectives that ride ICI within a slice and DCN across slices.

Canonical axis names (the scaling-book vocabulary):

- ``dp``   — pure data parallel (replicated params, sharded batch)
- ``fsdp`` — data parallel with parameter/optimizer sharding (ZeRO-3)
- ``tp``   — tensor (model) parallel
- ``sp``   — sequence/context parallel (ring attention)
- ``pp``   — pipeline stages
- ``ep``   — expert parallel (MoE)

A mesh spec like ``{"fsdp": 4, "tp": 2}`` or the string ``"fsdp=4,tp=2"``
(with at most one ``-1`` wildcard) is resolved against the available device
count.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Sequence, Union

MESH_AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")
# tp innermost: tensor-parallel collectives are the most latency-sensitive,
# and innermost mesh dims map to physically-adjacent devices on TPU slices.


def parse_mesh_spec(spec: Union[str, Mapping[str, int]]) -> Dict[str, int]:
    """Parse ``"dp=2,tp=4"`` (or a mapping) into an ordered axis dict."""
    if isinstance(spec, str):
        out: Dict[str, int] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"mesh spec {spec!r}: expected axis=size, got {part!r}")
            name, _, size = part.partition("=")
            out[name.strip()] = int(size)
    else:
        out = dict(spec)
    for name, size in out.items():
        if name not in MESH_AXIS_ORDER:
            raise ValueError(
                f"unknown mesh axis {name!r} (valid: {', '.join(MESH_AXIS_ORDER)})"
            )
        if size != -1 and size < 1:
            raise ValueError(f"mesh axis {name}: size must be >= 1 or -1, got {size}")
    if sum(1 for s in out.values() if s == -1) > 1:
        raise ValueError("mesh spec may contain at most one -1 wildcard")
    return out


def resolve_axis_sizes(
    spec: Union[str, Mapping[str, int]], n_devices: int
) -> Dict[str, int]:
    """Resolve a mesh spec against a device count (fills the -1 wildcard,
    checks the product divides the device count exactly)."""
    axes = parse_mesh_spec(spec)
    if not axes:
        axes = {"dp": -1}
    known = 1
    wildcard = None
    for name, size in axes.items():
        if size == -1:
            wildcard = name
        else:
            known *= size
    if wildcard is not None:
        if n_devices % known != 0:
            raise ValueError(
                f"mesh spec {axes}: known axis product {known} does not divide "
                f"device count {n_devices}"
            )
        axes[wildcard] = n_devices // known
        known *= axes[wildcard]
    if known != n_devices:
        raise ValueError(
            f"mesh spec {axes}: axis product {known} != device count {n_devices}"
        )
    # Canonical order keeps collective locality sane (tp innermost).
    return {k: axes[k] for k in MESH_AXIS_ORDER if k in axes}


def make_mesh(
    spec: Union[str, Mapping[str, int], None] = None,
    devices: Optional[Sequence] = None,
):
    """Build a named Mesh from a spec (default: all devices on ``dp``)."""
    import jax

    if devices is None:
        devices = jax.devices()
    axes = resolve_axis_sizes(spec if spec is not None else {"dp": -1}, len(devices))
    import numpy as np

    from jax.sharding import Mesh

    dev_array = np.asarray(devices).reshape(tuple(axes.values()))
    return Mesh(dev_array, tuple(axes.keys()))


def mesh_from_env(default: str = "dp=-1"):
    """Build the mesh from ``TPUJOB_MESH`` (supervisor-injected or user-set)."""
    import os

    return make_mesh(os.environ.get("TPUJOB_MESH", default))
