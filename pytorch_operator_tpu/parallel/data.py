"""Per-process data feeding for data-parallel training.

Reference mapping: DDP's per-rank DataLoader + DistributedSampler (inside the
reference's example containers) → per-process host data assembled into
*global* jax Arrays sharded over the ``dp`` mesh axis; XLA then sees one
logical batch (SPMD), which is the TPU-native shape of input pipelines.
"""

from __future__ import annotations

from typing import Sequence


def global_batch(batch, mesh, axis: str = "dp"):
    """Turn a host batch (every process holds identical data) into a global
    Array sharded along ``axis`` over the mesh.

    Single-process: a plain sharded device_put. Multi-process: each process
    contributes the rows its addressable devices own via
    ``jax.make_array_from_process_local_data``.
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    batch = np.asarray(batch)
    ndim = batch.ndim
    spec = PartitionSpec(axis, *([None] * (ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    n = batch.shape[0]
    pcount = jax.process_count()
    pid = jax.process_index()
    if n % pcount != 0:
        raise ValueError(
            f"global batch size {n} must divide evenly across {pcount} processes"
        )
    per = n // pcount
    local = batch[pid * per : (pid + 1) * per]
    return jax.make_array_from_process_local_data(sharding, local, batch.shape)


def put_global(batch, sharding):
    """Place a host array (identical on every process) onto an arbitrary
    global sharding — works single- and multi-process.

    ``jax.device_put`` alone cannot target shardings spanning other
    processes' devices; ``make_array_from_callback`` lets each process
    contribute exactly the shards its devices own, sliced from the full
    host copy.
    """
    import jax
    import numpy as np

    batch = np.asarray(batch)
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    return jax.make_array_from_callback(batch.shape, sharding, lambda idx: batch[idx])


def shard_batch_size(global_size: int, mesh, axis: str = "dp") -> int:
    """Validate a global batch size divides the dp extent; return per-device."""
    extent = mesh.shape[axis] if axis in mesh.axis_names else 1
    if global_size % extent != 0:
        raise ValueError(
            f"global batch {global_size} must be divisible by {axis}={extent}"
        )
    return global_size // extent


def epoch_batches(x, y, batch_size: int, *, seed: int, drop_last: bool = True):
    """Deterministic shuffled minibatches — same permutation on every process
    (all processes hold the same host dataset and the same seed)."""
    import numpy as np

    n = x.shape[0]
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    end = (n // batch_size) * batch_size if drop_last else n
    for i in range(0, end, batch_size):
        idx = perm[i : i + batch_size]
        yield x[idx], y[idx]
