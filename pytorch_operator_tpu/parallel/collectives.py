"""Collective wrappers for use inside ``shard_map`` — the XLA-over-ICI/DCN
replacement for the NCCL/Gloo layer the reference delegated to user
containers (SURVEY.md §5 "Distributed communication backend").

These are thin, named wrappers so workloads read like the topology they
implement (ring_shift for ring attention, reduce-scatter for ZeRO grads...).
"""

from __future__ import annotations

from typing import Any


def psum(x: Any, axis: str):
    import jax

    return jax.lax.psum(x, axis)


def pmean(x: Any, axis: str):
    import jax

    return jax.lax.pmean(x, axis)


def all_gather(x: Any, axis: str, *, tiled: bool = True):
    import jax

    return jax.lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x: Any, axis: str, *, scatter_dimension: int = 0):
    import jax

    return jax.lax.psum_scatter(
        x, axis, scatter_dimension=scatter_dimension, tiled=True
    )


def ring_shift(x: Any, axis: str, *, shift: int = 1):
    """Cyclic shift along a mesh axis via ppermute — the building block of
    ring attention and the smoke-dist ring canary. shift=+1 sends each
    shard to the next rank (rank i's output = rank i-1's input)."""
    import jax

    from ..jaxcompat import axis_size as _axis_size

    n = _axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)


def axis_index(axis: str):
    import jax

    return jax.lax.axis_index(axis)


def axis_size(axis: str):
    from ..jaxcompat import axis_size as _axis_size

    return _axis_size(axis)
