"""Depth autotuning for the device feed: size the lookahead to the
measured stall, not a guess.

PR 3's ``DevicePrefetcher`` shipped with a static ``depth=2`` — right
for a producer that is uniformly faster than the step, wrong the moment
the producer is BURSTY (a shared filesystem hiccup, a decode spike, a
noisy-neighbor host): a two-slot buffer drains in two steps and every
burst lands on the step loop as a feed stall, even though the producer's
AVERAGE rate keeps up. The fix is not "depth=16 everywhere" (each slot
pins a batch of device memory); it is a controller that grows the depth
when the step loop is measurably stalling and gives the memory back when
the feed has sustained headroom.

:class:`FeedAutotuner` mirrors the control discipline of the
supervisor's pool autoscaler (``controller/autoscale.py``), adapted to
the per-``get()`` cadence:

- **grow fast** — one observed stall at or above ``grow_stall_ms``
  doubles the depth (latency pain is paid per step; react in one
  observation);
- **shrink slow** — only after ``shrink_patience`` consecutive
  stall-free observations does the depth step DOWN by one (a burst gap
  must not thrash away the headroom the next burst needs);
- **bounded** — depth never leaves ``[floor, depth_max]``
  (``spec.data_plane.prefetch_depth_max`` is the device-memory budget
  the operator signed off on).

Pure decision logic — no threads, no clock, no jax — so the control law
is unit-testable; ``DevicePrefetcher`` feeds it the per-get stall and
applies the returned depth (``data/device_prefetch.py``).
"""

from __future__ import annotations

# One observed stall >= this fires a grow. 1 ms is real money on a
# multi-ms step and safely above timer noise on the queue hand-off.
DEFAULT_GROW_STALL_MS = 1.0
# Stall-free gets before ONE depth step down. At a 10 ms step this is
# ~0.3 s of sustained headroom per reclaimed slot.
DEFAULT_SHRINK_PATIENCE = 32


class FeedAutotuner:
    """Grow-fast / shrink-slow device-feed depth controller.

    ``observe(stall_ms)`` feeds one consumer-side measurement (the time
    the step loop waited in ``get()``) and returns the depth to use from
    now on. ``warmup`` initial observations are ignored entirely: the
    very first gets ALWAYS wait (the pipe is filling) and must not read
    as a stalling producer.
    """

    def __init__(
        self,
        depth_max: int,
        *,
        initial: int = 2,
        floor: int = 1,
        grow_stall_ms: float = DEFAULT_GROW_STALL_MS,
        shrink_patience: int = DEFAULT_SHRINK_PATIENCE,
        warmup: int = 4,
    ):
        self.floor = max(1, int(floor))
        self.depth_max = max(self.floor, int(depth_max))
        self.depth = min(max(int(initial), self.floor), self.depth_max)
        self.grow_stall_ms = float(grow_stall_ms)
        self.shrink_patience = max(1, int(shrink_patience))
        self.warmup = max(0, int(warmup))
        self._seen = 0
        self._quiet = 0  # consecutive stall-free observations
        self.grows = 0
        self.shrinks = 0

    def observe(self, stall_ms: float) -> int:
        """One consumer-side stall sample -> the depth to use next."""
        self._seen += 1
        if self._seen <= self.warmup:
            return self.depth
        if stall_ms >= self.grow_stall_ms:
            self._quiet = 0
            if self.depth < self.depth_max:
                # Double toward the cap: a stalling feed needs headroom
                # NOW, and a linear walk pays one burst per increment.
                self.depth = min(self.depth_max, self.depth * 2)
                self.grows += 1
        else:
            self._quiet += 1
            if self._quiet >= self.shrink_patience and self.depth > self.floor:
                # One slot at a time: reclaiming memory is never urgent,
                # and a halving here would surrender the buffer a bursty
                # producer refills only between bursts.
                self.depth -= 1
                self.shrinks += 1
                self._quiet = 0
        return self.depth
