"""Packed fixed-record array files (the native loader's on-disk format).

One file = N records; one record = the concatenated bytes of one example
across all fields (e.g. image then label). Fixed record size is what lets
the C++ loader mmap + random-gather without any per-record framing, and a
JSON sidecar (``<file>.meta.json``) carries shapes/dtypes so Python can
reconstruct typed arrays from raw slot bytes.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class FieldMeta:
    name: str
    shape: Tuple[int, ...]  # per-record shape (no leading N)
    dtype: str

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)) * np.dtype(self.dtype).itemsize


@dataclasses.dataclass
class ArrayFileMeta:
    n_records: int
    fields: List[FieldMeta]

    @property
    def record_bytes(self) -> int:
        return sum(f.nbytes for f in self.fields)

    def to_json(self) -> str:
        return json.dumps(
            {
                "n_records": self.n_records,
                "fields": [
                    {"name": f.name, "shape": list(f.shape), "dtype": f.dtype}
                    for f in self.fields
                ],
            }
        )

    @classmethod
    def from_json(cls, text: str) -> "ArrayFileMeta":
        d = json.loads(text)
        return cls(
            n_records=int(d["n_records"]),
            fields=[
                FieldMeta(f["name"], tuple(int(s) for s in f["shape"]), f["dtype"])
                for f in d["fields"]
            ],
        )


def meta_path(path) -> Path:
    return Path(str(path) + ".meta.json")


def field_range(path, meta: ArrayFileMeta, name: str, chunk_records: int = 8192):
    """(min, max) of a field across ALL records — one streaming memmap
    pass at file-read speed. Used to validate token ids up front: a
    per-batch check misses records outside the scanned batches, and BOTH
    out-of-range directions matter (negative ids clamp as silently in
    XLA embedding lookups as too-large ones).
    """
    off = 0
    fm = None
    for f in meta.fields:
        if f.name == name:
            fm = f
            break
        off += f.nbytes
    if fm is None:
        raise KeyError(f"field {name!r} not in {[f.name for f in meta.fields]}")
    R = meta.record_bytes
    data = np.memmap(path, np.uint8, mode="r")
    lo = hi = None
    for i in range(0, meta.n_records, chunk_records):
        j = min(i + chunk_records, meta.n_records)
        block = np.ascontiguousarray(
            data[i * R : j * R].reshape(j - i, R)[:, off : off + fm.nbytes]
        ).reshape(-1).view(fm.dtype)
        bl, bh = block.min(), block.max()
        lo = bl if lo is None else min(lo, bl)
        hi = bh if hi is None else max(hi, bh)
    return lo, hi


def field_max(path, meta: ArrayFileMeta, name: str, chunk_records: int = 8192):
    """Max value of a field (see :func:`field_range`)."""
    return field_range(path, meta, name, chunk_records)[1]


def pack_arrays(path, arrays: Dict[str, np.ndarray]) -> ArrayFileMeta:
    """Write per-example arrays (each shaped ``(N, ...)``) as one record file.

    Field order follows dict insertion order and is part of the format.
    """
    items = list(arrays.items())
    if not items:
        raise ValueError("pack_arrays: no arrays given")
    n = items[0][1].shape[0]
    for name, a in items:
        if a.shape[0] != n:
            raise ValueError(
                f"pack_arrays: field {name!r} has {a.shape[0]} records, expected {n}"
            )
    meta = ArrayFileMeta(
        n_records=n,
        fields=[FieldMeta(name, tuple(a.shape[1:]), str(a.dtype)) for name, a in items],
    )
    path = Path(path)
    with open(path, "wb") as f:
        # Vectorized interleave in record chunks: per-record Python
        # writes cost minutes of interpreter overhead at corpus scale;
        # viewing each field as (N, nbytes) uint8 and concatenating along
        # the byte axis runs at memory bandwidth, chunked to bound the
        # transient buffer.
        CHUNK = 65536
        for i in range(0, n, CHUNK):
            j = min(i + CHUNK, n)
            parts = [
                np.ascontiguousarray(a[i:j]).reshape(j - i, -1).view(np.uint8)
                for _, a in items
            ]
            f.write(np.concatenate(parts, axis=1).tobytes())
    meta_path(path).write_text(meta.to_json())
    return meta


def read_meta(path) -> ArrayFileMeta:
    mp = meta_path(path)
    if not mp.exists():
        raise FileNotFoundError(f"no sidecar {mp} for array file {path}")
    return ArrayFileMeta.from_json(mp.read_text())


def split_batch(
    meta: ArrayFileMeta, raw: np.ndarray, batch: int
) -> Dict[str, np.ndarray]:
    """Split a record-interleaved ``(batch * record_bytes,)`` uint8 buffer
    into typed per-field arrays shaped ``(batch, *field.shape)``. Copies
    per field when records have more than one field (de-interleave)."""
    rb = meta.record_bytes
    recs = raw.reshape(batch, rb)
    out: Dict[str, np.ndarray] = {}
    off = 0
    for f in meta.fields:
        chunk = recs[:, off : off + f.nbytes]
        out[f.name] = np.ascontiguousarray(chunk).view(f.dtype).reshape((batch,) + f.shape)
        off += f.nbytes
    return out


def split_planar(
    meta: ArrayFileMeta, raw: np.ndarray, batch: int
) -> Dict[str, np.ndarray]:
    """Split a planar (field-blocked) slot buffer — the native loader's
    output layout — into typed per-field arrays. Pure zero-copy views, so
    the consumer thread does no byte shuffling at all."""
    out: Dict[str, np.ndarray] = {}
    off = 0
    for f in meta.fields:
        block = raw[off : off + batch * f.nbytes]
        out[f.name] = block.view(f.dtype).reshape((batch,) + f.shape)
        off += batch * f.nbytes
    return out
