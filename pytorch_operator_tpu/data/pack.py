"""Pack in-tree datasets into the native loader's array-file format.

Usage::

    python -m pytorch_operator_tpu.data.pack --out digits.bin --dataset digits
    python -m pytorch_operator_tpu.data.pack --out syn.bin --dataset synthetic \
        --n 4096 --height 32 --width 32 --classes 10

The output is ``<out>`` plus a ``<out>.meta.json`` sidecar; feed it to
workloads via ``--data-file`` (mnist) or :func:`open_loader` directly.
"""

from __future__ import annotations

import argparse
import sys

from .array_file import pack_arrays


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out", required=True)
    p.add_argument(
        "--dataset", choices=("digits", "synthetic", "text"), default="digits"
    )
    p.add_argument("--split", default="train", choices=("train", "test"))
    p.add_argument("--n", type=int, default=4096, help="synthetic: record count")
    p.add_argument("--height", type=int, default=32)
    p.add_argument("--width", type=int, default=32)
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--input", default=None,
        help="text: path to a UTF-8/byte file to pack as LM training data",
    )
    p.add_argument(
        "--seq-len", type=int, default=512,
        help="text: tokens per record (byte-level, vocab 256)",
    )
    args = p.parse_args(argv)

    if args.dataset == "digits":
        from ..workloads.datasets import digits

        x, y = digits(args.split)
        meta = pack_arrays(args.out, {"x": x, "y": y})
    elif args.dataset == "text":
        # Byte-level LM corpus: any file becomes int32 token records of
        # --seq-len bytes (vocab 256) — the real-data path for
        # llama_train --data-file with no external tokenizer.
        import numpy as np
        from pathlib import Path

        if not args.input:
            raise SystemExit("--dataset text needs --input FILE")
        data = Path(args.input).read_bytes()
        S = args.seq_len
        n = len(data) // S
        if n == 0:
            raise SystemExit(
                f"{args.input}: {len(data)} bytes < one record of {S}"
            )
        tokens = (
            np.frombuffer(data[: n * S], np.uint8).astype(np.int32).reshape(n, S)
        )
        meta = pack_arrays(args.out, {"tokens": tokens})
    else:
        from ..workloads.datasets import synthetic_images

        x, y = synthetic_images(
            args.n, args.height, args.width, args.classes, seed=args.seed
        )
        meta = pack_arrays(args.out, {"x": x, "y": y})
    print(
        f"packed {meta.n_records} records "
        f"({meta.record_bytes} B each) -> {args.out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
