"""Pack in-tree datasets into the native loader's array-file format.

Usage::

    python -m pytorch_operator_tpu.data.pack --out digits.bin --dataset digits
    python -m pytorch_operator_tpu.data.pack --out syn.bin --dataset synthetic \
        --n 4096 --height 32 --width 32 --classes 10

The output is ``<out>`` plus a ``<out>.meta.json`` sidecar; feed it to
workloads via ``--data-file`` (mnist) or :func:`open_loader` directly.
"""

from __future__ import annotations

import argparse
import sys

from .array_file import pack_arrays


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out", required=True)
    p.add_argument("--dataset", choices=("digits", "synthetic"), default="digits")
    p.add_argument("--split", default="train", choices=("train", "test"))
    p.add_argument("--n", type=int, default=4096, help="synthetic: record count")
    p.add_argument("--height", type=int, default=32)
    p.add_argument("--width", type=int, default=32)
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    if args.dataset == "digits":
        from ..workloads.datasets import digits

        x, y = digits(args.split)
    else:
        from ..workloads.datasets import synthetic_images

        x, y = synthetic_images(
            args.n, args.height, args.width, args.classes, seed=args.seed
        )
    meta = pack_arrays(args.out, {"x": x, "y": y})
    print(
        f"packed {meta.n_records} records "
        f"({meta.record_bytes} B each) -> {args.out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
