"""Pipelined device feed: sharded producer pool + autotuned lookahead.

The C++ loader (``native_loader``) overlaps the host-side gather with
training, but every workload still paid the host→device transfer INLINE
with the step: ``device_put`` of batch N sat between step N-1 and step N
on the critical path. "Exploring the limits of Concurrency in ML
Training on Google TPUs" (PAPERS.md) identifies exactly this
input-pipeline/step overlap as where pod-scale step time goes.

:class:`DevicePrefetcher` moves the transfer off the step thread with a
bounded device-resident lookahead; this revision pipelines the feed
itself:

- **Sharded gather** (``workers=N``): N producer threads. The raw
  ``produce()`` calls stay strictly serialized in ticket order (loaders
  hand out borrowed slots; batch order is a determinism contract), but
  the expensive tail of each batch — dtype casts, stacking copies, the
  ``device_put`` — runs CONCURRENTLY across workers, and a reorder
  buffer hands batches to the consumer in exact FIFO order. Inline vs
  pipelined trains to the identical loss (pinned in tests).
- **Dynamic depth** (``depth_max`` + ``autotune``): the lookahead bound
  is a live variable, not a constructor constant. With ``autotune=True``
  a :class:`~pytorch_operator_tpu.data.feed_autotune.FeedAutotuner`
  grows the depth (fast) on measured consumer stalls and shrinks it
  (slowly) after sustained headroom, never leaving
  ``[1, depth_max]`` — the ``spec.data_plane.prefetch_depth_max``
  device-memory budget. ``set_depth`` is also public for external
  controllers.
- **Rolling stall telemetry**: ``stats()`` reports
  ``feed_stall_ms_recent`` — the mean step-loop wait over the last
  :data:`STALL_WINDOW` gets — alongside the lifetime
  ``feed_stall_ms_avg``. The heartbeat carries the RECENT number (a
  stall burst must move the live ``feed_stall_dominance`` rule now, not
  after the lifetime average catches up); the cumulative field stays
  for dashboards that integrate over the run.

Two entry points, unchanged in contract:

- :class:`DevicePrefetcher` — generic: ``produce()`` returns a host
  batch (any pytree), ``put()`` maps it to device.
- :func:`prefetch_to_device` — the loader wrapper: drop-in for a
  ``NativeLoader``/``PyLoader`` (same ``next_batch()`` contract),
  COPYING the borrowed slot inside the serialized produce turn — the
  loader recycles the slot on its next ``next_batch``, so the copy must
  land before the next ticket's pull, workers or not.

``close()`` is prompt from EVERY side: a consumer blocked in ``get()``
is woken and raises ``RuntimeError("prefetcher is closed")`` instead of
hanging on a queue nobody will ever fill (the PR-3 implementation
parked such a consumer forever), and producer threads exit at their
next gate.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Optional

import numpy as np

from .. import obs
from .feed_autotune import FeedAutotuner

# Per-get samples in the rolling stall window. ~64 gets is a few
# heartbeat intervals at typical step times: recent enough that a burst
# dominates, wide enough that one noisy get does not.
STALL_WINDOW = 64


def _default_put(tree: Any) -> Any:
    import jax

    return jax.device_put(tree)


class DevicePrefetcher:
    """Pipelined background device feed over an arbitrary host-batch
    source.

    ``produce()`` runs serialized in FIFO ticket order on the producer
    pool (borrow-contract + determinism); ``put()`` runs concurrently
    across ``workers`` threads; ``get()`` (the step path) pops ready
    device batches in production order from a reorder buffer. At most
    ``depth`` batches are in flight ahead of the consumer — bounded
    device-memory lookahead and producer backpressure.

    A ``produce``/``put`` exception is re-raised from the consumer's
    ``get()`` at the failed batch's position, after every earlier batch
    has drained — errors are not swallowed, just deferred in order to
    the thread that can act on them.
    """

    def __init__(
        self,
        produce: Callable[[], Any],
        *,
        put: Optional[Callable[[Any], Any]] = None,
        depth: int = 2,
        depth_max: Optional[int] = None,
        workers: int = 1,
        autotune: bool = False,
        autotuner: Optional[FeedAutotuner] = None,
        name: str = "device-prefetch",
    ):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self.workers = int(workers)
        self.depth_max = max(depth, int(depth_max or depth))
        self._depth = depth
        if autotune and autotuner is None:
            autotuner = FeedAutotuner(self.depth_max, initial=depth)
        self._autotuner = autotuner
        self._produce = produce
        self._put = put or _default_put
        # Consumer-side state: reorder buffer + delivery cursor, guarded
        # by one condition that close()/producers notify.
        self._cv = threading.Condition()
        self._buf: dict = {}  # seq -> ready device batch
        self._ticket = 0  # next seq a producer will claim
        self._next_out = 0  # next seq the consumer receives
        self._stop = False
        self._err: Optional[BaseException] = None
        self._err_seq: Optional[int] = None
        # Producer-side serialization: produce() calls run in claimed
        # ticket order (the borrow/determinism contract), concurrency
        # starts at put().
        self._pcv = threading.Condition()
        self._produce_turn = 0
        # Flight-recorder accounting: feed-thread time (host gather +
        # device_put) vs step-thread wait — "is the feed keeping ahead"
        # is THE data-plane health question, surfaced as the feed-stall
        # column of `tpujob top` via the progress heartbeat.
        self._stats_lock = threading.Lock()
        self._stats = {
            "batches": 0, "produce_s": 0.0, "put_s": 0.0,
            "gets": 0, "get_wait_s": 0.0,
        }
        self._recent: deque = deque(maxlen=STALL_WINDOW)
        self._threads = [
            threading.Thread(
                target=self._fill, name=f"{name}-{i}", daemon=True
            )
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()

    # ---- depth control ----

    @property
    def depth(self) -> int:
        """Current lookahead bound (live under autotuning)."""
        return self._depth

    def set_depth(self, depth: int) -> None:
        """Retarget the lookahead, clamped to ``[1, depth_max]``. Takes
        effect at the producers' next gate; shrinking never drops
        already-produced batches."""
        depth = max(1, min(int(depth), self.depth_max))
        with self._cv:
            if depth != self._depth:
                self._depth = depth
                self._cv.notify_all()

    # ---- producer pool ----

    def _record_failure(self, seq: int, e: BaseException) -> None:
        with self._cv:
            if self._err_seq is None or seq < self._err_seq:
                self._err, self._err_seq = e, seq
            self._cv.notify_all()
        with self._pcv:
            self._pcv.notify_all()

    def _fill(self) -> None:
        while True:
            # Gate: claim a ticket only while fewer than `depth` batches
            # are in flight ahead of the consumer — exact backpressure,
            # re-checked when the depth itself moves.
            with self._cv:
                while (
                    not self._stop
                    and self._err is None
                    and (self._ticket - self._next_out) >= self._depth
                ):
                    self._cv.wait(0.2)
                if self._stop or self._err is not None:
                    return
                seq = self._ticket
                self._ticket += 1
            # Serialized produce in ticket order: the loader borrow
            # contract and batch-order determinism both require that
            # produce #seq runs before produce #seq+1, whichever worker
            # holds which ticket.
            with self._pcv:
                while (
                    self._produce_turn != seq
                    and not self._stop
                    and self._err is None
                ):
                    self._pcv.wait(0.2)
                if self._stop or self._err is not None:
                    return
                try:
                    t0 = time.perf_counter()
                    with obs.span("feed_produce", cat="data"):
                        batch = self._produce()
                    t1 = time.perf_counter()
                except BaseException as e:  # noqa: BLE001 — deliver to consumer
                    self._record_failure(seq, e)
                    return
                self._produce_turn += 1
                self._pcv.notify_all()
            # Concurrent tail: casts/copies inside `put` plus the device
            # transfer overlap across workers — the sharded gather.
            try:
                with obs.span("feed_put", cat="data"):
                    item = self._put(batch)
                t2 = time.perf_counter()
            except BaseException as e:  # noqa: BLE001 — deliver to consumer
                self._record_failure(seq, e)
                return
            with self._stats_lock:
                self._stats["batches"] += 1
                self._stats["produce_s"] += t1 - t0
                self._stats["put_s"] += t2 - t1
            with self._cv:
                self._buf[seq] = item
                self._cv.notify_all()

    # ---- consumer (step path) ----

    def get(self) -> Any:
        """Next device batch, in production order. Blocks only when the
        producer pool has fallen behind the step loop; raises promptly
        if the prefetcher is closed underneath a blocked consumer."""
        t0 = time.perf_counter()
        with self._cv:
            while True:
                if self._stop:
                    raise RuntimeError("prefetcher is closed")
                if self._next_out in self._buf:
                    item = self._buf.pop(self._next_out)
                    self._next_out += 1
                    self._cv.notify_all()
                    break
                if (
                    self._err is not None
                    and self._next_out >= (self._err_seq or 0)
                ):
                    # In-order error delivery: every batch produced
                    # before the failure drains first, then the failure
                    # surfaces (and keeps surfacing) at its position.
                    raise self._err
                self._cv.wait()
        waited = time.perf_counter() - t0
        with self._stats_lock:
            self._stats["gets"] += 1
            self._stats["get_wait_s"] += waited
            self._recent.append(waited)
        if waited > 1e-4:
            rec = obs.tracer()
            if rec is not None:
                rec.emit("feed_wait", "data", time.time() - waited, waited)
        if self._autotuner is not None:
            new = self._autotuner.observe(1000.0 * waited)
            if new != self._depth:
                self.set_depth(new)
        return item

    def stats(self) -> dict:
        """Cumulative feed accounting plus two derived step-loop stall
        meters: ``feed_stall_ms_avg`` (lifetime mean per get — kept for
        back-compat and whole-run dashboards) and ``feed_stall_ms_recent``
        (mean over the last :data:`STALL_WINDOW` gets — the heartbeat
        field, so a live stall burst moves the ``feed_stall_dominance``
        rule immediately instead of being diluted by hours of healthy
        history). ``depth`` is the live lookahead bound."""
        with self._stats_lock:
            s = dict(self._stats)
            recent = list(self._recent)
        s["feed_stall_ms_avg"] = 1000.0 * s["get_wait_s"] / max(s["gets"], 1)
        s["feed_stall_ms_recent"] = (
            1000.0 * sum(recent) / len(recent) if recent else 0.0
        )
        s["depth"] = self._depth
        s["workers"] = self.workers
        return s

    def close(self) -> None:
        """Stop the producer pool and drop buffered batches. Idempotent.
        A consumer blocked in ``get()`` is woken and raises
        ``RuntimeError`` promptly — never parked on a dead feed."""
        with self._cv:
            if self._stop:
                return
            self._stop = True
            self._buf.clear()
            self._cv.notify_all()
        # Best-effort producer wake: a worker stuck inside a blocking
        # produce() HOLDS the produce lock, and close must not inherit
        # its stall — gate waiters use timed waits and will observe
        # ``_stop`` on their own within 0.2 s either way.
        if self._pcv.acquire(timeout=0.2):
            try:
                self._pcv.notify_all()
            finally:
                self._pcv.release()
        for t in self._threads:
            t.join(timeout=1.0)

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class PrefetchedLoader:
    """Loader-contract facade over :class:`DevicePrefetcher` — see
    :func:`prefetch_to_device`."""

    def __init__(
        self,
        loader,
        depth: int = 2,
        *,
        put=None,
        depth_max: Optional[int] = None,
        workers: int = 1,
        autotune: bool = False,
    ):
        self.loader = loader

        def produce():
            epoch, index, fields = loader.next_batch()
            # COPY the borrowed slot inside the serialized produce turn,
            # before the next ticket's next_batch() recycles it (the
            # loader's borrow contract holds workers or not).
            return epoch, index, {
                k: np.array(v, copy=True) for k, v in fields.items()
            }

        apply_put = put or _default_put
        self._pf = DevicePrefetcher(
            produce,
            put=lambda item: (item[0], item[1], apply_put(item[2])),
            depth=depth,
            depth_max=depth_max,
            workers=workers,
            autotune=autotune,
        )

    @property
    def batches_per_epoch(self) -> int:
        return self.loader.batches_per_epoch

    def stats(self) -> dict:
        return self._pf.stats()

    def next_batch(self):
        """Same contract as the wrapped loader, but ``fields`` is the
        device-resident result of ``put`` — already transferred, owned
        by the caller (no borrow to respect)."""
        return self._pf.get()

    def close(self) -> None:
        self._pf.close()
        self.loader.close()

    def __enter__(self) -> "PrefetchedLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def prefetch_to_device(
    loader,
    depth: int = 2,
    *,
    put=None,
    depth_max: Optional[int] = None,
    workers: int = 1,
    autotune: bool = False,
) -> PrefetchedLoader:
    """Wrap a batch loader in a pipelined device feed.

    ``put(fields_dict) -> device_batch`` defaults to ``jax.device_put``
    of the whole dict; sharded workloads pass their ``put_global``
    closure. ``workers`` sizes the producer pool (transfers overlap;
    batch order is unchanged), ``depth_max``/``autotune`` enable the
    stall-driven depth controller (data/feed_autotune.py). The wrapper
    owns the loader: ``close()`` closes both.
    """
    return PrefetchedLoader(
        loader, depth, put=put, depth_max=depth_max, workers=workers,
        autotune=autotune,
    )
