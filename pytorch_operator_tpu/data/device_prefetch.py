"""Double-buffered device feed: the native loader's prefetch thread gets
an on-device counterpart.

The C++ loader (``native_loader``) overlaps the host-side gather with
training, but every workload still paid the host→device transfer INLINE
with the step: ``device_put`` of batch N sat between step N-1 and step N
on the critical path. "Exploring the limits of Concurrency in ML
Training on Google TPUs" (PAPERS.md) identifies exactly this
input-pipeline/step overlap as where pod-scale step time goes.

:class:`DevicePrefetcher` moves the transfer onto a background thread
with a bounded lookahead queue (``depth`` batches resident on device
ahead of the consumer — double-buffered at the default ``depth=2``):
while step N runs, the feed thread is already copying batch N+1 out of
the loader's borrowed slot and dispatching its ``device_put``. The step
path does ZERO transfers — it pops ready device arrays.

Two entry points:

- :class:`DevicePrefetcher` — generic: ``produce()`` returns a host
  batch (any pytree), ``put()`` maps it to device. Synthetic feeds and
  the chunk-stacking image feed use this directly.
- :func:`prefetch_to_device` — the loader wrapper: drop-in for a
  ``NativeLoader``/``PyLoader`` (same ``next_batch()`` contract,
  ``batches_per_epoch`` passthrough), COPYING the borrowed slot before
  it leaves the feed thread (the loader recycles the slot on its next
  ``next_batch`` — a zero-copy view handed across threads would read
  recycled memory).

Ordering is strictly FIFO — batch order is identical to the inline
feed, so determinism contracts (seeded shuffles, resume fast-forward)
are unaffected; a crash merely re-reads the up-to-``depth`` batches
that were prefetched but never consumed.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from .. import obs

_SENTINEL = object()


def _default_put(tree: Any) -> Any:
    import jax

    return jax.device_put(tree)


class DevicePrefetcher:
    """Background-thread device feed over an arbitrary host-batch source.

    ``produce()`` and ``put()`` both run on the feed thread; ``get()``
    (the step path) only pops ready device batches. The queue holds at
    most ``depth`` put batches — bounded device-memory lookahead, and
    backpressure on the producer when the consumer falls behind.

    A ``produce``/``put`` exception is re-raised from the consumer's
    next ``get()`` — errors are not swallowed, just deferred to the
    thread that can act on them.
    """

    def __init__(
        self,
        produce: Callable[[], Any],
        *,
        put: Optional[Callable[[Any], Any]] = None,
        depth: int = 2,
        name: str = "device-prefetch",
    ):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.depth = depth
        self._produce = produce
        self._put = put or _default_put
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._err: Optional[BaseException] = None
        # Flight-recorder accounting: feed-thread time (host gather +
        # device_put) vs step-thread wait — "is the feed keeping ahead"
        # is THE data-plane health question, surfaced as the feed-stall
        # column of `tpujob top` via the progress heartbeat.
        self._stats_lock = threading.Lock()
        self._stats = {
            "batches": 0, "produce_s": 0.0, "put_s": 0.0,
            "gets": 0, "get_wait_s": 0.0,
        }
        self._thread = threading.Thread(target=self._fill, name=name, daemon=True)
        self._thread.start()

    def _fill(self) -> None:
        while not self._stop.is_set():
            try:
                t0 = time.perf_counter()
                with obs.span("feed_produce", cat="data"):
                    batch = self._produce()
                t1 = time.perf_counter()
                with obs.span("feed_put", cat="data"):
                    item = self._put(batch)
                t2 = time.perf_counter()
                with self._stats_lock:
                    self._stats["batches"] += 1
                    self._stats["produce_s"] += t1 - t0
                    self._stats["put_s"] += t2 - t1
            except BaseException as e:  # noqa: BLE001 — deliver to consumer
                self._err = e
                item = _SENTINEL
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    break
                except queue.Full:
                    continue
            if item is _SENTINEL:
                return

    def get(self) -> Any:
        """Next device batch, in production order. Blocks only when the
        feed thread has fallen behind the step loop."""
        if self._stop.is_set():
            raise RuntimeError("prefetcher is closed")
        t0 = time.perf_counter()
        item = self._q.get()
        waited = time.perf_counter() - t0
        with self._stats_lock:
            self._stats["gets"] += 1
            self._stats["get_wait_s"] += waited
        if waited > 1e-4:
            rec = obs.tracer()
            if rec is not None:
                rec.emit("feed_wait", "data", time.time() - waited, waited)
        if item is _SENTINEL:
            raise self._err
        return item

    def stats(self) -> dict:
        """Cumulative feed accounting plus the derived mean step-loop
        stall per get (``feed_stall_ms_avg``) — the heartbeat field the
        supervisor folds into ``tpujob_job_feed_stall_ms``."""
        with self._stats_lock:
            s = dict(self._stats)
        s["feed_stall_ms_avg"] = 1000.0 * s["get_wait_s"] / max(s["gets"], 1)
        return s

    def close(self) -> None:
        """Stop the feed thread and drop queued batches. Idempotent."""
        if self._stop.is_set():
            return
        self._stop.set()
        # Unblock a producer stuck on a full queue.
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class PrefetchedLoader:
    """Loader-contract facade over :class:`DevicePrefetcher` — see
    :func:`prefetch_to_device`."""

    def __init__(self, loader, depth: int = 2, *, put=None):
        self.loader = loader

        def produce():
            epoch, index, fields = loader.next_batch()
            # COPY the borrowed slot on the feed thread, before the next
            # next_batch() recycles it (the loader's borrow contract).
            return epoch, index, {
                k: np.array(v, copy=True) for k, v in fields.items()
            }

        apply_put = put or _default_put
        self._pf = DevicePrefetcher(
            produce,
            put=lambda item: (item[0], item[1], apply_put(item[2])),
            depth=depth,
        )

    @property
    def batches_per_epoch(self) -> int:
        return self.loader.batches_per_epoch

    def stats(self) -> dict:
        return self._pf.stats()

    def next_batch(self):
        """Same contract as the wrapped loader, but ``fields`` is the
        device-resident result of ``put`` — already transferred, owned
        by the caller (no borrow to respect)."""
        return self._pf.get()

    def close(self) -> None:
        self._pf.close()
        self.loader.close()

    def __enter__(self) -> "PrefetchedLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def prefetch_to_device(loader, depth: int = 2, *, put=None) -> PrefetchedLoader:
    """Wrap a batch loader in a double-buffered device feed.

    ``put(fields_dict) -> device_batch`` defaults to ``jax.device_put``
    of the whole dict; sharded workloads pass their ``put_global``
    closure. The wrapper owns the loader: ``close()`` closes both.
    """
    return PrefetchedLoader(loader, depth, put=put)
