"""ctypes binding for the C++ prefetching loader (``native/loader.cc``),
with a pure-Python fallback.

Usage::

    with open_loader(path, batch=128, shuffle=True, seed=0) as ld:
        for step in range(steps):
            epoch, index, fields = ld.next_batch()   # dict of np arrays
            train_step(state, fields["x"], fields["y"])

``next_batch`` returns arrays that are OWNED BY THE LOADER only until the
next ``next_batch``/``close`` for the native path (the slot is released on
the next call); callers that stash batches must copy. jax.device_put /
jnp.asarray during the borrow is the intended consumption pattern.

The native library is auto-built with ``make -C native`` on first use (g++,
no deps — Environment: native toolchain is baked in; pybind11 is not, hence
ctypes). If the toolchain is missing, :func:`open_loader` silently falls
back to :class:`PyLoader`, which has identical semantics but does the gather
on the calling thread (and a different — equally deterministic — shuffle
order, as it uses numpy's RNG rather than splitmix64).
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path
from typing import Dict, Optional, Tuple

import numpy as np

from .array_file import ArrayFileMeta, read_meta, split_batch, split_planar

_REPO_ROOT = Path(__file__).resolve().parents[2]
_NATIVE_DIR = _REPO_ROOT / "native"
_LIB_PATH = _NATIVE_DIR / "libtpujob_loader.so"


class LoaderUnavailable(RuntimeError):
    """The NATIVE loader cannot run here (toolchain/library problem).
    open_loader treats this as 'fall back to PyLoader'."""


class LoaderDataError(ValueError):
    """The data file/parameters are invalid (short file, bad metadata,
    batch > records). NOT caught by open_loader's fallback: handing the
    same bad input to PyLoader would just crash later and more
    confusingly — both implementations raise this up front."""


_lib = None


def _load_lib() -> ctypes.CDLL:
    """Load (building if stale/missing) the native library. Raises
    LoaderUnavailable when it can't be built here."""
    global _lib
    if _lib is not None:
        return _lib
    src = _NATIVE_DIR / "loader.cc"
    if not src.exists():
        raise LoaderUnavailable(f"native source missing: {src}")
    if not _LIB_PATH.exists() or _LIB_PATH.stat().st_mtime < src.stat().st_mtime:
        # Serialize concurrent first-use builds (multi-process gangs all
        # hit this at once): without the lock, one rank can CDLL a
        # half-written .so while another's make is mid-link.
        import fcntl

        lock_path = _NATIVE_DIR / ".build.lock"
        try:
            with open(lock_path, "w") as lock_f:
                fcntl.flock(lock_f, fcntl.LOCK_EX)
                # Re-check under the lock: a peer may have built it.
                if (
                    not _LIB_PATH.exists()
                    or _LIB_PATH.stat().st_mtime < src.stat().st_mtime
                ):
                    subprocess.run(
                        ["make", "-C", str(_NATIVE_DIR)],
                        check=True,
                        capture_output=True,
                        text=True,
                    )
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            raise LoaderUnavailable(f"cannot build native loader: {detail}") from e
    lib = ctypes.CDLL(str(_LIB_PATH))
    lib.tpujob_loader_open.restype = ctypes.c_void_p
    lib.tpujob_loader_open.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64,
    ]
    lib.tpujob_loader_acquire.restype = ctypes.c_void_p
    lib.tpujob_loader_acquire.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.tpujob_loader_release.restype = None
    lib.tpujob_loader_release.argtypes = [ctypes.c_void_p]
    lib.tpujob_loader_batches_per_epoch.restype = ctypes.c_uint64
    lib.tpujob_loader_batches_per_epoch.argtypes = [ctypes.c_void_p]
    lib.tpujob_loader_close.restype = None
    lib.tpujob_loader_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class NativeLoader:
    """Background-prefetching batch loader over a packed array file."""

    def __init__(
        self,
        path,
        batch: int,
        shuffle: bool = True,
        seed: int = 0,
        prefetch: int = 4,
        meta: Optional[ArrayFileMeta] = None,
    ):
        self.meta = meta or read_meta(path)
        self.batch = batch
        lib = _load_lib()
        self._lib = lib
        field_sizes = (ctypes.c_uint64 * len(self.meta.fields))(
            *[f.nbytes for f in self.meta.fields]
        )
        self._handle = lib.tpujob_loader_open(
            str(path).encode(),
            self.meta.record_bytes,
            self.meta.n_records,
            batch,
            prefetch,
            seed,
            1 if shuffle else 0,
            field_sizes,
            len(self.meta.fields),
        )
        if not self._handle:
            # Data/parameter problem, not a toolchain one — must NOT be
            # swallowed by open_loader's PyLoader fallback.
            raise LoaderDataError(
                f"tpujob_loader_open failed for {path} "
                f"(record_bytes={self.meta.record_bytes}, "
                f"n_records={self.meta.n_records}, batch={batch} — is the file "
                f"at least record_bytes*n_records long and batch <= n_records?)"
            )
        self._borrowed = False

    @property
    def batches_per_epoch(self) -> int:
        return int(self._lib.tpujob_loader_batches_per_epoch(self._handle))

    def next_batch(self) -> Tuple[int, int, Dict[str, np.ndarray]]:
        """Blocks for the next prefetched batch; returns (epoch, index,
        {field: array}). Releases the previously borrowed slot first.

        BORROW CONTRACT: the returned arrays are zero-copy views into a
        prefetch ring slot owned by the C++ loader. They are valid ONLY
        until the next ``next_batch()`` or ``close()`` — consume them
        (device_put / compute) or ``np.array(..., copy=True)`` before
        either; a held view reads recycled memory afterwards."""
        if self._handle is None:
            raise RuntimeError("loader is closed")
        if self._borrowed:
            self._lib.tpujob_loader_release(self._handle)
            self._borrowed = False
        epoch = ctypes.c_uint64()
        index = ctypes.c_uint64()
        ptr = self._lib.tpujob_loader_acquire(
            self._handle, ctypes.byref(epoch), ctypes.byref(index)
        )
        if not ptr:
            raise RuntimeError("loader closed while waiting for a batch")
        self._borrowed = True
        nbytes = self.batch * self.meta.record_bytes
        raw = np.ctypeslib.as_array(
            ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)), shape=(nbytes,)
        )
        # The C++ gather wrote the slot planar (field-blocked), so the field
        # views below are zero-copy — no byte shuffling on this thread.
        return (
            int(epoch.value),
            int(index.value),
            split_planar(self.meta, raw, self.batch),
        )

    def close(self) -> None:
        if self._handle is not None:
            self._lib.tpujob_loader_close(self._handle)
            self._handle = None

    def __enter__(self) -> "NativeLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class PyLoader:
    """Same contract as NativeLoader, pure numpy (no prefetch thread)."""

    def __init__(
        self,
        path,
        batch: int,
        shuffle: bool = True,
        seed: int = 0,
        prefetch: int = 4,  # accepted for interface parity; unused
        meta: Optional[ArrayFileMeta] = None,
    ):
        self.meta = meta or read_meta(path)
        self.batch = batch
        self.shuffle = shuffle
        self.seed = seed
        rb = self.meta.record_bytes
        raw = np.memmap(path, dtype=np.uint8, mode="r")
        need = rb * self.meta.n_records
        if raw.size < need:
            # Same up-front contract as the native loader (which checks
            # file size against the metadata and refuses to open).
            raise LoaderDataError(
                f"{path}: {raw.size} bytes < record_bytes*n_records "
                f"({rb}*{self.meta.n_records}={need})"
            )
        # Slice BEFORE reshape: trailing bytes (file longer than the
        # metadata claims) are tolerated exactly like the native path.
        self._records = raw[:need].reshape(-1, rb)
        self._epoch = 0
        self._index = 0
        self._perm = self._make_perm()

    def _make_perm(self) -> np.ndarray:
        if not self.shuffle:
            return np.arange(self.meta.n_records)
        # SeedSequence-mixed (seed, epoch): additive seed+epoch made
        # adjacent seeds produce identical permutation streams shifted by
        # one epoch, undermining seed-based run independence.
        return np.random.default_rng((self.seed, self._epoch)).permutation(
            self.meta.n_records
        )

    @property
    def batches_per_epoch(self) -> int:
        return self.meta.n_records // self.batch

    def next_batch(self) -> Tuple[int, int, Dict[str, np.ndarray]]:
        if self._index >= self.batches_per_epoch:
            self._epoch += 1
            self._index = 0
            self._perm = self._make_perm()
        idx = self._perm[self._index * self.batch : (self._index + 1) * self.batch]
        raw = np.ascontiguousarray(self._records[idx]).reshape(-1)
        out = (self._epoch, self._index, split_batch(self.meta, raw, self.batch))
        self._index += 1
        return out

    def close(self) -> None:
        self._records = None

    def __enter__(self) -> "PyLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def open_loader(
    path,
    batch: int,
    shuffle: bool = True,
    seed: int = 0,
    prefetch: int = 4,
    native: Optional[bool] = None,
):
    """Open the best available loader. ``native=None`` tries the C++ loader
    and falls back to PyLoader; True/False force one implementation."""
    if native is False:
        return PyLoader(path, batch, shuffle=shuffle, seed=seed, prefetch=prefetch)
    try:
        return NativeLoader(path, batch, shuffle=shuffle, seed=seed, prefetch=prefetch)
    except LoaderUnavailable:
        if native is True:
            raise
        return PyLoader(path, batch, shuffle=shuffle, seed=seed, prefetch=prefetch)
