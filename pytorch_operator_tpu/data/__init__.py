"""File-backed dataset layer with a native prefetching loader.

Reference analog: the operator repo itself has no input pipeline — examples
lean on torch's DataLoader, whose prefetch workers are PyTorch's native C++
layer inside the user container (SURVEY.md §2, component-inventory preamble).
This package is the TPU-native equivalent: a packed record file format
(:mod:`array_file`), a C++ background-prefetch loader
(:mod:`native_loader`, ``native/loader.cc``) that keeps host-side gather off
the training loop's critical path, and a double-buffered device feed
(:mod:`device_prefetch`) that keeps the host→device transfer off it too —
``prefetch_to_device(loader, depth=2)`` overlaps ``device_put`` of batch
N+1 with step N on a background thread.
"""

from .array_file import ArrayFileMeta, field_max, field_range, pack_arrays, read_meta
from .device_prefetch import DevicePrefetcher, PrefetchedLoader, prefetch_to_device
from .native_loader import (
    LoaderDataError,
    LoaderUnavailable,
    NativeLoader,
    PyLoader,
    open_loader,
)


def open_training_loader(path, batch: int, *, seed: int = 0, processes: int = 1):
    """``open_loader`` with the gang-determinism guard every training
    workload needs: multi-process worlds PIN the native loader, because
    the pure-python fallback shuffles with a different RNG and divergent
    per-rank permutations would silently corrupt assembled global
    batches. (One shared helper so the rule can't drift per workload.)"""
    return open_loader(path, batch, seed=seed, native=True if processes > 1 else None)


__all__ = [
    "ArrayFileMeta",
    "DevicePrefetcher",
    "PrefetchedLoader",
    "field_max",
    "field_range",
    "pack_arrays",
    "prefetch_to_device",
    "read_meta",
    "LoaderDataError",
    "LoaderUnavailable",
    "NativeLoader",
    "PyLoader",
    "open_loader",
    "open_training_loader",
]
