"""File-backed dataset layer with a native prefetching loader.

Reference analog: the operator repo itself has no input pipeline — examples
lean on torch's DataLoader, whose prefetch workers are PyTorch's native C++
layer inside the user container (SURVEY.md §2, component-inventory preamble).
This package is the TPU-native equivalent: a packed record file format
(:mod:`array_file`) plus a C++ background-prefetch loader
(:mod:`native_loader`, ``native/loader.cc``) that keeps host-side gather off
the training loop's critical path.
"""

from .array_file import ArrayFileMeta, pack_arrays, read_meta
from .native_loader import LoaderUnavailable, NativeLoader, PyLoader, open_loader

__all__ = [
    "ArrayFileMeta",
    "pack_arrays",
    "read_meta",
    "LoaderUnavailable",
    "NativeLoader",
    "PyLoader",
    "open_loader",
]
