"""File-backed dataset layer with a native prefetching loader.

Reference analog: the operator repo itself has no input pipeline — examples
lean on torch's DataLoader, whose prefetch workers are PyTorch's native C++
layer inside the user container (SURVEY.md §2, component-inventory preamble).
This package is the TPU-native equivalent: a packed record file format
(:mod:`array_file`) plus a C++ background-prefetch loader
(:mod:`native_loader`, ``native/loader.cc``) that keeps host-side gather off
the training loop's critical path.
"""

from .array_file import ArrayFileMeta, field_max, field_range, pack_arrays, read_meta
from .native_loader import (
    LoaderDataError,
    LoaderUnavailable,
    NativeLoader,
    PyLoader,
    open_loader,
)


def open_training_loader(path, batch: int, *, seed: int = 0, processes: int = 1):
    """``open_loader`` with the gang-determinism guard every training
    workload needs: multi-process worlds PIN the native loader, because
    the pure-python fallback shuffles with a different RNG and divergent
    per-rank permutations would silently corrupt assembled global
    batches. (One shared helper so the rule can't drift per workload.)"""
    return open_loader(path, batch, seed=seed, native=True if processes > 1 else None)


__all__ = [
    "ArrayFileMeta",
    "field_max",
    "field_range",
    "pack_arrays",
    "read_meta",
    "LoaderDataError",
    "LoaderUnavailable",
    "NativeLoader",
    "PyLoader",
    "open_loader",
    "open_training_loader",
]
