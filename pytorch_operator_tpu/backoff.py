"""Jittered exponential backoff — THE retry schedule for transient
failures (rendezvous joins, checkpoint I/O, anything a fault plan can
make flake).

Why one shared helper: the rendezvous loop retried on a fixed 1 s
interval, which synchronizes every worker of a gang into a thundering
herd against the coordinator; checkpoint I/O had no retry at all. Both
now share this schedule: exponential growth, a cap, and DETERMINISTIC
jitter — derived by hashing (seed, attempt), never from a PRNG or the
clock — so a replayed fault plan (faults/) sleeps the identical
schedule both times while distinct seeds (e.g. per process id) still
decorrelate real workers.
"""

from __future__ import annotations

import hashlib
import math
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type


@dataclass(frozen=True)
class Backoff:
    """attempt (0-based) -> delay seconds: ``base * factor^attempt``,
    capped, then jittered by ±``jitter`` fraction deterministically."""

    base_s: float = 0.1
    cap_s: float = 30.0
    factor: float = 2.0
    jitter: float = 0.25
    seed: int = 0

    def delay(self, attempt: int) -> float:
        attempt = max(0, attempt)
        exp = attempt
        if self.factor > 1.0 and self.base_s > 0:
            # Clamp the exponent at the cap crossover: past it the
            # un-jittered delay is cap_s regardless, and an unbounded
            # attempt counter (an idle poll loop running for hours)
            # would overflow float pow. Jitter still hashes the REAL
            # attempt, so capped delays stay decorrelated.
            limit = math.log(
                max(self.cap_s, self.base_s) / self.base_s
            ) / math.log(self.factor)
            exp = min(exp, int(limit) + 1)
        d = min(self.cap_s, self.base_s * self.factor ** exp)
        if self.jitter:
            h = hashlib.blake2b(
                f"{self.seed}:{attempt}".encode(), digest_size=8
            ).digest()
            frac = int.from_bytes(h, "big") / 2**64  # [0, 1)
            d *= 1.0 + self.jitter * (2.0 * frac - 1.0)
        return max(0.0, d)

    def delays(self, attempts: int):
        return [self.delay(a) for a in range(attempts)]


def retry_call(
    fn: Callable,
    *,
    backoff: Backoff,
    attempts: Optional[int] = None,
    timeout_s: Optional[float] = None,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    on_retry: Optional[Callable[[BaseException, int], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
):
    """Call ``fn`` until it returns, retrying ``retry_on`` failures on
    the backoff schedule. Stops at ``attempts`` calls and/or when the
    next sleep would cross ``timeout_s`` (measured from the first call)
    — whichever comes first — then re-raises the last failure.

    ``on_retry(exc, attempt)`` runs before each sleep (cleanup hooks:
    e.g. removing a partially-written checkpoint step so the retry
    starts clean).
    """
    if attempts is None and timeout_s is None:
        raise ValueError("retry_call needs attempts and/or timeout_s")
    deadline = None if timeout_s is None else clock() + timeout_s
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            attempt += 1
            if attempts is not None and attempt >= attempts:
                raise
            d = backoff.delay(attempt - 1)
            if deadline is not None:
                remaining = deadline - clock()
                if remaining <= 0:
                    raise
                d = min(d, remaining)
            if on_retry is not None:
                on_retry(e, attempt)
            sleep(d)
