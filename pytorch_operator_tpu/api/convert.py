"""PyTorchJob → TPUJob conversion (migration shim).

The reference's users submit ``kind: PyTorchJob`` manifests
(``kubeflow.org/v1``, camelCase keys, ``pytorchReplicaSpecs`` holding pod
templates; reference: ``pkg/apis/pytorch/v1/types.go`` and
``examples/mnist`` job YAMLs — SURVEY.md §1 layer 7, §2 "PyTorchJob
types"). This module converts such a manifest into the TPUJob dict shape
so ``tpujob submit my-pytorchjob.yaml`` works directly: replica specs,
restart policies, run policy (including the v1beta2-era spec-level
placement of cleanPodPolicy/ttl), scheduling policy, and elastic policy
all map; the pod template's first container becomes the process template.

What cannot map is surfaced, not silently dropped: a container with no
``command`` is an error (there is no container runtime to run an image's
entrypoint); the image name / valueFrom env / priorityClassName, dropped
pod-level fields (nodeSelector, tolerations, volumes, initContainers,
affinity, ...), non-TPU resource requests, and sidecar commands are all
recorded as ``tpujob.dev/converted-*`` annotations for the operator to
see in ``tpujob describe``. (The reference's one operator-injected
initContainer — the wait-for-master DNS gate, SURVEY.md §2 "Pod
management" — needs no analog: coordinator connect-retry is built into
the rendezvous.)
"""

from __future__ import annotations

from typing import Any, Dict

CONVERTED_FROM_ANNOTATION = "tpujob.dev/converted-from"


def is_pytorchjob(data: Dict[str, Any]) -> bool:
    """Does this manifest look like a kubeflow PyTorchJob?"""
    if data.get("kind") == "PyTorchJob":
        return True
    spec = data.get("spec")
    return isinstance(spec, dict) and "pytorchReplicaSpecs" in spec


def convert_pytorchjob(data: Dict[str, Any]) -> Dict[str, Any]:
    """Convert a PyTorchJob manifest dict to a TPUJob dict.

    Raises ValueError (with the offending path) for constructs that cannot
    be represented, rather than guessing.
    """
    spec = data.get("spec") or {}
    annotations: Dict[str, str] = {}
    meta_in = data.get("metadata") or {}
    out_meta: Dict[str, Any] = {
        "name": meta_in.get("name", ""),
        "namespace": meta_in.get("namespace", "default"),
    }
    if meta_in.get("labels"):
        out_meta["labels"] = dict(meta_in["labels"])
    for k, v in (meta_in.get("annotations") or {}).items():
        annotations[str(k)] = str(v)
    annotations[CONVERTED_FROM_ANNOTATION] = (
        f"{data.get('apiVersion', 'kubeflow.org/v1')} PyTorchJob"
    )

    replica_specs_in = spec.get("pytorchReplicaSpecs") or {}
    if not isinstance(replica_specs_in, dict) or not replica_specs_in:
        raise ValueError("spec.pytorchReplicaSpecs: missing or empty")
    replica_specs: Dict[str, Any] = {}
    ports: Dict[str, int] = {}
    for rtype, rs in replica_specs_in.items():
        converted, rport = _convert_replica_spec(rtype, rs or {}, annotations)
        replica_specs[rtype] = converted
        if rport is not None:
            ports[rtype] = rport
    # MASTER_PORT comes from the Master container's pytorchjob-port in the
    # reference; a Worker's declaration must not override it.
    port = ports.get("Master", next(iter(ports.values()), None))

    # RunPolicy: v1 nests it under spec.runPolicy; v1beta2 had the same
    # fields at spec level. Accept both (runPolicy wins where both exist).
    rp_in = dict(spec.get("runPolicy") or {})
    for legacy_key in (
        "cleanPodPolicy",
        "ttlSecondsAfterFinished",
        "activeDeadlineSeconds",
        "backoffLimit",
        "schedulingPolicy",
    ):
        if legacy_key not in rp_in and legacy_key in spec:
            rp_in[legacy_key] = spec[legacy_key]
    run_policy: Dict[str, Any] = {}
    if rp_in.get("suspend"):
        # Real field (training-operator / Kueue): create-but-don't-run.
        run_policy["suspend"] = True
    if rp_in.get("schedulingPolicy", {}) and (
        rp_in["schedulingPolicy"].get("scheduleTimeoutSeconds") is not None
    ):
        annotations["tpujob.dev/converted-schedule-timeout-seconds"] = str(
            rp_in["schedulingPolicy"]["scheduleTimeoutSeconds"]
        )
    if rp_in.get("cleanPodPolicy") is not None:
        run_policy["clean_pod_policy"] = rp_in["cleanPodPolicy"]
    for camel, snake in (
        ("ttlSecondsAfterFinished", "ttl_seconds_after_finished"),
        ("activeDeadlineSeconds", "active_deadline_seconds"),
        ("backoffLimit", "backoff_limit"),
    ):
        if rp_in.get(camel) is not None:
            run_policy[snake] = rp_in[camel]
    sp_in = rp_in.get("schedulingPolicy") or {}
    if sp_in:
        sp_out: Dict[str, Any] = {}
        if sp_in.get("minAvailable") is not None:
            sp_out["min_available"] = sp_in["minAvailable"]
        if sp_in.get("queue"):
            sp_out["queue"] = sp_in["queue"]
        if sp_in.get("priorityClass"):
            # Priority classes are cluster objects we don't have; keep the
            # name visible and let the operator set a numeric priority.
            annotations["tpujob.dev/converted-priority-class"] = str(
                sp_in["priorityClass"]
            )
        if sp_out:
            run_policy["scheduling_policy"] = sp_out

    out_spec: Dict[str, Any] = {"replica_specs": replica_specs}
    if run_policy:
        out_spec["run_policy"] = run_policy
    if port is not None:
        out_spec["port"] = port

    ep_in = spec.get("elasticPolicy") or {}
    if ep_in:
        ep_out: Dict[str, Any] = {}
        for camel, snake in (
            ("minReplicas", "min_replicas"),
            ("maxReplicas", "max_replicas"),
            ("maxRestarts", "max_restarts"),
        ):
            if ep_in.get(camel) is not None:
                ep_out[snake] = ep_in[camel]
        if ep_in.get("nProcPerNode") is not None:
            annotations["tpujob.dev/converted-nproc-per-node"] = str(
                ep_in["nProcPerNode"]
            )
        if ep_out:
            out_spec["elastic_policy"] = ep_out

    out_meta["annotations"] = annotations
    return {
        "api_version": "tpujob.dev/v1",
        "kind": "TPUJob",
        "metadata": out_meta,
        "spec": out_spec,
    }


def _convert_replica_spec(rtype: str, rs: Dict[str, Any], annotations: Dict[str, str]):
    """One pytorchReplicaSpecs entry → (ReplicaSpec dict, port or None)."""
    path = f"spec.pytorchReplicaSpecs.{rtype}"
    out: Dict[str, Any] = {}
    if rs.get("replicas") is not None:
        out["replicas"] = rs["replicas"]
    if rs.get("restartPolicy") is not None:
        out["restart_policy"] = rs["restartPolicy"]

    pod = (rs.get("template") or {}).get("spec") or {}
    containers = pod.get("containers") or []
    if not containers:
        raise ValueError(f"{path}.template.spec.containers: missing or empty")
    c = containers[0]
    if len(containers) > 1:
        # Sidecars cannot run (no container runtime); keep name AND command
        # visible so the operator can reconstruct what the pod did.
        annotations[f"tpujob.dev/converted-sidecars-{rtype.lower()}"] = ";".join(
            "{}={}".format(
                x.get("name", "?"),
                " ".join(str(a) for a in (x.get("command") or [])) or "<image entrypoint>",
            )
            for x in containers[1:]
        )
    # Pod-level fields with no process analog (nodeSelector, tolerations,
    # volumes, affinity, ...): record them rather than silently dropping.
    dropped_pod = sorted(
        k for k, v in pod.items() if k != "containers" and v not in (None, [], {})
    )
    if "initContainers" in dropped_pod:
        # initContainers change execution semantics — call them out by name
        # in their own annotation (the canonical wait-for-master DNS gate is
        # subsumed by the rendezvous's built-in connect-retry).
        annotations[f"tpujob.dev/converted-init-containers-{rtype.lower()}"] = ",".join(
            str(x.get("name", "?")) for x in pod.get("initContainers") or []
        )
    if dropped_pod:
        annotations[f"tpujob.dev/converted-dropped-{rtype.lower()}"] = ",".join(
            dropped_pod
        )
    template: Dict[str, Any] = {}
    command = list(c.get("command") or [])
    if not command:
        raise ValueError(
            f"{path}: container {c.get('name', '?')!r} has no command — a "
            "container image's entrypoint cannot run without a container "
            "runtime; set an explicit command (e.g. ['python', '-m', ...])"
        )
    template["command"] = command
    if c.get("args"):
        template["args"] = [str(a) for a in c["args"]]
    if c.get("workingDir"):
        template["working_dir"] = c["workingDir"]
    if c.get("image"):
        annotations[f"tpujob.dev/converted-image-{rtype.lower()}"] = str(c["image"])
    env: Dict[str, str] = {}
    dropped = []
    for e in c.get("env") or []:
        if "valueFrom" in e:
            dropped.append(str(e.get("name", "?")))
            continue
        env[str(e["name"])] = str(e.get("value", ""))
    if dropped:
        annotations[f"tpujob.dev/converted-env-dropped-{rtype.lower()}"] = ",".join(
            dropped
        )
    if env:
        template["env"] = env

    # google.com/tpu resources → tpu_chips (the env's device ask). Limits
    # win; a requests-only ask (no limits block) still counts.
    limits = (c.get("resources") or {}).get("limits") or {}
    requests = (c.get("resources") or {}).get("requests") or {}
    TPU_KEYS = ("google.com/tpu", "cloud-tpus.google.com/v5e")
    tpu = next(
        (src[k] for src in (limits, requests) for k in TPU_KEYS if k in src),
        None,
    )
    if tpu is not None:
        template["resources"] = {"tpu_chips": int(tpu)}
    # Non-TPU resource asks (cpu, memory, nvidia.com/gpu, ...) have no
    # process-supervisor analog — surface what was dropped.
    non_tpu = sorted(
        k for k in set(limits) | set(requests) if k not in TPU_KEYS
    )
    if non_tpu:
        annotations[
            f"tpujob.dev/converted-resources-dropped-{rtype.lower()}"
        ] = ",".join(non_tpu)

    port = None
    for p in c.get("ports") or []:
        if p.get("name") == "pytorchjob-port" and p.get("containerPort"):
            port = int(p["containerPort"])
    out["template"] = template
    return out, port
