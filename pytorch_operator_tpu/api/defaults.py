"""Defaulting for TPUJob specs.

Reference: ``SetDefaults_PyTorchJob`` in ``pkg/apis/pytorch/v1/defaults.go``
(SURVEY.md §2 "Defaulting"): default port 23456, default replicas=1, default
restart policy, default cleanup policy.

Deviations, documented:

- The upstream default CleanPodPolicy is believed version-dependent
  (SURVEY.md tags it without a committed value). Locally, leaving worker
  *processes* running after job end leaks real PIDs on the host — unlike k8s
  pods there is no kubelet to reap them — so the default here is RUNNING
  (terminate still-running replicas when the job finishes). ``None`` remains
  selectable for parity.
- Default restart policy is ON_FAILURE (the sensible default for training
  replicas; upstream exact default is version-dependent).
"""

from __future__ import annotations

from .types import (
    DEFAULT_PORT,
    CleanPodPolicy,
    ReplicaType,
    RestartPolicy,
    TPUJob,
)

# Jobs that omitted spec.port carry this annotation: local supervisors
# re-probe a free coordinator port per world launch (all jobs share
# 127.0.0.1, unlike pods with distinct IPs). Set here — the one place every
# submission path funnels through — so CLI-queued and API-submitted jobs
# behave identically.
AUTO_PORT_ANNOTATION = "tpujob.dev/auto-port"

# Elastic jobs remember the worker count the user ASKED for: under capacity
# pressure the world launches smaller (down to min_replicas, torchelastic
# rendezvous-min semantics) and the reconciler grows it back toward this
# target as capacity frees. Manual `tpujob scale` re-pins it.
ELASTIC_TARGET_ANNOTATION = "tpujob.dev/elastic-target-workers"

# Opt-in hung-world detection: a job carrying this annotation (seconds,
# float) promises its workload heartbeats via rendezvous.report_progress;
# when the newest heartbeat (or, before any, the master's spawn) is older
# than the deadline, the supervisor kills and restarts the world — the
# recovery for a wedged collective that exits nothing (a host dropping
# off ICI mid-allreduce hangs forever instead of crashing).
HANG_DEADLINE_ANNOTATION = "tpujob.dev/hang-deadline-seconds"

# Exactly-once remediation (controller/remediation.py): the LAST
# committed action record, snapshotted as JSON in the SAME lease-fenced
# store write that mutates the spec and bumps
# status.remediation_generation. The audit-log append is derived state:
# a supervisor that dies between commit and append leaves at most the
# newest record missing, and the adopter re-materialises it from this
# annotation instead of re-running the action.
LAST_REMEDIATION_ANNOTATION = "tpujob.dev/last-remediation"


def set_defaults(job: TPUJob) -> TPUJob:
    """Fill defaulted fields in place (idempotent); returns the job."""
    spec = job.spec

    if spec.port is None:
        job.metadata.annotations[AUTO_PORT_ANNOTATION] = "true"
        spec.port = DEFAULT_PORT

    for rs in spec.replica_specs.values():
        if rs.replicas is None:
            rs.replicas = 1
        if rs.restart_policy is None:
            rs.restart_policy = RestartPolicy.ON_FAILURE

    rp = spec.run_policy
    if rp.clean_pod_policy is None:
        rp.clean_pod_policy = CleanPodPolicy.RUNNING
    if spec.elastic_policy is not None:
        workers = spec.replica_specs.get(ReplicaType.WORKER)
        if workers is not None:
            job.metadata.annotations.setdefault(
                ELASTIC_TARGET_ANNOTATION, str(workers.replicas)
            )
        # Elastic gang floor: master + min_replicas may start (torchelastic
        # rendezvous min), not the full desired world.
        if rp.scheduling_policy.min_available is None:
            rp.scheduling_policy.min_available = min(
                spec.total_replicas(), 1 + spec.elastic_policy.min_replicas
            )
    if rp.scheduling_policy.min_available is None:
        rp.scheduling_policy.min_available = spec.total_replicas()

    if not job.metadata.namespace:
        job.metadata.namespace = "default"

    return job
