"""Validation for TPUJob specs.

Reference: ``ValidateV1PyTorchJobSpec`` (SURVEY.md §2 "Validation"): rejects a
spec without exactly one Master, validates containers/ports. Extended with
the local-process equivalents (template must name a runnable) and elastic
policy consistency.
"""

from __future__ import annotations

import re
from typing import List

from .types import ElasticPolicy, ReplicaType, TPUJob, TPUJobSpec

_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")  # DNS-1123 label
MAX_NAME_LEN = 63


class ValidationError(ValueError):
    """Raised when a TPUJob spec is invalid; carries all messages."""

    def __init__(self, errors: List[str]):
        self.errors = list(errors)
        super().__init__("; ".join(errors))


def validate_name(name: str, field: str = "metadata.name") -> List[str]:
    errs = []
    if not name:
        errs.append(f"{field}: must not be empty")
    elif len(name) > MAX_NAME_LEN:
        errs.append(f"{field}: must be at most {MAX_NAME_LEN} characters")
    elif not _NAME_RE.match(name):
        errs.append(
            f"{field}: must be a DNS-1123 label "
            "(lowercase alphanumeric and '-', start/end alphanumeric)"
        )
    return errs


def _validate_elastic(ep: ElasticPolicy, spec: TPUJobSpec) -> List[str]:
    errs = []
    if ep.min_replicas < 1:
        errs.append("elastic_policy.min_replicas: must be >= 1")
    if ep.max_replicas < ep.min_replicas:
        errs.append("elastic_policy.max_replicas: must be >= min_replicas")
    if ep.max_restarts < 0:
        errs.append("elastic_policy.max_restarts: must be >= 0")
    if ep.hot_spares < 0:
        errs.append("elastic_policy.hot_spares: must be >= 0")
    workers = spec.replica_specs.get(ReplicaType.WORKER)
    if workers is not None and workers.replicas is not None:
        if not (ep.min_replicas <= workers.replicas <= ep.max_replicas):
            errs.append(
                "elastic_policy: Worker replicas "
                f"({workers.replicas}) must lie within "
                f"[min_replicas={ep.min_replicas}, max_replicas={ep.max_replicas}]"
            )
    return errs


def validate_spec(spec: TPUJobSpec) -> List[str]:
    """Return a list of error strings (empty when valid)."""
    errs: List[str] = []

    if not spec.replica_specs:
        errs.append("spec.replica_specs: must define at least a Master replica")
        return errs

    master = spec.replica_specs.get(ReplicaType.MASTER)
    if master is None:
        errs.append("spec.replica_specs: must contain exactly one Master replica type")
    else:
        if master.replicas is not None and master.replicas != 1:
            errs.append(
                f"spec.replica_specs[Master].replicas: must be 1, got {master.replicas}"
            )

    for rtype, rs in spec.replica_specs.items():
        prefix = f"spec.replica_specs[{rtype.value}]"
        if rs.replicas is not None and rs.replicas < 0:
            errs.append(f"{prefix}.replicas: must be >= 0, got {rs.replicas}")
        t = rs.template
        has_cmd = t.command is not None and len(t.command) > 0
        has_mod = t.module is not None and len(t.module) > 0
        if not has_cmd and not has_mod:
            errs.append(f"{prefix}.template: must set either 'command' or 'module'")
        if has_cmd and has_mod:
            errs.append(f"{prefix}.template: 'command' and 'module' are mutually exclusive")
        if t.resources.tpu_chips < 0:
            errs.append(f"{prefix}.template.resources.tpu_chips: must be >= 0")
        if t.resources.cpu_devices < 0:
            errs.append(f"{prefix}.template.resources.cpu_devices: must be >= 0")

    if spec.port is not None and not (1 <= spec.port <= 65535):
        errs.append(f"spec.port: must be in [1, 65535], got {spec.port}")

    rp = spec.run_policy
    if rp.backoff_limit is not None and rp.backoff_limit < 0:
        errs.append("spec.run_policy.backoff_limit: must be >= 0")
    if rp.active_deadline_seconds is not None and rp.active_deadline_seconds <= 0:
        errs.append("spec.run_policy.active_deadline_seconds: must be > 0")
    if rp.ttl_seconds_after_finished is not None and rp.ttl_seconds_after_finished < 0:
        errs.append("spec.run_policy.ttl_seconds_after_finished: must be >= 0")
    if rp.scheduling_policy.min_available is not None:
        if rp.scheduling_policy.min_available < 0:
            errs.append("spec.run_policy.scheduling_policy.min_available: must be >= 0")
        # Effective total: unset replica counts default to 1, so this holds
        # for undefaulted specs too (a min_available that can never be met
        # would gang-hold the job forever).
        total = sum(
            rs.replicas if rs.replicas is not None else 1
            for rs in spec.replica_specs.values()
        )
        if rp.scheduling_policy.min_available > total:
            errs.append(
                "spec.run_policy.scheduling_policy.min_available: "
                f"({rp.scheduling_policy.min_available}) exceeds total replicas ({total})"
            )
    if rp.scheduling_policy.shard is not None and rp.scheduling_policy.shard < 0:
        errs.append(
            "spec.run_policy.scheduling_policy.shard: must be >= 0 "
            "(an explicit control-plane shard pin; taken modulo the "
            "state dir's shard count)"
        )

    if spec.elastic_policy is not None:
        errs.extend(_validate_elastic(spec.elastic_policy, spec))

    if spec.data_plane is not None:
        dp = spec.data_plane
        if dp.prefetch < 0:
            errs.append("spec.data_plane.prefetch: must be >= 0")
        if dp.prefetch_depth_max < 0:
            errs.append("spec.data_plane.prefetch_depth_max: must be >= 0")
        if dp.prefetch_workers < 0:
            errs.append("spec.data_plane.prefetch_workers: must be >= 0")
        if dp.prefetch_depth_max and dp.prefetch_depth_max < dp.prefetch:
            errs.append(
                "spec.data_plane.prefetch_depth_max: "
                f"({dp.prefetch_depth_max}) is below the initial prefetch "
                f"depth ({dp.prefetch}) — the cap would shrink the feed "
                "it is supposed to bound"
            )
        if dp.autotune and dp.prefetch <= 0:
            errs.append(
                "spec.data_plane.autotune: requires prefetch > 0 (there "
                "is no device feed to autotune with inline transfers)"
            )

    if spec.serving is not None:
        sv = spec.serving
        if sv.transport not in ("spool", "shmring"):
            errs.append(
                "spec.serving.transport: must be 'spool' or 'shmring' "
                f"(got {sv.transport!r})"
            )
        if sv.router_shards < 0:
            errs.append("spec.serving.router_shards: must be >= 0")
        if sv.router_shards > 64:
            errs.append(
                "spec.serving.router_shards: must be <= 64 (each shard "
                "is a live router thread)"
            )
        if sv.slo is not None:
            slo = sv.slo
            if slo.max_queue_depth < 0:
                errs.append("spec.serving.slo.max_queue_depth: must be >= 0")
            if slo.deadline_s < 0:
                errs.append("spec.serving.slo.deadline_s: must be >= 0")
            if slo.retry_limit < 0:
                errs.append("spec.serving.slo.retry_limit: must be >= 0")
            if slo.target and not 0.0 < slo.target < 1.0:
                errs.append(
                    "spec.serving.slo.target: must be in (0, 1) — an "
                    "availability fraction, e.g. 0.99 (0 = default)"
                )
            if slo.burn_window_s < 0:
                errs.append("spec.serving.slo.burn_window_s: must be >= 0")

    if spec.remediation is not None:
        rm = spec.remediation
        if rm.cooldown_s < 0:
            errs.append("spec.remediation.cooldown_s: must be >= 0")
        if rm.backoff < 1.0:
            errs.append(
                "spec.remediation.backoff: must be >= 1.0 (a backoff "
                "below 1 would ACCELERATE repeated actions)"
            )
        if rm.max_actions < 0:
            errs.append("spec.remediation.max_actions: must be >= 0")
        if rm.scale_min < 1:
            errs.append("spec.remediation.scale_min: must be >= 1")
        if rm.scale_max < rm.scale_min:
            errs.append(
                "spec.remediation.scale_max: must be >= scale_min "
                f"({rm.scale_max} < {rm.scale_min})"
            )
        if rm.idle_s < 0:
            errs.append("spec.remediation.idle_s: must be >= 0")
        # Unknown rule names are near-certainly typos — the route would
        # silently never fire (same stance as alert thresholds).
        from ..obs.rules import RULES

        rule_names = set(RULES)
        for i, rt in enumerate(rm.routes):
            at = f"spec.remediation.routes[{i}]"
            if not rt.rule:
                errs.append(f"{at}.rule: required")
            elif rt.rule not in rule_names:
                errs.append(
                    f"{at}.rule: unknown alert rule {rt.rule!r} "
                    f"(valid: {', '.join(sorted(rule_names))})"
                )
            if bool(rt.webhook) == bool(rt.exec):
                errs.append(
                    f"{at}: exactly one of webhook or exec is required"
                )

    if spec.observability is not None:
        ob = spec.observability
        if ob.trace_ring_bytes < 0:
            errs.append("spec.observability.trace_ring_bytes: must be >= 0")
        if ob.trace_flush_every < 0:
            errs.append("spec.observability.trace_flush_every: must be >= 0")
        if ob.alerts is not None:
            al = ob.alerts
            if al.for_s < 0:
                errs.append("spec.observability.alerts.for_s: must be >= 0")
            if al.clear_s < 0:
                errs.append("spec.observability.alerts.clear_s: must be >= 0")
            # Unknown threshold names are near-certainly typos — the
            # override would silently never apply (the live watch and
            # `tpujob why` both ignore unknown keys at read time).
            from ..obs.rules import THRESHOLD_FIELDS

            for k, v in sorted(al.thresholds.items()):
                if k not in THRESHOLD_FIELDS:
                    errs.append(
                        f"spec.observability.alerts.thresholds[{k}]: "
                        f"unknown rule threshold (valid: "
                        f"{', '.join(sorted(THRESHOLD_FIELDS))})"
                    )
                elif v <= 0:
                    errs.append(
                        f"spec.observability.alerts.thresholds[{k}]: "
                        "must be > 0"
                    )

    return errs


def validate(job: TPUJob) -> None:
    """Raise ValidationError if the job is invalid.

    The namespace is held to DNS-1123 as well: both name and namespace are
    embedded in state filenames (``<ns>_<name>.json``) whose decoding relies
    on neither containing an underscore.
    """
    errs = validate_name(job.metadata.name)
    if job.metadata.namespace:
        errs.extend(validate_name(job.metadata.namespace, field="metadata.namespace"))
    errs.extend(validate_spec(job.spec))
    if errs:
        raise ValidationError(errs)
