"""TPUJob API: types, defaulting, validation, serialization.

Mirror of the reference's ``pkg/apis/pytorch/v1/`` (SURVEY.md §1 layer 1).
"""

from .types import (  # noqa: F401
    API_VERSION,
    DEFAULT_PORT,
    DEFAULT_PORT_NAME,
    KIND,
    RETRYABLE_EXIT_CODE_MIN,
    TERMINAL_CONDITIONS,
    AlertPolicy,
    CleanPodPolicy,
    ConditionType,
    ElasticPolicy,
    JobCondition,
    ObjectMeta,
    ObservabilityPolicy,
    ProcessTemplate,
    RemediationPolicy,
    RemediationRoute,
    ReplicaPhase,
    ReplicaSpec,
    ReplicaStatus,
    ReplicaType,
    Resources,
    RestartPolicy,
    RunPolicy,
    SchedulingPolicy,
    TPUJob,
    TPUJobSpec,
    TPUJobStatus,
)
from .defaults import set_defaults  # noqa: F401
from .validation import ValidationError, validate, validate_spec  # noqa: F401
from .serialization import (  # noqa: F401
    dump_job,
    dump_job_json,
    job_from_dict,
    load_job,
    loads_job,
    save_job,
)
from .convert import (  # noqa: F401
    convert_pytorchjob,
    is_pytorchjob,
)
