"""TPUJob API types.

TPU-native re-design of the reference's CRD types (reference:
``pkg/apis/pytorch/v1/types.go`` plus the shared ``ReplicaSpec``/``RunPolicy``/
``JobCondition`` types vendored from ``kubeflow/common``; see SURVEY.md §2
rows 1–4). Where the reference describes Kubernetes pods, this API describes
local worker *processes* that rendezvous via ``jax.distributed`` and compute
with XLA collectives over ICI/DCN (BASELINE.json:5).

Design notes (TPU-first, not a translation):

- There is no apimachinery; these are plain dataclasses with explicit
  ``to_dict``/``from_dict`` used by the YAML layer (serialization.py).
- A "pod template" becomes a :class:`ProcessTemplate` — argv or a python
  module, env, resource request (TPU chip count), working dir.
- The rendezvous port (reference default 23456, port name
  ``pytorchjob-port``) becomes the jax.distributed coordinator port.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

API_VERSION = "tpujob.dev/v1"
KIND = "TPUJob"


def _parse_enum(enum_cls, value, field_path: str):
    """Coerce a raw spec value to an enum, failing with a field-pathed,
    valid-values-listing error instead of the bare Enum ValueError."""
    try:
        return enum_cls(value)
    except ValueError:
        valid = ", ".join(e.value for e in enum_cls)
        raise ValueError(
            f"{field_path}: unknown value {value!r} (valid: {valid})"
        ) from None


def _parse_int(value, field_path: str) -> int:
    try:
        return int(value)
    except (TypeError, ValueError):
        raise ValueError(f"{field_path}: invalid integer {value!r}") from None


def _parse_opt_int(d: Dict[str, Any], key: str, field_path: str) -> Optional[int]:
    return _parse_int(d[key], field_path) if d.get(key) is not None else None


def _parse_float(value, field_path: str) -> float:
    try:
        return float(value)
    except (TypeError, ValueError):
        raise ValueError(f"{field_path}: invalid number {value!r}") from None


def _env_str(value, field_path: str) -> str:
    """Coerce an env value: YAML booleans become 'true'/'false' (what the
    user wrote), scalars stringify, structures are rejected."""
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (dict, list, tuple)):
        raise ValueError(f"{field_path}: env values must be scalar strings")
    return str(value)

# Reference parity: default rendezvous port and port name
# (pkg/apis/pytorch/v1/defaults.go — SURVEY.md §2 "Defaulting").
DEFAULT_PORT = 23456
DEFAULT_PORT_NAME = "tpujob-port"


class ReplicaType(str, enum.Enum):
    """Replica roles. Reference: PyTorchReplicaType (Master exactly-1, Worker 0..N)."""

    MASTER = "Master"
    WORKER = "Worker"

    def __str__(self) -> str:  # pragma: no cover - cosmetic
        return self.value


class RestartPolicy(str, enum.Enum):
    """Per-replica restart policy.

    Reference semantics (SURVEY.md §2 "Restart policies"):
      - ALWAYS: restart the process on any exit, success included.
      - ON_FAILURE: restart only on nonzero exit.
      - NEVER: never restart; a failure fails the job.
      - EXIT_CODE: exit 1–127 is a permanent failure (job fails); exit >=128
        (signal-ish / infrastructure codes, e.g. SIGKILL=137 on preemption)
        is retryable and triggers a restart.
    """

    ALWAYS = "Always"
    ON_FAILURE = "OnFailure"
    NEVER = "Never"
    EXIT_CODE = "ExitCode"


class CleanPodPolicy(str, enum.Enum):
    """What to do with worker processes when the job finishes.

    Reference: CleanPodPolicy All/Running/None (SURVEY.md §2 "Job lifecycle").
    Locally: RUNNING terminates still-running processes; ALL additionally
    removes per-replica artifacts (log files); NONE leaves processes alone
    (they are reparented, not killed — matches "leave pods around").
    """

    ALL = "All"
    RUNNING = "Running"
    NONE = "None"


class ConditionType(str, enum.Enum):
    """Job condition types — the state machine the reference drives in
    ``pkg/controller.v1/pytorch/status.go`` (SURVEY.md §2 "Status engine")."""

    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUSPENDED = "Suspended"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


# Terminal condition types: once one of these is true the job is finished.
TERMINAL_CONDITIONS = (ConditionType.SUCCEEDED, ConditionType.FAILED)

# ExitCode policy boundary: reference classifies exit 1-127 permanent,
# >=128 retryable (SURVEY.md §2 "Restart policies").
RETRYABLE_EXIT_CODE_MIN = 128


class ReplicaPhase(str, enum.Enum):
    """Phase of one replica process (pod-phase analog)."""

    PENDING = "Pending"    # created in the store, not yet started (gang hold)
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


@dataclass
class Resources:
    """Resource request for one replica process.

    The reference swaps ``nvidia.com/gpu`` limits for ``google.com/tpu``
    (BASELINE.json:5 north star); here the request is TPU chips for the
    process plus an optional CPU-device count for CPU-backend (test) runs.
    """

    tpu_chips: int = 0
    cpu_devices: int = 0  # forces JAX_PLATFORMS=cpu with N host devices

    def to_dict(self) -> Dict[str, Any]:
        return {"tpu_chips": self.tpu_chips, "cpu_devices": self.cpu_devices}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Resources":
        return cls(
            tpu_chips=_parse_int(d.get("tpu_chips", 0), "resources.tpu_chips"),
            cpu_devices=_parse_int(d.get("cpu_devices", 0), "resources.cpu_devices"),
        )


@dataclass
class ProcessTemplate:
    """Template for a replica process — the pod-template analog.

    Exactly one of ``command`` (argv) or ``module`` (run as ``python -m``)
    must be set. ``args`` are appended in either case.
    """

    command: Optional[List[str]] = None
    module: Optional[str] = None
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    working_dir: Optional[str] = None
    resources: Resources = field(default_factory=Resources)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.command is not None:
            d["command"] = list(self.command)
        if self.module is not None:
            d["module"] = self.module
        if self.args:
            d["args"] = list(self.args)
        if self.env:
            d["env"] = dict(self.env)
        if self.working_dir:
            d["working_dir"] = self.working_dir
        d["resources"] = self.resources.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ProcessTemplate":
        command = d.get("command")
        if command is not None and (
            isinstance(command, str) or not isinstance(command, (list, tuple))
        ):
            raise ValueError(
                "template.command: must be a list of argv strings "
                f"(got {type(command).__name__}); e.g. [python, train.py]"
            )
        args = d.get("args", [])
        if isinstance(args, str) or not isinstance(args, (list, tuple)):
            raise ValueError("template.args: must be a list of strings")
        return cls(
            command=[str(c) for c in command] if command is not None else None,
            module=d.get("module"),
            args=[str(a) for a in args],
            env={
                str(k): _env_str(v, f"template.env[{k}]")
                for k, v in (d.get("env") or {}).items()
            },
            working_dir=d.get("working_dir"),
            resources=Resources.from_dict(d.get("resources") or {}),
        )


@dataclass
class ReplicaSpec:
    """Spec for one replica type (reference: common ReplicaSpec)."""

    replicas: Optional[int] = None  # defaulted to 1
    restart_policy: Optional[RestartPolicy] = None  # defaulted
    template: ProcessTemplate = field(default_factory=ProcessTemplate)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"template": self.template.to_dict()}
        if self.replicas is not None:
            d["replicas"] = self.replicas
        if self.restart_policy is not None:
            d["restart_policy"] = self.restart_policy.value
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ReplicaSpec":
        rp = d.get("restart_policy")
        return cls(
            replicas=(
                _parse_int(d["replicas"], "replicas")
                if d.get("replicas") is not None
                else None
            ),
            restart_policy=(
                _parse_enum(RestartPolicy, rp, "restart_policy") if rp is not None else None
            ),
            template=ProcessTemplate.from_dict(d.get("template") or {}),
        )


@dataclass
class SchedulingPolicy:
    """Gang-scheduling policy (reference: volcano PodGroup via
    ``--enable-gang-scheduling``; SURVEY.md §2 "Gang scheduling").

    ``min_available`` defaults to the total replica count — all-or-nothing.
    ``priority`` orders jobs competing for capacity (higher wins; volcano
    priorityClass analog); ``queue`` names a capacity pool enforced by the
    supervisor's ``--queue-slots`` (volcano queue analog). ``shard`` pins
    the job to an explicit control-plane shard (modulo the state dir's
    shard count) instead of the key hash — co-locates related jobs (a
    wide gang and its feeders) on ONE reconciler under a sharded
    multi-supervisor control plane; ignored when the control plane runs
    unsharded.
    """

    gang: bool = True
    min_available: Optional[int] = None
    queue: Optional[str] = None
    priority: int = 0
    shard: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"gang": self.gang}
        if self.min_available is not None:
            d["min_available"] = self.min_available
        if self.queue is not None:
            d["queue"] = self.queue
        if self.priority:
            d["priority"] = self.priority
        if self.shard is not None:
            d["shard"] = self.shard
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SchedulingPolicy":
        return cls(
            gang=bool(d.get("gang", True)),
            min_available=_parse_opt_int(
                d, "min_available", "scheduling_policy.min_available"
            ),
            # Coerced at parse time: a numeric YAML queue name must not
            # surface as an int to consumers (display, queue-cap lookup).
            queue=str(d["queue"]) if d.get("queue") is not None else None,
            priority=(
                _parse_int(d["priority"], "scheduling_policy.priority")
                if d.get("priority") is not None
                else 0
            ),
            shard=_parse_opt_int(d, "shard", "scheduling_policy.shard"),
        )


@dataclass
class RunPolicy:
    """Job-level run policy (reference: common RunPolicy; SURVEY.md §2
    "Job lifecycle / cleanup")."""

    clean_pod_policy: Optional[CleanPodPolicy] = None  # defaulted
    ttl_seconds_after_finished: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    backoff_limit: Optional[int] = None  # max total restarts before Failed
    scheduling_policy: SchedulingPolicy = field(default_factory=SchedulingPolicy)
    # Create-but-don't-run (reference: training-operator RunPolicy.suspend,
    # the Kueue integration point): while True, no replicas run — a live
    # world is torn down — and the job waits in Suspended until resumed.
    suspend: bool = False

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"scheduling_policy": self.scheduling_policy.to_dict()}
        if self.suspend:
            d["suspend"] = True
        if self.clean_pod_policy is not None:
            d["clean_pod_policy"] = self.clean_pod_policy.value
        for k in ("ttl_seconds_after_finished", "active_deadline_seconds", "backoff_limit"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RunPolicy":
        cpp = d.get("clean_pod_policy")
        return cls(
            clean_pod_policy=(
                _parse_enum(CleanPodPolicy, cpp, "run_policy.clean_pod_policy")
                if cpp is not None
                else None
            ),
            ttl_seconds_after_finished=_parse_opt_int(
                d, "ttl_seconds_after_finished", "run_policy.ttl_seconds_after_finished"
            ),
            active_deadline_seconds=_parse_opt_int(
                d, "active_deadline_seconds", "run_policy.active_deadline_seconds"
            ),
            backoff_limit=_parse_opt_int(d, "backoff_limit", "run_policy.backoff_limit"),
            scheduling_policy=SchedulingPolicy.from_dict(d.get("scheduling_policy") or {}),
            suspend=bool(d.get("suspend", False)),
        )


@dataclass
class ElasticPolicy:
    """Elastic training policy (reference: torchelastic integration /
    ElasticPolicy in the training-operator era; SURVEY.md §2 "Elastic",
    BASELINE.json:11).

    When set, the job may run with worker counts in [min_replicas,
    max_replicas]. A partial-gang death RESIZES the world in place
    (survivors re-join at a new resize generation — controller/elastic.py);
    coordinator death or a death that would leave fewer than
    ``min_replicas`` workers still re-rendezvouses the whole gang (fresh
    jax.distributed world) from the latest checkpoint, up to
    ``max_restarts`` times. ``hot_spares`` keeps N pre-warmed standby
    processes (controller/standby.py) that a shrink promotes into the
    gang instead of cold-spawning a replacement.
    """

    min_replicas: int = 1
    max_replicas: int = 1
    max_restarts: int = 10
    hot_spares: int = 0

    def to_dict(self) -> Dict[str, Any]:
        # Explicit dict, not dataclasses.asdict: this runs on the
        # supervisor's per-pass persistence path and asdict's recursive
        # deep-copy is ~10x the cost of building the flat dict.
        d = {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "max_restarts": self.max_restarts,
        }
        if self.hot_spares:
            d["hot_spares"] = self.hot_spares
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ElasticPolicy":
        return cls(
            min_replicas=_parse_int(d.get("min_replicas", 1), "elastic_policy.min_replicas"),
            max_replicas=_parse_int(d.get("max_replicas", 1), "elastic_policy.max_replicas"),
            max_restarts=_parse_int(d.get("max_restarts", 10), "elastic_policy.max_restarts"),
            hot_spares=_parse_int(d.get("hot_spares", 0), "elastic_policy.hot_spares"),
        )


@dataclass
class DataPlanePolicy:
    """Host-I/O overlap knobs for the training data plane.

    Threaded into every replica's environment (``TPUJOB_ASYNC_CHECKPOINT``
    / ``TPUJOB_PREFETCH``, runtime/env.py) where the training workloads
    read them as defaults for their ``--async-checkpoint`` / ``--prefetch``
    flags — so a spec can take checkpoint commits and host→device
    transfers off the step loop's critical path without per-workload
    args plumbing.
    """

    # Overlap checkpoint commits with training steps (verified at commit
    # — checkpoint/async_writer.py).
    async_checkpoint: bool = False
    # Device-feed lookahead depth (batches resident on device ahead of
    # the step loop — data/device_prefetch.py). 0 = inline transfers.
    prefetch: int = 0
    # Upper bound for the feed's lookahead — the device-memory budget
    # the depth autotuner may grow into (0 = the static ``prefetch``
    # depth is also the cap).
    prefetch_depth_max: int = 0
    # Let the feed resize its own depth inside [1, prefetch_depth_max]
    # from the measured step-loop stall (data/feed_autotune.py:
    # grow-fast/shrink-slow). Requires prefetch > 0.
    autotune: bool = False
    # Producer threads in the device feed's sharded gather (batch pulls
    # stay serialized and FIFO-ordered; casts/copies/transfers overlap).
    # 0 = single producer thread.
    prefetch_workers: int = 0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.async_checkpoint:
            d["async_checkpoint"] = True
        if self.prefetch:
            d["prefetch"] = self.prefetch
        if self.prefetch_depth_max:
            d["prefetch_depth_max"] = self.prefetch_depth_max
        if self.autotune:
            d["autotune"] = True
        if self.prefetch_workers:
            d["prefetch_workers"] = self.prefetch_workers
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DataPlanePolicy":
        return cls(
            async_checkpoint=bool(d.get("async_checkpoint", False)),
            prefetch=_parse_int(d.get("prefetch", 0), "data_plane.prefetch"),
            prefetch_depth_max=_parse_int(
                d.get("prefetch_depth_max", 0), "data_plane.prefetch_depth_max"
            ),
            autotune=bool(d.get("autotune", False)),
            prefetch_workers=_parse_int(
                d.get("prefetch_workers", 0), "data_plane.prefetch_workers"
            ),
        )


@dataclass
class AlertPolicy:
    """Live health-engine knobs (obs/watch.py + obs/rules.py).

    The supervisor's streaming evaluator runs the shared detector
    rules (heartbeat silence, step-time regression, feed-stall
    dominance, checkpoint lag, straggler, noisy neighbor) over every
    reporting job each sync pass. This block tunes ONE job's alerting:
    ``enabled: false`` opts the job out entirely; ``for_s`` is the
    hysteresis before a pending alert fires (a condition must persist
    this long); ``clear_s`` before a firing alert resolves after the
    condition clears; ``thresholds`` overrides any subset of the rule
    thresholds by name (see obs/rules.Thresholds — e.g.
    ``regression_factor: 2.0``, ``silence_min_s: 5``). The SAME values
    drive ``tpujob why`` offline, so live and postmortem judge by one
    bar.
    """

    enabled: bool = True
    for_s: float = 0.0
    clear_s: float = 5.0
    thresholds: Dict[str, float] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if not self.enabled:
            d["enabled"] = False
        if self.for_s:
            d["for_s"] = self.for_s
        if self.clear_s != 5.0:
            d["clear_s"] = self.clear_s
        if self.thresholds:
            d["thresholds"] = dict(self.thresholds)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AlertPolicy":
        raw = d.get("thresholds") or {}
        if not isinstance(raw, dict):
            raise ValueError(
                "observability.alerts.thresholds: must be a mapping"
            )
        thresholds: Dict[str, float] = {}
        for k, v in raw.items():
            try:
                thresholds[str(k)] = float(v)
            except (TypeError, ValueError):
                raise ValueError(
                    f"observability.alerts.thresholds[{k}]: must be a "
                    f"number, got {v!r}"
                ) from None
        return cls(
            enabled=bool(d.get("enabled", True)),
            for_s=_parse_float(d.get("for_s", 0.0), "observability.alerts.for_s"),
            clear_s=_parse_float(
                d.get("clear_s", 5.0), "observability.alerts.clear_s"
            ),
            thresholds=thresholds,
        )


@dataclass
class ObservabilityPolicy:
    """Flight-recorder knobs (obs/).

    ``trace: true`` makes the supervisor inject a per-job
    ``TPUJOB_TRACE_DIR`` into every replica (runtime/env.py), so the
    step loop, device feed, rendezvous join, and async checkpoint
    commits record spans to per-process ring files that ``tpujob trace
    <job>`` merges into one Chrome-trace/Perfetto JSON. Off (the
    default) the span helpers are a cached None check — zero step-path
    overhead, pinned by the bench_smoke lane.

    ``trace_ring_bytes`` / ``trace_flush_every`` size the per-process
    span ring (bytes per generation, two generations kept) and the
    record-count flush cadence — spec knobs instead of the former fixed
    constants, threaded as ``TPUJOB_TRACE_RING_BYTES`` /
    ``TPUJOB_TRACE_FLUSH_EVERY``. 0 (the default) keeps the built-in
    defaults (obs/trace.py: 8 MiB, 32 records).
    """

    trace: bool = False
    trace_ring_bytes: int = 0
    trace_flush_every: int = 0
    # Live health-engine tuning (obs/watch.py); None = defaults (the
    # watch runs for every job — this block customizes, it doesn't arm).
    alerts: Optional[AlertPolicy] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.trace:
            d["trace"] = True
        if self.trace_ring_bytes:
            d["trace_ring_bytes"] = self.trace_ring_bytes
        if self.trace_flush_every:
            d["trace_flush_every"] = self.trace_flush_every
        if self.alerts is not None and (al := self.alerts.to_dict()):
            d["alerts"] = al
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ObservabilityPolicy":
        return cls(
            trace=bool(d.get("trace", False)),
            trace_ring_bytes=_parse_int(
                d.get("trace_ring_bytes", 0), "observability.trace_ring_bytes"
            ),
            trace_flush_every=_parse_int(
                d.get("trace_flush_every", 0),
                "observability.trace_flush_every",
            ),
            alerts=(
                AlertPolicy.from_dict(d["alerts"])
                if d.get("alerts") is not None
                else None
            ),
        )


@dataclass
class ServingSLOPolicy:
    """Admission-control bar for a serving job's front queue
    (serving/slo.py). The router judges every request against this at
    claim time: a front queue past ``max_queue_depth`` or a request
    older than ``deadline_s`` is SHED with an explicit overload
    response instead of queueing unboundedly — the client learns it
    must back off now, not after a timeout.
    """

    # Requests admitted + in flight through the router at once; arrivals
    # past it are shed. 0 = unbounded (no depth-based shedding).
    max_queue_depth: int = 0
    # Per-request deadline measured from the client's submit_time; a
    # request that cannot be dispatched before it is shed. 0 = none.
    deadline_s: float = 0.0
    # Re-route attempts after a replica death before the router answers
    # the request with an error response itself.
    retry_limit: int = 2
    # Availability target for error-budget burn accounting
    # (serving/slo.py:BurnAccount): the fraction of published outcomes
    # expected to be good (not shed / errored / past deadline). 0 =
    # default (0.99). Feeds the tpujob_slo_burn_rate{job,window}
    # gauges and the slo_burn rule, never the admission decision.
    target: float = 0.0
    # Width of the FAST burn window in seconds (the one the BURN
    # column, the serve-record burn field and the slo_burn rule read).
    # 0 = default (30s); the 5m slow window is fixed.
    burn_window_s: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.max_queue_depth:
            d["max_queue_depth"] = self.max_queue_depth
        if self.deadline_s:
            d["deadline_s"] = self.deadline_s
        if self.retry_limit != 2:
            d["retry_limit"] = self.retry_limit
        if self.target:
            d["target"] = self.target
        if self.burn_window_s:
            d["burn_window_s"] = self.burn_window_s
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServingSLOPolicy":
        return cls(
            max_queue_depth=_parse_int(
                d.get("max_queue_depth", 0), "serving.slo.max_queue_depth"
            ),
            deadline_s=_parse_float(
                d.get("deadline_s", 0.0), "serving.slo.deadline_s"
            ),
            retry_limit=_parse_int(
                d.get("retry_limit", 2), "serving.slo.retry_limit"
            ),
            target=_parse_float(d.get("target", 0.0), "serving.slo.target"),
            burn_window_s=_parse_float(
                d.get("burn_window_s", 0.0), "serving.slo.burn_window_s"
            ),
        )


@dataclass
class ServingPolicy:
    """Marks the job as a SERVING job and configures the serve plane
    (serving/router.py): the supervisor hosts a request router that
    claims from the job's client-facing FRONT spool, admission-controls
    against ``slo``, and dispatches each request to the least-loaded
    replica's private spool (injected per replica as
    ``TPUJOB_SPOOL_DIR`` — runtime/env.py). Presence of this block is
    what arms the router; an empty ``serving: {}`` is a serving job
    with defaults, NOT a no-op — so, unlike the other optional policy
    blocks, it round-trips even when empty.
    """

    # Client-facing front spool directory. Unset = the supervisor's
    # default layout: <state>/serve/<ns>_<job>/front.
    spool_dir: Optional[str] = None
    slo: Optional[ServingSLOPolicy] = None
    # Router↔engine transport tier (serving/shmring.py). "spool" (the
    # default) keeps every request on the durable file path; "shmring"
    # adds per-replica shared-memory rings for co-host traffic, with
    # the file spool as the automatic spill (ring full) and cross-host
    # path — durability semantics are identical either way, because
    # the front spool's respond_once is the exactly-once point.
    transport: str = "spool"
    # 0 = the router data plane rides the supervisor sync pass (legacy,
    # single-threaded). N >= 1 = N continuously-running router shard
    # workers partitioned by request hash — the serve-plane analog of
    # the N-supervisor lease split.
    router_shards: int = 0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.spool_dir:
            d["spool_dir"] = self.spool_dir
        if self.slo is not None and (s := self.slo.to_dict()):
            d["slo"] = s
        if self.transport != "spool":
            d["transport"] = self.transport
        if self.router_shards:
            d["router_shards"] = self.router_shards
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServingPolicy":
        sd = d.get("spool_dir")
        return cls(
            spool_dir=str(sd) if sd else None,
            slo=(
                ServingSLOPolicy.from_dict(d["slo"])
                if d.get("slo") is not None
                else None
            ),
            transport=str(d.get("transport", "spool") or "spool"),
            router_shards=int(d.get("router_shards", 0) or 0),
        )


@dataclass
class RemediationRoute:
    """Generic alert→external-action route for rules with no built-in
    actuator (controller/remediation.py). Exactly one of ``webhook``
    (POST the committed audit record as JSON) or ``exec`` (argv; the
    record rides stdin as JSON) must be set. Delivery is best-effort
    and strictly post-commit: the fenced audit record is the durable
    truth whether or not the external side ever hears about it."""

    # Alert rule name (obs/rules.py) this route answers.
    rule: str = ""
    # URL to POST the audit record to.
    webhook: str = ""
    # Argv to spawn with the audit record on stdin.
    exec: List[str] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"rule": self.rule}
        if self.webhook:
            d["webhook"] = self.webhook
        if self.exec:
            d["exec"] = list(self.exec)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RemediationRoute":
        ex = d.get("exec") or []
        if not isinstance(ex, list):
            raise ValueError("remediation.routes[].exec: expected a list")
        return cls(
            rule=str(d.get("rule", "") or ""),
            webhook=str(d.get("webhook", "") or ""),
            exec=[str(a) for a in ex],
        )


@dataclass
class RemediationPolicy:
    """Arms alert-driven auto-remediation (controller/remediation.py):
    the supervisor maps this job's FIRING alert transitions to actuator
    actions — serving replica-set grow/shrink for ``slo_burn`` /
    ``queue_growth`` / sustained idle, preempt-into-hot-spare for
    ``straggler`` / ``heartbeat_silence``, async-checkpoint cadence
    raise for ``checkpoint_lag``, migrate for ``noisy_neighbor``, and
    generic webhook/exec ``routes`` for everything else. Presence of
    this block arms the engine; like ``serving`` it round-trips even
    when empty. The SAFE default is ``dry_run: true`` — decisions are
    audited (``tpujob remediations``) but the fleet is never touched
    until dry_run is explicitly turned off.
    """

    # Master off-switch without dropping the block (keeps the policy
    # diffable while disarmed).
    enabled: bool = True
    # Log would-have-acted decisions to the audit log, never actuate.
    # THE DEFAULT: flipping this to false is the operator's explicit
    # "hands off the wheel" moment.
    dry_run: bool = True
    # Seconds between actions for the same (rule, action) pair; each
    # consecutive action on the pair stretches it by ``backoff``×
    # (grow-fast/shrink-slow hysteresis, controller/autoscale.py).
    cooldown_s: float = 30.0
    backoff: float = 2.0
    # Lifetime action budget for the job: the remediation generation IS
    # the counter, so the cap survives supervisor failover. 0 = none.
    max_actions: int = 20
    # Serving replica-set bounds for the scale actuator.
    scale_min: int = 1
    scale_max: int = 8
    # Sustained-idle window before the shrink actuator considers the
    # serve plane over-provisioned (front queue empty AND zero inflight
    # the whole window).
    idle_s: float = 60.0
    # Generic routes for rules with no built-in actuator.
    routes: List[RemediationRoute] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if not self.enabled:
            d["enabled"] = False
        if not self.dry_run:
            d["dry_run"] = False
        if self.cooldown_s != 30.0:
            d["cooldown_s"] = self.cooldown_s
        if self.backoff != 2.0:
            d["backoff"] = self.backoff
        if self.max_actions != 20:
            d["max_actions"] = self.max_actions
        if self.scale_min != 1:
            d["scale_min"] = self.scale_min
        if self.scale_max != 8:
            d["scale_max"] = self.scale_max
        if self.idle_s != 60.0:
            d["idle_s"] = self.idle_s
        if self.routes:
            d["routes"] = [r.to_dict() for r in self.routes]
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RemediationPolicy":
        routes = d.get("routes") or []
        if not isinstance(routes, list):
            raise ValueError("remediation.routes: expected a list")
        return cls(
            enabled=bool(d.get("enabled", True)),
            dry_run=bool(d.get("dry_run", True)),
            cooldown_s=_parse_float(
                d.get("cooldown_s", 30.0), "remediation.cooldown_s"
            ),
            backoff=_parse_float(d.get("backoff", 2.0), "remediation.backoff"),
            max_actions=_parse_int(
                d.get("max_actions", 20), "remediation.max_actions"
            ),
            scale_min=_parse_int(
                d.get("scale_min", 1), "remediation.scale_min"
            ),
            scale_max=_parse_int(
                d.get("scale_max", 8), "remediation.scale_max"
            ),
            idle_s=_parse_float(d.get("idle_s", 60.0), "remediation.idle_s"),
            routes=[RemediationRoute.from_dict(r) for r in routes],
        )


@dataclass
class TPUJobSpec:
    """The TPUJob spec (reference: PyTorchJobSpec — RunPolicy + a map
    ReplicaType→ReplicaSpec with Master exactly-1)."""

    replica_specs: Dict[ReplicaType, ReplicaSpec] = field(default_factory=dict)
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    elastic_policy: Optional[ElasticPolicy] = None
    data_plane: Optional[DataPlanePolicy] = None
    observability: Optional[ObservabilityPolicy] = None
    # Serve plane (serving/router.py); presence arms the router.
    serving: Optional[ServingPolicy] = None
    # Auto-remediation (controller/remediation.py); presence arms the
    # engine (dry-run by default).
    remediation: Optional[RemediationPolicy] = None
    # Coordinator (rendezvous) port — the pytorchjob-port analog.
    port: Optional[int] = None  # defaulted to DEFAULT_PORT

    def total_replicas(self) -> int:
        return sum(rs.replicas or 0 for rs in self.replica_specs.values())

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "replica_specs": {
                rt.value: rs.to_dict() for rt, rs in self.replica_specs.items()
            },
            "run_policy": self.run_policy.to_dict(),
        }
        if self.elastic_policy is not None:
            d["elastic_policy"] = self.elastic_policy.to_dict()
        if self.data_plane is not None and (dp := self.data_plane.to_dict()):
            d["data_plane"] = dp
        if self.observability is not None and (
            ob := self.observability.to_dict()
        ):
            d["observability"] = ob
        if self.serving is not None:
            # Not sparse-elided: an empty serving block still arms the
            # router (see ServingPolicy).
            d["serving"] = self.serving.to_dict()
        if self.remediation is not None:
            # Same presence-arms semantics as serving.
            d["remediation"] = self.remediation.to_dict()
        if self.port is not None:
            d["port"] = self.port
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TPUJobSpec":
        replica_specs: Dict[ReplicaType, ReplicaSpec] = {}
        for rt, rs in (d.get("replica_specs") or {}).items():
            rtype = _parse_enum(ReplicaType, rt, "spec.replica_specs key")
            try:
                replica_specs[rtype] = ReplicaSpec.from_dict(rs)
            except ValueError as e:
                raise ValueError(f"spec.replica_specs[{rtype.value}].{e}") from None
        return cls(
            replica_specs=replica_specs,
            run_policy=RunPolicy.from_dict(d.get("run_policy") or {}),
            elastic_policy=(
                ElasticPolicy.from_dict(d["elastic_policy"])
                if d.get("elastic_policy") is not None
                else None
            ),
            data_plane=(
                DataPlanePolicy.from_dict(d["data_plane"])
                if d.get("data_plane") is not None
                else None
            ),
            observability=(
                ObservabilityPolicy.from_dict(d["observability"])
                if d.get("observability") is not None
                else None
            ),
            serving=(
                ServingPolicy.from_dict(d["serving"])
                if d.get("serving") is not None
                else None
            ),
            remediation=(
                RemediationPolicy.from_dict(d["remediation"])
                if d.get("remediation") is not None
                else None
            ),
            port=_parse_opt_int(d, "port", "spec.port"),
        )


@dataclass
class JobCondition:
    """One entry in status.conditions (reference: common JobCondition)."""

    type: ConditionType
    status: bool
    reason: str = ""
    message: str = ""
    last_update_time: float = 0.0
    last_transition_time: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "type": self.type.value,
            "status": self.status,
            "reason": self.reason,
            "message": self.message,
            "last_update_time": self.last_update_time,
            "last_transition_time": self.last_transition_time,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "JobCondition":
        return cls(
            type=_parse_enum(ConditionType, d.get("type"), "condition.type"),
            status=bool(d.get("status", False)),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            last_update_time=float(d.get("last_update_time", 0.0)),
            last_transition_time=float(d.get("last_transition_time", 0.0)),
        )


@dataclass
class ReplicaStatus:
    """Per-replica-type counters (reference: common ReplicaStatus)."""

    active: int = 0
    succeeded: int = 0
    failed: int = 0

    def to_dict(self) -> Dict[str, Any]:
        # Explicit dict, not dataclasses.asdict — per-pass hot path (see
        # ElasticPolicy.to_dict).
        return {
            "active": self.active,
            "succeeded": self.succeeded,
            "failed": self.failed,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ReplicaStatus":
        return cls(
            active=int(d.get("active", 0)),
            succeeded=int(d.get("succeeded", 0)),
            failed=int(d.get("failed", 0)),
        )


@dataclass
class TPUJobStatus:
    """Job status (reference: PyTorchJobStatus / common JobStatus)."""

    conditions: List[JobCondition] = field(default_factory=list)
    replica_statuses: Dict[ReplicaType, ReplicaStatus] = field(default_factory=dict)
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    restart_count: int = 0
    # Elastic resize epoch (controller/elastic.py): bumped once per
    # in-place world resize. Persisted through the (lease-fenced) store
    # so a supervisor failover mid-resize completes the SAME generation
    # exactly once instead of minting a second one. 0 = the world has
    # never resized.
    resize_generation: int = 0
    # Remediation epoch (controller/remediation.py): bumped once per
    # committed remediation action, through the same lease-fenced store
    # write that mutates the spec — the PR-11 resize-fencing template.
    # A supervisor failover mid-action adopts the SAME generation and
    # heals derived state instead of acting twice; it doubles as the
    # lifetime max_actions budget counter. 0 = never remediated.
    remediation_generation: int = 0
    # Observability extras (north-star metric BASELINE.json:2): wall-clock
    # timestamps of submit-accepted and first training step, set by the
    # supervisor from workload status reports.
    submit_time: Optional[float] = None
    first_step_time: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "conditions": [c.to_dict() for c in self.conditions],
            "replica_statuses": {
                rt.value: rs.to_dict() for rt, rs in self.replica_statuses.items()
            },
            "start_time": self.start_time,
            "completion_time": self.completion_time,
            "restart_count": self.restart_count,
            "resize_generation": self.resize_generation,
            "remediation_generation": self.remediation_generation,
            "submit_time": self.submit_time,
            "first_step_time": self.first_step_time,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TPUJobStatus":
        return cls(
            conditions=[JobCondition.from_dict(c) for c in d.get("conditions", [])],
            replica_statuses={
                _parse_enum(ReplicaType, rt, "status.replica_statuses key"):
                    ReplicaStatus.from_dict(rs)
                for rt, rs in (d.get("replica_statuses") or {}).items()
            },
            start_time=d.get("start_time"),
            completion_time=d.get("completion_time"),
            restart_count=int(d.get("restart_count", 0)),
            resize_generation=int(d.get("resize_generation", 0)),
            remediation_generation=int(d.get("remediation_generation", 0)),
            submit_time=d.get("submit_time"),
            first_step_time=d.get("first_step_time"),
        )


@dataclass
class ObjectMeta:
    """Object metadata (name/namespace/uid/labels)."""

    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name, "namespace": self.namespace}
        if self.uid:
            d["uid"] = self.uid
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.annotations:
            d["annotations"] = dict(self.annotations)
        if self.creation_timestamp is not None:
            d["creation_timestamp"] = self.creation_timestamp
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ObjectMeta":
        return cls(
            name=d.get("name", ""),
            namespace=d.get("namespace", "default"),
            uid=d.get("uid", ""),
            labels={str(k): str(v) for k, v in (d.get("labels") or {}).items()},
            annotations={str(k): str(v) for k, v in (d.get("annotations") or {}).items()},
            creation_timestamp=d.get("creation_timestamp"),
        )


@dataclass
class TPUJob:
    """The TPUJob object (reference: PyTorchJob CRD)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TPUJobSpec = field(default_factory=TPUJobSpec)
    status: TPUJobStatus = field(default_factory=TPUJobStatus)
    api_version: str = API_VERSION
    kind: str = KIND

    def __post_init__(self) -> None:
        # In-memory generation counter (NOT a dataclass field: it must
        # never serialize, reach the CRD schema, or survive a reload).
        # Mutators bump it via touch(); JobStore._persist compares it
        # against the generation last written to disk, making the
        # clean-job check O(1) — no to_dict() per job per pass.
        self.generation = 0

    def touch(self) -> None:
        """Mark this object dirty for persistence. Call after mutating
        spec/status/metadata in place; :meth:`set_condition` and
        ``controller.status.update_replica_statuses`` call it for you.
        A missed touch means the change stays in-memory-only until the
        next real transition — the store's dirty check trusts this
        counter INSTEAD of serializing the job on every pass."""
        self.generation += 1

    # ---- condition helpers (reference: status.go condition utilities) ----

    def get_condition(self, ctype: ConditionType) -> Optional[JobCondition]:
        for c in self.status.conditions:
            if c.type == ctype:
                return c
        return None

    def has_condition(self, ctype: ConditionType) -> bool:
        c = self.get_condition(ctype)
        return c is not None and c.status

    def is_finished(self) -> bool:
        return any(self.has_condition(t) for t in TERMINAL_CONDITIONS)

    def is_succeeded(self) -> bool:
        return self.has_condition(ConditionType.SUCCEEDED)

    def is_failed(self) -> bool:
        return self.has_condition(ConditionType.FAILED)

    def set_condition(
        self,
        ctype: ConditionType,
        status: bool = True,
        reason: str = "",
        message: str = "",
        now: Optional[float] = None,
    ) -> None:
        """Set a condition, mirroring the reference's updateJobConditions:

        - updating an existing condition touches last_update_time, and
          last_transition_time only when the status flips;
        - setting RUNNING true clears RESTARTING (and vice versa) — they are
          mutually exclusive "current state" conditions;
        - terminal conditions clear RUNNING/RESTARTING.
        """
        now = time.time() if now is None else now
        self.touch()  # every set_condition changes last_update_time
        cond = self.get_condition(ctype)
        if cond is None:
            self.status.conditions.append(
                JobCondition(
                    type=ctype,
                    status=status,
                    reason=reason,
                    message=message,
                    last_update_time=now,
                    last_transition_time=now,
                )
            )
        else:
            if cond.status != status:
                cond.last_transition_time = now
            cond.status = status
            cond.reason = reason or cond.reason
            cond.message = message or cond.message
            cond.last_update_time = now

        if status:
            exclusive: Dict[ConditionType, List[ConditionType]] = {
                ConditionType.RUNNING: [
                    ConditionType.RESTARTING,
                    ConditionType.SUSPENDED,
                ],
                ConditionType.RESTARTING: [
                    ConditionType.RUNNING,
                    ConditionType.SUSPENDED,
                ],
                ConditionType.SUSPENDED: [
                    ConditionType.RUNNING,
                    ConditionType.RESTARTING,
                ],
                ConditionType.SUCCEEDED: [
                    ConditionType.RUNNING,
                    ConditionType.RESTARTING,
                    ConditionType.SUSPENDED,
                ],
                ConditionType.FAILED: [
                    ConditionType.RUNNING,
                    ConditionType.RESTARTING,
                    ConditionType.SUSPENDED,
                ],
            }
            for other in exclusive.get(ctype, []):
                oc = self.get_condition(other)
                if oc is not None and oc.status:
                    oc.status = False
                    oc.last_update_time = now
                    oc.last_transition_time = now

    # ---- serialization ----

    def to_dict(self) -> Dict[str, Any]:
        return {
            "api_version": self.api_version,
            "kind": self.kind,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TPUJob":
        return cls(
            api_version=d.get("api_version", API_VERSION),
            kind=d.get("kind", KIND),
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=TPUJobSpec.from_dict(d.get("spec") or {}),
            status=TPUJobStatus.from_dict(d.get("status") or {}),
        )
