"""YAML/JSON (de)serialization for TPUJob.

The kubectl-apply surface of the reference (CRD YAML under ``manifests/`` and
``examples/*.yaml``; SURVEY.md §1 layers 6–7) becomes plain YAML files loaded
into :class:`~pytorch_operator_tpu.api.types.TPUJob` dataclasses.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Union

import yaml

from .convert import convert_pytorchjob, is_pytorchjob
from .types import TPUJob


def job_from_dict(d: dict) -> TPUJob:
    # Migration shim: a kubeflow PyTorchJob manifest (the reference's user
    # surface) is converted on the way in, so `tpujob submit` accepts it
    # directly (api/convert.py).
    if is_pytorchjob(d):
        d = convert_pytorchjob(d)
    return TPUJob.from_dict(d)


def load_job(path: Union[str, Path]) -> TPUJob:
    """Load a TPUJob from a YAML (or JSON) file."""
    text = Path(path).read_text()
    data = yaml.safe_load(text)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: expected a mapping at the top level")
    return job_from_dict(data)


def loads_job(text: str) -> TPUJob:
    data = yaml.safe_load(text)
    if not isinstance(data, dict):
        raise ValueError("expected a mapping at the top level")
    return job_from_dict(data)


def dump_job(job: TPUJob) -> str:
    """Serialize a TPUJob to YAML (round-trips through from_dict)."""
    return yaml.safe_dump(job.to_dict(), sort_keys=False)


def dump_job_json(job: TPUJob) -> str:
    return json.dumps(job.to_dict(), indent=2)


def save_job(job: TPUJob, path: Union[str, Path]) -> None:
    Path(path).write_text(dump_job(job))
