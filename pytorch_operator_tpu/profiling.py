"""Profile-report tool: wall-time breakdown from a jax.profiler trace.

SURVEY.md §5 "Tracing / profiling": the reference has none of its own
(training-side profiling is user-container business); the rebuild's
workloads write ``jax.profiler`` traces via ``--profile-dir``. This
module closes the loop WITHOUT tensorboard: it parses the trace's
``*.xplane.pb`` directly and prints where device time goes — per-step
busy/idle split, op-category totals, and the top individual ops — the
analysis used for the BASELINE.md bandwidth-wall findings, as a tool.

Usage::

    python -m pytorch_operator_tpu.workloads.llama_train ... --profile-dir /tmp/prof
    python -m pytorch_operator_tpu.profiling /tmp/prof [--top 12] [--json]

The xplane schema is stable across the jax/tf profiler family: planes
(one per device) → lines (Steps / XLA Ops / ...) → timed events whose
metadata names the HLO op. Parsing needs the ``xplane_pb2`` proto, which
ships inside the installed tensorflow (cpu) package; anything missing
degrades to a clear error, never a crash, since this is a diagnostics
path.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from collections import defaultdict
from pathlib import Path
from typing import Optional

_PS = 1e-12


def _import_xplane_pb2():
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2  # type: ignore

        return xplane_pb2
    except ImportError:
        pass
    try:  # newer layouts
        from tsl.profiler.protobuf import xplane_pb2  # type: ignore

        return xplane_pb2
    except ImportError as e:
        raise RuntimeError(
            "no xplane_pb2 proto available (needs the tensorflow package "
            "that ships in this image) — cannot parse the trace"
        ) from e


def find_xplane(profile_dir) -> Path:
    """Newest ``*.xplane.pb`` under a ``--profile-dir`` tree."""
    paths = sorted(
        Path(profile_dir).rglob("*.xplane.pb"), key=lambda p: p.stat().st_mtime
    )
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {profile_dir}")
    return paths[-1]


def _category(display_name: str) -> str:
    """HLO op display names carry a ``kind.N`` suffix — strip the serial
    to get the category (fusion, copy, all-reduce, custom-call, ...)."""
    return re.sub(r"[.\-]?\d+$", "", display_name) or display_name


def _aggregate_self_times(line, meta, by_cat, by_op) -> float:
    """Charge each event its SELF time (duration minus enclosed children)
    into the aggregates; returns the line's total busy seconds.

    Events nest within a line (a layer-scan ``while`` contains its body
    ops; a python frame contains its callees) — self-time keeps the
    total equal to true busy time instead of double-counting every
    nesting level. An interval stack over offset-sorted events recovers
    the tree.
    """
    busy = 0.0
    stack: list = []  # [end_ps, metadata_id, start_ps, child_ps]

    def pop(ev_start_ps) -> None:
        nonlocal busy
        while stack and (ev_start_ps is None or stack[-1][0] <= ev_start_ps):
            end, mid, start, child = stack.pop()
            dur = end - start
            if stack:
                stack[-1][3] += dur
            dt = (dur - child) * _PS
            busy += dt
            m = meta.get(mid)
            name = (m.display_name or m.name) if m is not None else f"op{mid}"
            by_cat[_category(name)] += dt
            by_op[name] += dt

    # Outer intervals must be pushed before children that share their
    # start timestamp — longest-first at ties keeps the nesting upright
    # (child-first would charge the child a negative self time).
    for e in sorted(line.events, key=lambda e: (e.offset_ps, -e.duration_ps)):
        pop(e.offset_ps)
        stack.append([e.offset_ps + e.duration_ps, e.metadata_id, e.offset_ps, 0])
    pop(None)
    return busy


def device_report(profile_dir, device_substr: str = "TPU") -> Optional[dict]:
    """Aggregate the device plane into a wall breakdown dict.

    Returns None when the trace has no matching device plane (e.g. a
    CPU-only run asked for TPU).
    """
    xplane_pb2 = _import_xplane_pb2()
    xs = xplane_pb2.XSpace()
    xs.ParseFromString(find_xplane(profile_dir).read_bytes())

    plane = next(
        (p for p in xs.planes if device_substr in p.name and p.lines), None
    )
    if plane is None:
        return None

    lines = {l.name: l for l in plane.lines}
    report: dict = {"device": plane.name}

    steps = lines.get("Steps")
    if steps is not None and steps.events:
        durs = [e.duration_ps * _PS for e in steps.events]
        report["steps"] = len(durs)
        report["mean_step_s"] = sum(durs) / len(durs)
        report["span_s"] = sum(durs)

    # Per-op accounting: the device's "XLA Ops" line when present (TPU
    # traces), else every thread line (host/CPU traces, where the events
    # are python/runtime frames — still a useful where-does-time-go).
    if "XLA Ops" in lines:
        op_lines = [lines["XLA Ops"]]
    else:
        op_lines = [
            l for l in plane.lines
            if l.events and l.name not in ("Steps", "XLA Modules")
        ]
    if any(l.events for l in op_lines):
        meta = plane.event_metadata
        by_cat: dict = defaultdict(float)
        by_op: dict = defaultdict(float)
        busy = 0.0
        for line in op_lines:
            busy += _aggregate_self_times(line, meta, by_cat, by_op)
        if busy <= 0:
            # All-zero-duration events (truncated capture, instant
            # markers): no meaningful breakdown — report what exists
            # rather than dividing by zero below.
            return report
        report["busy_s"] = busy
        # Busy-vs-span is a utilization figure only for the single device
        # op line; summing N concurrent host threads against wall time
        # would read >100% and mean nothing.
        if len(op_lines) == 1 and report.get("span_s", 0) > 0:
            report["busy_frac_of_steps"] = busy / report["span_s"]
        n = report.get("steps") or 1
        report["categories"] = sorted(
            (
                {"category": c, "s_per_step": t / n, "pct_of_busy": 100 * t / busy}
                for c, t in by_cat.items()
            ),
            key=lambda r: -r["s_per_step"],
        )
        report["top_ops"] = sorted(
            (
                {"op": o, "s_per_step": t / n, "pct_of_busy": 100 * t / busy}
                for o, t in by_op.items()
            ),
            key=lambda r: -r["s_per_step"],
        )
    return report


def format_report(report: dict, top: int = 12) -> str:
    out = [f"device: {report['device']}"]
    if "steps" in report:
        out.append(
            f"steps: {report['steps']}  mean {report['mean_step_s']*1e3:.2f} ms/step"
        )
    if "busy_s" in report:
        n = report.get("steps") or 1
        line = f"device busy: {report['busy_s']/n*1e3:.2f} ms/step"
        if "busy_frac_of_steps" in report:
            line += f" ({100*report['busy_frac_of_steps']:.1f}% of step span)"
        out.append(line)
    if report.get("categories"):
        out.append("\nby op category (ms/step, % of busy):")
        for r in report["categories"][:top]:
            out.append(
                f"  {r['s_per_step']*1e3:8.2f}  {r['pct_of_busy']:5.1f}%  "
                f"{r['category']}"
            )
    if report.get("top_ops"):
        out.append(f"\ntop {top} ops (ms/step, % of busy):")
        for r in report["top_ops"][:top]:
            out.append(
                f"  {r['s_per_step']*1e3:8.2f}  {r['pct_of_busy']:5.1f}%  {r['op']}"
            )
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("profile_dir", help="the --profile-dir a workload wrote")
    p.add_argument("--device", default="TPU", help="device plane substring")
    p.add_argument("--top", type=int, default=12)
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)
    try:
        report = device_report(args.profile_dir, args.device)
    except (RuntimeError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except Exception as e:  # corrupt/truncated trace (protobuf DecodeError)
        print(f"error: unreadable trace: {e!r}", file=sys.stderr)
        return 1
    if report is None:
        print(
            f"error: no '{args.device}' device plane in the trace "
            "(try --device CPU)",
            file=sys.stderr,
        )
        return 1
    if args.as_json:
        # Trim the unbounded op table for machine consumers too.
        report["top_ops"] = report.get("top_ops", [])[: args.top]
        print(json.dumps(report, indent=2))
    else:
        print(format_report(report, args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
