"""The ``tpujob`` CLI — the kubectl+CRD surface of the reference.

Reference mapping (SURVEY.md §7 architecture sketch):

- ``kubectl apply -f job.yaml``   → ``tpujob run job.yaml`` (foreground
  supervise-to-completion) or ``tpujob submit job.yaml`` (queue for a
  running ``tpujob supervisor`` daemon)
- ``kubectl get pytorchjobs``     → ``tpujob get``
- ``kubectl describe pytorchjob`` → ``tpujob describe NAME`` (spec, status,
  Events — the reference's user-facing observability surface)
- ``kubectl logs``                → ``tpujob logs NAME``
- ``kubectl delete``              → ``tpujob delete NAME``
- operator flags (--namespace, --enable-gang-scheduling, --threadiness,
  --monitoring-port; SURVEY.md §2 "Entrypoint/CLI") → supervisor flags
  (--state-dir, --no-gang, --max-slots, metrics file)

Usage: ``python -m pytorch_operator_tpu.client.cli <command> ...``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path
from typing import Optional

from ..api import (
    ConditionType,
    ValidationError,
    load_job,
    set_defaults,
    validate,
)
from ..controller.store import (
    JobStore,
    fs_to_key,
    job_key,
    key_to_fs,
    purge_job_artifacts,
)
from ..controller.supervisor import (
    Supervisor,
    default_state_dir,
    job_timeline,
    schedule_to_first_step_latency,
)


def _state_dir(args) -> Path:
    return Path(args.state_dir) if args.state_dir else default_state_dir()


def _resolve_key(args) -> str:
    return f"{args.namespace}/{args.name}"


def _phase_of(job) -> str:
    for ct in (
        ConditionType.SUCCEEDED,
        ConditionType.FAILED,
        ConditionType.SUSPENDED,
        ConditionType.RESTARTING,
        ConditionType.RUNNING,
        ConditionType.CREATED,
    ):
        if job.has_condition(ct):
            return ct.value
    return "Pending"


def _age(ts: Optional[float]) -> str:
    if ts is None:
        return "-"
    s = int(time.time() - ts)
    if s < 120:
        return f"{s}s"
    if s < 7200:
        return f"{s // 60}m"
    return f"{s // 3600}h"


def _load_fault_plan(path):
    """Parse a fault-plan file, or exit with a spec-style error."""
    from pytorch_operator_tpu.faults import FaultPlan

    try:
        return FaultPlan.load(path)
    except (OSError, ValueError, KeyError, TypeError) as e:
        raise SystemExit(f"error: invalid fault plan {path}: {e}")


def _arm_cli_tracing(args) -> None:
    """``--trace``: arm the flight recorder for this process AND every
    replica it spawns — the supervisor's spans land in
    ``<state>/trace/``, each job's in ``<state>/trace/<ns>_<job>/``
    (the reconciler injects the per-job dir whenever process tracing is
    on). ``tpujob trace <job>`` merges them afterward."""
    if not getattr(args, "trace", False):
        return
    import os

    from pytorch_operator_tpu import obs

    os.environ["TPUJOB_TRACE_DIR"] = str(_state_dir(args) / "trace")
    obs.reset_tracer()  # re-read the env this process already cached


def _run_foreground(args, fault_plan=None, chaos: bool = False) -> int:
    """Shared supervise-to-completion loop behind ``run`` and ``chaos``.

    With a fault plan armed, controller-side faults fire in-process and
    worker-side faults ride into replicas via the runner's env
    threading; ``chaos`` additionally prints a timestamp-free replay
    summary — the artifact two runs of one plan+seed must reproduce
    byte-identically (the determinism contract tests pin)."""
    from pytorch_operator_tpu import faults

    job = load_job(args.file)
    if fault_plan is not None:
        # Plan lint: a fault aimed at a replica this spec can never run
        # silently never fires — warn up front (the run still proceeds;
        # the plan may be shared across differently-shaped jobs).
        from pytorch_operator_tpu.faults.plan import validate_against_job

        set_defaults(job)
        for warning in validate_against_job(fault_plan, job):
            print(f"warning: fault plan: {warning}", file=sys.stderr)
        faults.arm(fault_plan)
    _arm_cli_tracing(args)
    sup = Supervisor(
        state_dir=_state_dir(args),
        gang_enabled=not args.no_gang,
        max_slots=args.max_slots,
    )
    try:
        try:
            key = sup.submit(job)
        except ValidationError as e:
            print("error: invalid TPUJob spec:", file=sys.stderr)
            for msg in e.errors:
                print(f"  - {msg}", file=sys.stderr)
            return 2
        print(f"tpujob {key} submitted")
        if fault_plan is not None:
            sup.events.normal(
                key, "ChaosPlanArmed",
                f"fault plan armed: {fault_plan.summary()}",
            )
        printed = 0
        # monotonic: the foreground wait budget must not move with NTP.
        deadline = (
            None if args.timeout is None else time.monotonic() + args.timeout
        )
        while True:
            if fault_plan is not None:
                # The daemon's sync_once runs this hook; the foreground
                # loop syncs one key directly, so drive it here.
                sup._inject_pass_faults()
            # Sync only the submitted job — other persisted jobs in this
            # state dir may be owned by a running daemon.
            sup.reconciler.sync(key)
            events = sup.events.for_job(key)
            for ev in events[printed:]:
                print(f"  [{ev.type}] {ev.reason}: {ev.message}")
            printed = len(events)
            j = sup.get(key)
            if j is None or j.is_finished():
                break
            if deadline is not None and time.monotonic() > deadline:
                print(f"error: timeout after {args.timeout}s", file=sys.stderr)
                sup.delete_job(key)
                return 3
            time.sleep(sup.poll_interval)
        # No settle pass needed: within one sync, runner.sync observes
        # the exit BEFORE the status scan runs, so every record a
        # replica wrote is folded into events by the pass that
        # completes the job.
    finally:
        sup.shutdown()
        if fault_plan is not None:
            faults.disarm()
        if getattr(args, "trace", False):
            from pytorch_operator_tpu import obs

            rec = obs.tracer()
            if rec is not None:
                rec.flush()  # buffered supervisor spans, visible now
    if j is None:
        print("job was garbage-collected")
        return 0
    phase = _phase_of(j)
    lat = schedule_to_first_step_latency(j)
    if lat is not None:
        print(f"schedule-to-first-step latency: {lat:.3f}s")
    print(f"tpujob {key}: {phase} (restarts={j.status.restart_count})")
    if chaos:
        # The deterministic replay artifact: event sequence (no
        # timestamps, no counts), final phase, restart count.
        seq = " -> ".join(f"{ev.type}:{ev.reason}" for ev in events)
        print(f"chaos events: {seq}")
        print(f"chaos final: {phase} restarts={j.status.restart_count}")
    return 0 if j.is_succeeded() else 1


def cmd_run(args) -> int:
    plan = None
    if getattr(args, "fault_plan", None):
        plan = _load_fault_plan(args.fault_plan)
    return _run_foreground(args, fault_plan=plan)


def cmd_chaos(args) -> int:
    """Replay a declared failure scenario end-to-end: arm the plan, run
    the job under it, print the deterministic replay summary. With
    ``--record``, the positional argument is a JOB NAME instead of a
    spec file: reconstruct a replayable plan from that job's recorded
    failure artifacts (faults/record.py) and write it out — a watched
    incident becomes a committed regression test."""
    if getattr(args, "record", False):
        return _cmd_chaos_record(args)
    if not args.plan:
        print("error: --plan is required (or use --record NAME)",
              file=sys.stderr)
        return 2
    return _run_foreground(
        args, fault_plan=_load_fault_plan(args.plan), chaos=True
    )


def _cmd_chaos_record(args) -> int:
    from pytorch_operator_tpu.faults.record import plan_from_recording

    state = _state_dir(args)
    key = f"{args.namespace}/{args.file}"
    plan = plan_from_recording(state, key)
    if not plan.faults:
        print(
            f"error: no replayable failure found in the recording of "
            f"tpujob {key} (no hung-world kill, crash exit, or "
            "checkpoint-save failure on record)",
            file=sys.stderr,
        )
        return 1
    body = json.dumps(plan.to_dict(), indent=2, sort_keys=True) + "\n"
    if args.out:
        Path(args.out).write_text(body)
        print(
            f"wrote {args.out}: {plan.summary()}\n"
            f"replay with: tpujob chaos <job.yaml> --plan {args.out}"
        )
    else:
        print(body, end="")
    return 0


def _load_validated_job(path):
    """Load + default + validate a spec file, or None after printing the
    errors (shared by submit/apply)."""
    job = load_job(path)
    set_defaults(job)
    try:
        validate(job)
    except ValidationError as e:
        print("error: invalid TPUJob spec:", file=sys.stderr)
        for msg in e.errors:
            print(f"  - {msg}", file=sys.stderr)
        return None
    return job


def cmd_submit(args) -> int:
    job = _load_validated_job(args.file)
    if job is None:
        return 2
    store = JobStore(persist_dir=_state_dir(args) / "jobs")
    try:
        key = store.add(job)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(f"tpujob {key} submitted (run 'tpujob supervisor' to reconcile)")
    return 0


def _parse_queue_slots(spec):
    """``'default=4,batch=2'`` → ``{'default': 4, 'batch': 2}``."""
    if not spec:
        return None
    out = {}
    for part in spec.split(","):
        name, _, cap = part.partition("=")
        name = name.strip()
        if not name or not cap:
            raise SystemExit(f"--queue-slots: malformed entry {part!r}")
        try:
            n = int(cap)
        except ValueError:
            raise SystemExit(f"--queue-slots: non-integer cap in {part!r}")
        if n <= 0:
            raise SystemExit(f"--queue-slots: cap must be positive in {part!r}")
        if name in out:
            raise SystemExit(f"--queue-slots: duplicate queue {name!r}")
        out[name] = n
    return out


def cmd_supervisor(args) -> int:
    # SIGTERM (systemd stop / kubelet-style termination) takes the same
    # clean shutdown path as Ctrl-C: kill replicas, release the lease.
    # One-shot: a re-delivered SIGTERM during the cleanup itself must not
    # abort it (that would orphan replicas and hold the lease).
    import signal

    def _sigterm(signum, frame):
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _sigterm)
    _arm_cli_tracing(args)
    shards = getattr(args, "shards", None)
    sync_workers_max = getattr(args, "sync_workers_max", None)
    if sync_workers_max is None and os.environ.get("TPUJOB_SYNC_WORKERS_MAX"):
        try:
            sync_workers_max = int(os.environ["TPUJOB_SYNC_WORKERS_MAX"])
        except ValueError:
            pass
    sup = Supervisor(
        state_dir=_state_dir(args),
        gang_enabled=not args.no_gang,
        max_slots=args.max_slots,
        # Sharding replaces leader election: N ACTIVE reconcilers, one
        # per shard set, is the whole point.
        leader_elect=not args.no_leader_elect and not shards,
        queue_slots=_parse_queue_slots(getattr(args, "queue_slots", None)),
        preempt=getattr(args, "preempt", False),
        standby=getattr(args, "standby", 0) or 0,
        shards=shards,
        supervisor_id=getattr(args, "supervisor_id", None),
        lease_ttl=getattr(args, "lease_ttl", 5.0),
        sync_workers_max=sync_workers_max,
    )
    if shards:
        print(
            f"tpujob supervisor: sharded control plane — identity "
            f"{sup.identity}, {shards} shards, lease ttl "
            f"{getattr(args, 'lease_ttl', 5.0):g}s"
        )
    # Monitoring comes up BEFORE the lease wait: a standby must answer
    # /healthz while blocked (it reports is_leader=false), or liveness
    # probes would kill the hot spare.
    monitoring = None

    def start_monitoring() -> bool:
        nonlocal monitoring
        from ..controller.monitoring import MonitoringServer, supervisor_health
        from ..obs import top as obs_top

        monitoring = MonitoringServer(
            render_metrics=sup.metrics.render_text,
            health=lambda: supervisor_health(sup),
            port=args.monitoring_port,
            # `curl :port/top` — the tpujob-top table over HTTP;
            # `curl :port/alerts` — the live health engine's state
            # (in-memory: the watch is THE source, no log re-read).
            text_routes={
                "/top": lambda: obs_top.render(sup.state_dir) + "\n",
                "/alerts": lambda: sup.watch.render_text() + "\n",
            },
        )
        try:
            print(f"tpujob supervisor: monitoring on 127.0.0.1:{monitoring.start()}")
            return True
        except OSError as e:
            monitoring = None
            print(
                f"warning: cannot bind monitoring port {args.monitoring_port}: {e}",
                file=sys.stderr,
            )
            return False

    if args.monitoring_port is not None and not start_monitoring():
        # A fixed port is typically held by the current leader on this
        # host. A standby must still reach the lease wait (the hot-spare
        # property), so only a non-HA daemon treats this as fatal.
        if sup.lease is None:
            sup.shutdown()
            return 2
        print("tpujob supervisor: will retry monitoring bind after lease", flush=True)
    try:
        if sup.lease is not None and not sup.lease.acquire(blocking=False):
            holder = sup.lease.holder()
            print(
                f"tpujob supervisor: standby — lease held by {holder}; waiting",
                flush=True,
            )
            sup.lease.acquire()  # blocks until the leader exits or crashes
            print("tpujob supervisor: acquired leader lease", flush=True)
            # Takeover: adopt the worlds the dead leader left running —
            # this runner loaded (empty) records at startup, before the
            # leader launched anything.
            sup.runner.rescan()
        if args.monitoring_port is not None and monitoring is None:
            # The dead leader's exit freed its port; best effort rebind.
            start_monitoring()
        print(f"tpujob supervisor: state dir {sup.state_dir}, "
              f"gang={'on' if not args.no_gang else 'off'}")
        while True:
            try:
                sup.store.rescan()
                sup.process_deletion_markers()
                sup.process_scale_markers()
                sup.process_suspend_markers()
                sup.process_apply_markers()
                sup.sync_once()
                # Retire reconcile locks of deleted jobs (delete_job
                # can't: it may run nested under a held lock).
                sup.reconciler.gc_key_locks(
                    {job_key(j) for j in sup.store.list()}
                )
                sup.write_metrics_file()
            except Exception:
                # Controller semantics (the reference's workqueue requeues
                # on sync error): a transient failure in one pass — disk
                # hiccup, one bad job record — must not crash the daemon,
                # whose shutdown would tear down every live training
                # world it spawned. Log and keep reconciling.
                import traceback

                traceback.print_exc()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        print("supervisor: shutting down")
        return 0
    finally:
        if monitoring is not None:
            monitoring.stop()
        sup.shutdown()


def cmd_get(args) -> int:
    if getattr(args, "watch", False):
        return _get_watch(args)
    return _get_once(args)


def _get_watch(args) -> int:
    """kubectl get -w analog: re-render whenever a watched job's STATE
    changes (poll the persisted store — it IS the watch surface; the
    reconciler writes every transition through it). Change detection
    runs on a state fingerprint, NOT the rendered text: the AGE column
    ticks every second and must not trigger re-renders. ``--json``
    streams bare snapshots with no separator (kubectl -w -o json)."""

    jobs_dir = _state_dir(args) / "jobs"
    mtimes: dict = {}

    def refresh(store) -> None:
        # Read-only observer: the transitions being watched are written
        # by the owning supervisor process, so list()'s in-process cache
        # must be refreshed from disk — but only for files whose mtime
        # actually moved (a flat rescan+reload would parse every job's
        # JSON twice per 0.5s poll forever).
        nonlocal mtimes
        current: dict = {}
        for p in jobs_dir.glob("*.json"):
            try:
                st = p.stat()
                # (mtime_ns, size): on filesystems with coarse mtime
                # granularity two writes can land in one tick, and a
                # final transition written in the same tick as the
                # previous write would otherwise stay invisible forever.
                current[p.name] = (st.st_mtime_ns, st.st_size)
            except OSError:
                pass  # deleted mid-scan
        if current == mtimes:
            return
        store.rescan()  # picks up newly submitted jobs
        for name in set(mtimes) | set(current):
            if mtimes.get(name) != current.get(name):
                store.reload(fs_to_key(name[: -len(".json")]))
        mtimes = current

    def fingerprint(store) -> list:
        refresh(store)
        jobs = store.list()
        if args.name:
            jobs = [
                j for j in jobs
                if j.metadata.name == args.name
                and j.metadata.namespace == args.namespace
            ]
        return sorted(
            (
                job_key(j),
                _phase_of(j),
                j.status.restart_count,
                j.spec.run_policy.scheduling_policy.queue,
                j.spec.run_policy.scheduling_policy.priority,
            )
            for j in jobs
        )

    store = JobStore(persist_dir=_state_dir(args) / "jobs")
    last = None
    try:
        while True:
            fp = fingerprint(store)
            if fp != last:
                if last is not None and not getattr(args, "json", False):
                    print("---")
                rc = _get_once(args, missing_ok=True, store=store)
                if rc != 0:
                    return rc
                sys.stdout.flush()
                last = fp
            time.sleep(0.5)
    except KeyboardInterrupt:
        return 0


def _get_once(args, missing_ok: bool = False, store=None) -> int:
    if store is None:
        store = JobStore(persist_dir=_state_dir(args) / "jobs")
    jobs = store.list()
    if args.name:
        jobs = [j for j in jobs if j.metadata.name == args.name
                and j.metadata.namespace == args.namespace]
        if not jobs and not missing_ok:
            print(f"error: tpujob {_resolve_key(args)} not found", file=sys.stderr)
            return 1
    if getattr(args, "json", False):
        # kubectl -o json analog: the full stored objects, parseable.
        out = [j.to_dict() for j in sorted(
            jobs, key=lambda j: j.metadata.creation_timestamp or 0
        )]
        print(json.dumps(out[0] if args.name and len(out) == 1 else out, indent=2))
        return 0
    # QUEUE/PRIORITY columns appear only when some job sets them — the
    # default listing stays as terse as kubectl's.
    show_sched = any(
        j.spec.run_policy.scheduling_policy.queue
        or j.spec.run_policy.scheduling_policy.priority
        for j in jobs
    )
    header = ("NAME", "NAMESPACE", "STATE", "RESTARTS", "AGE")
    if show_sched:
        header += ("QUEUE", "PRIORITY")
    rows = [header]
    for j in sorted(jobs, key=lambda j: j.metadata.creation_timestamp or 0):
        row = (
            j.metadata.name,
            j.metadata.namespace,
            _phase_of(j),
            str(j.status.restart_count),
            _age(j.metadata.creation_timestamp),
        )
        if show_sched:
            sp = j.spec.run_policy.scheduling_policy
            row += (sp.queue or "default", str(sp.priority))
        rows.append(row)
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return 0


def _render_request_waterfall(doc: dict, rid: str) -> Optional[str]:
    """Clock-aligned text waterfall for ONE request: every serve-path
    span whose args carry this rid (enqueue → claim → dispatch →
    ring/spool transit → slot wait → decode → respond → publish),
    offsets relative to the first hop, a proportional bar per hop, and
    the emitting process named from the trace metadata. None when the
    merged doc has no spans for the rid."""
    pid_names = {
        e.get("pid"): (e.get("args") or {}).get("name", "")
        for e in doc.get("traceEvents", [])
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    hops = [
        e
        for e in doc.get("traceEvents", [])
        if e.get("ph") == "X" and (e.get("args") or {}).get("rid") == rid
    ]
    if not hops:
        return None
    hops.sort(key=lambda e: (e.get("ts", 0), e.get("name", "")))
    t0 = hops[0].get("ts", 0)
    t_end = max(e.get("ts", 0) + e.get("dur", 0) for e in hops)
    total_us = max(t_end - t0, 1)
    width = 32
    corrected = any(
        e.get("ph") == "M" and e.get("name") == "clock_sync_correction"
        for e in doc.get("traceEvents", [])
    )
    lines = [
        f"request {rid} — {len(hops)} hop(s), "
        f"{total_us / 1e3:.3f}ms end to end"
        + (", clock-synced" if corrected else "")
    ]
    for e in hops:
        off = e.get("ts", 0) - t0
        dur = e.get("dur", 0)
        lead = min(int(width * off / total_us), width - 1)
        blen = max(1, min(int(round(width * dur / total_us)), width - lead))
        bar = " " * lead + "#" * blen
        extras = " ".join(
            f"{k}={v}"
            for k, v in sorted((e.get("args") or {}).items())
            if k != "rid"
        )
        who = pid_names.get(e.get("pid"), "") or "?"
        lines.append(
            f"  {off / 1e3:9.3f}ms  {e.get('name', '?'):<13} "
            f"{dur / 1e3:9.3f}ms  |{bar:<{width}}|  {who}"
            + (f"  {extras}" if extras else "")
        )
    return "\n".join(lines)


def cmd_trace(args) -> int:
    """Merge the supervisor's and every replica's span files into one
    Chrome-trace/Perfetto JSON for this job (obs/trace.py), with
    per-replica clock corrections from the heartbeat-matching estimator
    (obs/clock.py) so cross-host timelines come out causally ordered.
    Open the output at https://ui.perfetto.dev or chrome://tracing."""
    from pytorch_operator_tpu.obs import merge_trace_files
    from pytorch_operator_tpu.obs.clock import (
        estimate_job_offsets,
        offsets_for_trace_files,
    )
    from pytorch_operator_tpu.obs.trace import span_files

    state = _state_dir(args)
    key = _resolve_key(args)
    trace_root = state / "trace"
    # Replica spans live in the per-job dir the reconciler injected;
    # supervisor spans (pass phases, per-job reconciles, store I/O)
    # directly under the root. Rotated ring generations included.
    paths = span_files(trace_root / key_to_fs(key)) + span_files(trace_root)
    if not paths:
        print(
            f"error: no span files for tpujob {key} under {trace_root} — "
            "run with --trace or set spec.observability.trace: true",
            file=sys.stderr,
        )
        return 1
    # Clock alignment: per-replica offsets estimated from the job's
    # heartbeat observation log (empty → no corrections, the single-host
    # behavior). --no-clock-sync keeps raw per-host timestamps.
    offsets = {}
    if not getattr(args, "no_clock_sync", False):
        estimates = estimate_job_offsets(state, key)
        offsets = offsets_for_trace_files(paths, estimates)
        for p, off in sorted(offsets.items()):
            print(
                f"clock_sync: {Path(p).name} corrected by {off:+.6f}s",
                file=sys.stderr,
            )
    doc = merge_trace_files(paths, clock_offsets=offsets or None)
    n_spans = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    rid = getattr(args, "request", None)
    if rid:
        # Per-request waterfall: the serve-path hop spans for one rid,
        # already on the aligned clock, rendered as text (the full
        # Perfetto doc still lands in --out when asked).
        text = _render_request_waterfall(doc, rid)
        if text is None:
            print(
                f"error: no spans carry request id {rid!r} "
                f"({n_spans} spans searched) — was the request served "
                "with tracing on?",
                file=sys.stderr,
            )
            return 1
        print(text)
        if args.out:
            Path(args.out).write_text(json.dumps(doc) + "\n")
            print(f"\nwrote {args.out}")
        return 0
    if args.out:
        Path(args.out).write_text(json.dumps(doc) + "\n")
        print(
            f"wrote {args.out}: {n_spans} spans from {len(paths)} file(s) "
            "(open in https://ui.perfetto.dev)"
        )
    else:
        print(json.dumps(doc))
    return 0


def cmd_why(args) -> int:
    """The postmortem engine (obs/analyze.py): reconstruct the job's
    causal timeline from recorded artifacts — clock-aligned heartbeats,
    events, spans — and run the detector pass (step-time regression,
    feed-stall dominance, checkpoint lag, heartbeat silence, straggler).
    Strictly offline: reads the state dir, touches no live process."""
    from pytorch_operator_tpu.obs import analyze as obs_analyze

    state = _state_dir(args)
    key = _resolve_key(args)
    report = obs_analyze.analyze(state, key, window_s=args.window)
    if (
        not report["replicas"]
        and not report["events"]
        and report["phase"] is None
    ):
        print(
            f"error: no recorded artifacts for tpujob {key} under {state} "
            "(no status records, events, or job object)",
            file=sys.stderr,
        )
        return 1
    if args.out:
        Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    if getattr(args, "json", False):
        print(json.dumps(report, indent=2))
    else:
        print(obs_analyze.render_report(report))
        if args.out:
            print(f"\nwrote {args.out}")
    return 0


def _follow_alerts(args, state: Path, key: str) -> int:
    """``alerts --follow``: live-tail one job's alert transition log
    (like ``tpujob events -f``): incremental offset reads, each
    firing/resolved transition printed once, rotation-tolerant (a
    shrunken file restarts from zero). Ends when the job record
    finishes or disappears, after a final drain."""
    from pytorch_operator_tpu.obs.watch import format_alert_record, job_alert_log

    path = job_alert_log(state, key)
    store = JobStore(persist_dir=state / "jobs")
    offset = 0

    def drain() -> None:
        nonlocal offset
        try:
            size = path.stat().st_size
        except OSError:
            return
        if size < offset:
            offset = 0  # rotated under us: replay the fresh generation
        if size == offset:
            return
        try:
            with path.open("rb") as f:
                f.seek(offset)
                chunk = f.read()
        except OSError:
            return
        last_nl = chunk.rfind(b"\n")
        if last_nl < 0:
            return  # torn line: wait for the writer to finish it
        offset += last_nl + 1
        for line in chunk[: last_nl + 1].splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "rule" in rec:
                print(format_alert_record(rec), flush=True)

    try:
        while True:
            job = store.reload(key)
            finished = job is None or job.is_finished()
            drain()  # after the finish check: the last pass drains fully
            if finished:
                return 0
            time.sleep(0.5)
    except KeyboardInterrupt:
        return 0


def cmd_alerts(args) -> int:
    """The live health engine's alert surface (obs/watch.py): current
    state per (job, rule, replica) folded from the per-job alert logs —
    file-based, so it answers with or without a daemon. ``--follow``
    live-tails one job's transitions; ``--json`` emits the raw
    records."""
    from pytorch_operator_tpu.obs import watch as obs_watch

    state = _state_dir(args)
    if getattr(args, "follow", False):
        if not args.name:
            print("error: --follow requires a job NAME", file=sys.stderr)
            return 2
        return _follow_alerts(args, state, _resolve_key(args))
    key = _resolve_key(args) if args.name else None
    if getattr(args, "json", False):
        keys = [key] if key else obs_watch.list_alert_jobs(state)
        records = [
            rec for k in keys for rec in obs_watch.load_alert_log(state, k)
        ]
        records.sort(key=lambda r: float(r.get("ts", 0.0)))
        print(json.dumps(records, indent=2))
        return 0
    rows = obs_watch.gather_alert_rows(state, key)
    print(obs_watch.render_alert_table(rows))
    return 0


def _follow_remediations(args, state: Path, key: str) -> int:
    """``remediations --follow``: live-tail one job's remediation audit
    log (same discipline as ``alerts -f``): incremental offset reads,
    each alert→decision→action record printed once, rotation-tolerant
    (a shrunken file restarts from zero). Ends when the job record
    finishes or disappears, after a final drain."""
    from pytorch_operator_tpu.controller.remediation import (
        format_remediation_record,
        job_remediation_log,
    )

    path = job_remediation_log(state, key)
    store = JobStore(persist_dir=state / "jobs")
    offset = 0

    def drain() -> None:
        nonlocal offset
        try:
            size = path.stat().st_size
        except OSError:
            return
        if size < offset:
            offset = 0  # rotated under us: replay the fresh generation
        if size == offset:
            return
        try:
            with path.open("rb") as f:
                f.seek(offset)
                chunk = f.read()
        except OSError:
            return
        last_nl = chunk.rfind(b"\n")
        if last_nl < 0:
            return  # torn line: wait for the writer to finish it
        offset += last_nl + 1
        for line in chunk[: last_nl + 1].splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "action" in rec:
                print(format_remediation_record(rec), flush=True)

    try:
        while True:
            job = store.reload(key)
            finished = job is None or job.is_finished()
            drain()  # after the finish check: the last pass drains fully
            if finished:
                return 0
            time.sleep(0.5)
    except KeyboardInterrupt:
        return 0


def cmd_remediations(args) -> int:
    """The remediation engine's audit surface
    (controller/remediation.py): every alert→decision→action→outcome
    the closed loop recorded, folded from the per-job audit logs —
    file-based, so it answers with or without a daemon. ``--follow``
    live-tails one job's actions; ``--json`` emits the raw records."""
    from pytorch_operator_tpu.controller import remediation as rem

    state = _state_dir(args)
    if getattr(args, "follow", False):
        if not args.name:
            print("error: --follow requires a job NAME", file=sys.stderr)
            return 2
        return _follow_remediations(args, state, _resolve_key(args))
    key = _resolve_key(args) if args.name else None
    keys = [key] if key else rem.list_remediation_jobs(state)
    records = [r for k in keys for r in rem.load_remediation_log(state, k)]
    records.sort(key=lambda r: float(r.get("ts", 0.0)))
    if getattr(args, "json", False):
        print(json.dumps(records, indent=2))
        return 0
    if not records:
        print("no remediation actions recorded.")
        return 0
    for rec in records:
        print(rem.format_remediation_record(rec))
    return 0


def cmd_top(args) -> int:
    """Live one-screen fleet table (obs/top.py): per-job step, steps/s,
    p50/p99 step time, checkpoint lag, feed stall — from the status-dir
    heartbeats plus the daemon's metrics.prom when present.

    On a TTY the repaint loop takes keys (still no curses): ``s`` cycles
    the sort column, ``r`` flips direction, ``/`` starts a job-name
    substring filter (Enter/Esc ends it), ``c`` clears the filter,
    ``q`` quits."""
    from pytorch_operator_tpu.obs import top as obs_top

    state = _state_dir(args)
    if args.once:
        print(obs_top.render(state))
        return 0

    if getattr(args, "diff", False):
        # Delta mode: print the full table once, then only what CHANGED
        # each interval (step-rate moves, new firing alerts, jobs
        # appearing/finishing) — a scrolling incident log instead of a
        # repaint, so nothing scrolls away unseen.
        prev = None
        try:
            while True:
                rows = obs_top.gather_rows(state)
                if prev is None:
                    print(obs_top.render_table(rows))
                else:
                    for line in obs_top.diff_rows(prev, rows):
                        print(line)
                sys.stdout.flush()
                prev = rows
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0

    sort_idx = None  # index into obs_top.COLUMNS; None = default order
    reverse = True
    filt = ""
    filter_mode = False

    def paint(interactive: bool) -> None:
        key = None if sort_idx is None else obs_top.COLUMNS[sort_idx][1]
        body = obs_top.render(
            state, sort_key=key, reverse=reverse, filter_str=filt or None,
            color=interactive,  # firing-alert rows highlight on a TTY
        )
        if interactive:
            hint = (
                f"filter> {filt}▏  (Enter=apply, Esc=cancel)"
                if filter_mode
                else "keys: s=sort col  r=reverse  /=filter  c=clear  q=quit"
            )
            body += "\n\n" + hint
        # ANSI clear + home — a poor man's curses, dependency-free.
        sys.stdout.write("\x1b[2J\x1b[H" + body + "\n")
        sys.stdout.flush()

    interactive = sys.stdin.isatty()
    if not interactive:
        # Piped/headless: the plain repaint loop (previous behavior).
        try:
            while True:
                paint(False)
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0

    import os
    import select
    import termios
    import tty

    fd = sys.stdin.fileno()
    saved = termios.tcgetattr(fd)
    try:
        tty.setcbreak(fd)
        deadline = 0.0
        while True:
            # monotonic: repaint pacing is pure interval math; an NTP
            # step would freeze or spin the TUI.
            now = time.monotonic()
            if now >= deadline:
                paint(True)
                deadline = now + args.interval
            ready, _, _ = select.select([sys.stdin], [], [], deadline - now)
            if not ready:
                continue
            ch = os.read(fd, 1).decode(errors="replace")
            if filter_mode:
                if ch in ("\r", "\n"):
                    filter_mode = False
                elif ch == "\x1b":  # Esc cancels the filter being typed
                    filter_mode, filt = False, ""
                elif ch in ("\x7f", "\b"):
                    filt = filt[:-1]
                elif ch.isprintable():
                    filt += ch
            elif ch == "q":
                sys.stdout.write("\n")
                return 0
            elif ch == "s":
                sort_idx = 0 if sort_idx is None else sort_idx + 1
                if sort_idx >= len(obs_top.COLUMNS):
                    sort_idx = None
            elif ch == "r":
                reverse = not reverse
            elif ch == "/":
                filter_mode, filt = True, ""
            elif ch == "c":
                filt = ""
            deadline = 0.0  # immediate repaint on any key
    except KeyboardInterrupt:
        return 0
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, saved)


def _follow_events(args, state: Path, key: str) -> int:
    """``events --follow``: tail one job's event sink, aggregation-aware
    — the sink appends cumulative-count update records for a repeating
    event, so the follower re-merges the file each poll
    (load_merged_events) and re-prints a record whose count grew
    (crash-loop debugging without re-running describe). Ends when the
    job record finishes or disappears, after a final drain."""
    from pytorch_operator_tpu.controller.events import load_merged_events

    path = state / "events" / (key_to_fs(key) + ".events.jsonl")
    store = JobStore(persist_dir=state / "jobs")
    shown: list = []  # (type, reason, message, count) already printed

    def fmt(rec) -> str:
        count = int(rec.get("count", 1) or 1)
        tail = f" (x{count})" if count > 1 else ""
        return (
            f"[{rec.get('type', '?')}] {rec.get('reason', '?')}: "
            f"{rec.get('message', '')}{tail}"
        )

    def drain() -> None:
        merged = load_merged_events(path)
        for i, rec in enumerate(merged):
            ident = (
                rec.get("type"), rec.get("reason"), rec.get("message"),
                int(rec.get("count", 1) or 1),
            )
            if i < len(shown):
                if shown[i] != ident:
                    # Same position, higher count: the aggregated event
                    # repeated — reprint with the live count.
                    print(fmt(rec), flush=True)
                    shown[i] = ident
            else:
                print(fmt(rec), flush=True)
                shown.append(ident)

    try:
        while True:
            job = store.reload(key)
            finished = job is None or job.is_finished()
            drain()  # after the finish check: the last pass drains fully
            if finished:
                return 0
            time.sleep(0.5)
    except KeyboardInterrupt:
        return 0


def cmd_events(args) -> int:
    """kubectl get events analog: merged per-job event logs, oldest first,
    bounded by --tail. With a NAME, only that job's; ``--follow`` tails
    the job's sink live."""
    from pytorch_operator_tpu.controller.events import load_merged_events

    state = _state_dir(args)
    if getattr(args, "follow", False):
        if not args.name:
            print("error: --follow requires a job NAME", file=sys.stderr)
            return 2
        return _follow_events(args, state, _resolve_key(args))
    ev_dir = _state_dir(args) / "events"
    records = []
    if ev_dir.is_dir():
        for p in sorted(ev_dir.glob("*.events.jsonl")):
            obj = fs_to_key(p.name[: -len(".events.jsonl")])
            if args.name and obj != _resolve_key(args):
                continue
            # A repeating event appends updated records (cumulative
            # count); the loader collapses runs so one crash-loop warning
            # shows once with its live count, not once per flush.
            for rec in load_merged_events(p):
                records.append((float(rec.get("timestamp", 0.0)), obj, rec))
    records.sort(key=lambda r: r[0])
    if args.tail > 0:
        records = records[-args.tail :]
    if not records:
        print("no events")
        return 0
    rows = [("AGE", "TYPE", "OBJECT", "REASON", "MESSAGE")]
    for ts, obj, rec in records:
        count = int(rec.get("count", 1) or 1)
        msg = str(rec.get("message", ""))
        if count > 1:
            msg += f" (x{count})"
        rows.append(
            (
                _age(ts),
                str(rec.get("type", "?")),
                obj,
                str(rec.get("reason", "?")),
                msg,
            )
        )
    widths = [max(len(r[i]) for r in rows) for i in range(4)]
    for r in rows:
        lead = "  ".join(c.ljust(w) for c, w in zip(r[:4], widths))
        print(f"{lead}  {r[4]}")
    return 0


def cmd_describe(args) -> int:
    state = _state_dir(args)
    store = JobStore(persist_dir=state / "jobs")
    key = _resolve_key(args)
    job = store.get(key)
    if job is None:
        print(f"error: tpujob {key} not found", file=sys.stderr)
        return 1
    if getattr(args, "json", False):
        print(json.dumps(job.to_dict(), indent=2))
        return 0
    print(f"Name:       {job.metadata.name}")
    print(f"Namespace:  {job.metadata.namespace}")
    print(f"UID:        {job.metadata.uid}")
    print(f"State:      {_phase_of(job)}")
    print(f"Restarts:   {job.status.restart_count}")
    if job.status.submit_time:
        print(f"Submitted:  {time.ctime(job.status.submit_time)}")
    if job.metadata.labels:
        print("Labels:     " + ", ".join(f"{k}={v}" for k, v in job.metadata.labels.items()))
    if job.metadata.annotations:
        print("Annotations:")
        for k, v in sorted(job.metadata.annotations.items()):
            print(f"  {k}: {v}")
    lat = schedule_to_first_step_latency(job)
    if lat is not None:
        print(f"Schedule-to-first-step: {lat:.3f}s")
    from pytorch_operator_tpu.controller.progress import (
        format_progress,
        job_status_dir,
        read_latest_progress,
    )

    rec = read_latest_progress(job_status_dir(state / "status", key))
    if rec is not None:
        # Live while the job runs; last-known afterward. Read straight
        # from the status files, so it works with or without a daemon.
        print("Training:")
        for line in format_progress(rec, time.time()):
            print(f"  {line}")
    spans = job_timeline(job)
    if spans:
        print("Timeline:")
        for name, seconds in spans:
            print(f"  {name:<28} {seconds:.3f}s")
    sp = job.spec.run_policy.scheduling_policy
    sched = [f"gang={'on' if sp.gang else 'off'}"]
    if sp.min_available is not None:
        sched.append(f"min_available={sp.min_available}")
    if sp.queue:
        sched.append(f"queue={sp.queue}")
    if sp.priority:
        sched.append(f"priority={sp.priority}")
    if job.spec.run_policy.suspend:
        sched.append("SUSPENDED")
    print("Scheduling: " + ", ".join(sched))
    print("Replicas:")
    for rtype, rs in job.spec.replica_specs.items():
        status = job.status.replica_statuses.get(rtype)
        line = f"  {rtype.value}: desired={rs.replicas}"
        if status:
            line += (
                f" active={status.active} succeeded={status.succeeded} "
                f"failed={status.failed}"
            )
        print(line)
    print("Conditions:")
    for c in job.status.conditions:
        print(
            f"  {c.type.value:<12} {str(c.status):<6} {c.reason:<24} {c.message}"
        )
    ev_path = state / "events" / (key_to_fs(key) + ".events.jsonl")
    print("Events:")
    from pytorch_operator_tpu.controller.events import load_merged_events

    merged = load_merged_events(ev_path)
    for ev in merged:
        tail = f" (x{ev['count']})" if int(ev.get("count", 1) or 1) > 1 else ""
        print(f"  [{ev.get('type', '?')}] {ev.get('reason', '?')}: {ev.get('message', '')}{tail}")
    if not merged:
        print("  <none>")
    return 0


def cmd_logs(args) -> int:
    state = _state_dir(args)
    key = _resolve_key(args)
    prefix = key_to_fs(key)
    log_dir = state / "logs"
    if args.replica:
        paths = [log_dir / f"{prefix}-{args.replica}.log"]
        if not paths[0].exists():
            print(f"error: no log for replica {args.replica} of {key}", file=sys.stderr)
            return 1
    else:
        paths = sorted(log_dir.glob(f"{prefix}-*.log"))
        if not paths:
            print(f"error: no logs found for tpujob {key}", file=sys.stderr)
            return 1
    if not args.follow:
        for p in paths:
            if len(paths) > 1:
                print(f"==> {p.name} <==")
            sys.stdout.write(p.read_text(errors="replace"))
        return 0

    # kubectl logs -f analog: one incremental read pass, repeated until the
    # job record is finished OR gone (deleted / TTL-GC'd mid-follow). The
    # finished check runs BEFORE the pass so the last pass drains output
    # written right up to the finish. New replicas appearing mid-follow
    # (restarts) are picked up by the glob.
    store = JobStore(persist_dir=state / "jobs")
    offsets: dict = {}

    def read_pass() -> None:
        for p in sorted(log_dir.glob(f"{prefix}-*.log")):
            if args.replica and not p.name.endswith(f"-{args.replica}.log"):
                continue
            off = offsets.get(p, 0)
            try:
                with p.open("rb") as f:
                    f.seek(off)
                    data = f.read()
            except OSError:
                continue  # purged under us — nothing more to print
            if data:
                sys.stdout.write(data.decode(errors="replace"))
                sys.stdout.flush()
                offsets[p] = off + len(data)

    try:
        while True:
            job = store.reload(key)
            finished = job is None or job.is_finished()
            read_pass()
            if finished:
                return 0
            time.sleep(0.5)
    except KeyboardInterrupt:
        return 0


def cmd_delete(args) -> int:
    state = _state_dir(args)
    key = _resolve_key(args)
    store = JobStore(persist_dir=state / "jobs")
    job = store.get(key)
    if job is None:
        print(f"error: tpujob {key} not found", file=sys.stderr)
        return 1
    # Cross-process delete: leave a marker a running supervisor will act on
    # (it owns the replica processes); also remove the stored object so the
    # job disappears from get/describe immediately.
    # The marker carries the purge request: a running supervisor purges
    # AFTER killing the replicas (else a live workload's next checkpoint
    # save would re-create the dir behind the purge). The immediate purge
    # below covers the daemon-less case (no replicas running).
    store.mark_deletion(key, purge=args.purge, uid=job.metadata.uid or "")
    store.delete(key)
    if args.purge:
        purge_job_artifacts(state, key)
    print(f"tpujob {key} deleted")
    return 0


def cmd_scale(args) -> int:
    """Elastic resize: validate against the stored spec, then leave a marker
    for the owning supervisor (it must re-rendezvous the live gang)."""
    state = _state_dir(args)
    key = _resolve_key(args)
    store = JobStore(persist_dir=state / "jobs")
    job = store.get(key)
    if job is None:
        print(f"error: tpujob {key} not found", file=sys.stderr)
        return 1
    ep = job.spec.elastic_policy
    if ep is None:
        print(f"error: tpujob {key} has no elastic_policy", file=sys.stderr)
        return 2
    if not (ep.min_replicas <= args.workers <= ep.max_replicas):
        print(
            f"error: workers={args.workers} outside "
            f"[{ep.min_replicas}, {ep.max_replicas}]",
            file=sys.stderr,
        )
        return 2
    store.mark_scale(key, args.workers)
    print(f"tpujob {key} scale to {args.workers} workers requested")
    return 0


def cmd_apply(args) -> int:
    """kubectl apply analog: create or update. A new job is stored
    directly; an update to an existing job is left as a marker for the
    owning supervisor (it may need to restart the world at the new
    shape)."""
    from ..controller.store import job_key as _job_key

    job = _load_validated_job(args.file)
    if job is None:
        return 2
    store = JobStore(persist_dir=_state_dir(args) / "jobs")
    key = _job_key(job)
    if store.get(key) is None:
        try:
            store.add(job)
        except ValueError:
            # Lost a create race with a concurrent apply — fall through to
            # the update path.
            store.mark_apply(key, job.to_dict())
            print(f"tpujob {key} update requested")
            return 0
        print(f"tpujob {key} created (run 'tpujob supervisor' to reconcile)")
    else:
        store.mark_apply(key, job.to_dict())
        print(f"tpujob {key} update requested")
    return 0


def _cmd_set_suspend(args, flag: bool) -> int:
    """Suspend/resume: leave a marker for the owning supervisor (it owns
    the replica processes, so it performs the teardown/relaunch)."""
    state = _state_dir(args)
    key = _resolve_key(args)
    store = JobStore(persist_dir=state / "jobs")
    job = store.get(key)
    if job is None:
        print(f"error: tpujob {key} not found", file=sys.stderr)
        return 1
    if job.is_finished():
        print(f"error: tpujob {key} already finished", file=sys.stderr)
        return 2
    store.mark_suspend(key, flag)
    print(f"tpujob {key} {'suspend' if flag else 'resume'} requested")
    return 0


def cmd_suspend(args) -> int:
    return _cmd_set_suspend(args, True)


def cmd_resume(args) -> int:
    return _cmd_set_suspend(args, False)


def cmd_metrics(args) -> int:
    # Unsharded daemons write metrics.prom; sharded ones write one
    # metrics-<identity>.prom each — print the union.
    paths = sorted(_state_dir(args).glob("metrics*.prom"))
    if not paths:
        print("no metrics recorded yet", file=sys.stderr)
        return 1
    for path in paths:
        if len(paths) > 1:
            sys.stdout.write(f"# ---- {path.name} ----\n")
        sys.stdout.write(path.read_text())
    return 0


def cmd_serve_request(args) -> int:
    """Submit a request to a serving job's spool and (optionally) wait
    for the response — the client half of the serving service
    (serving/spool.py; the serve workload is the engine half).

    ``--job`` targets a ``spec.serving`` job's FRONT spool (resolved
    from the supervisor state layout — the router fans the request out
    across replicas); ``--spool`` names a spool directory directly
    (single-engine serve jobs that picked their own path)."""
    from pathlib import Path

    from pytorch_operator_tpu.serving import Spool

    if (args.prompt is None) == (args.prompt_len is None):
        print(
            "exactly one of --prompt / --prompt-len is required",
            file=sys.stderr,
        )
        return 2
    if (args.spool is None) == (args.job is None):
        print(
            "exactly one of --spool / --job is required",
            file=sys.stderr,
        )
        return 2
    if args.job is not None:
        from pytorch_operator_tpu.controller.store import JobStore
        from pytorch_operator_tpu.serving.router import (
            front_spool_dir,
            serve_root_dir,
        )

        state = _state_dir(args)
        key = (
            args.job
            if "/" in args.job
            else f"{args.namespace}/{args.job}"
        )
        job = JobStore(persist_dir=state / "jobs").get(key)
        if job is None:
            print(f"error: tpujob {key} not found", file=sys.stderr)
            return 1
        if job.spec.serving is None:
            print(
                f"error: tpujob {key} has no spec.serving block — not a "
                "serving job (use --spool for raw spools)",
                file=sys.stderr,
            )
            return 2
        args.spool = str(
            front_spool_dir(serve_root_dir(state), key, job.spec.serving)
        )
    prompt = None
    if args.prompt is not None:
        try:
            prompt = [int(t) for t in args.prompt.split(",") if t.strip()]
        except ValueError:
            print(
                f"--prompt must be comma-separated token ids, got "
                f"{args.prompt!r}",
                file=sys.stderr,
            )
            return 2
        if not prompt:
            print(
                f"--prompt contains no token ids: {args.prompt!r}",
                file=sys.stderr,
            )
            return 2
    # The SERVE JOB owns spool creation; the client creating a fresh
    # spool at a typo'd path would leave dead directories and block the
    # full timeout on a request nothing will ever read.
    if not Path(args.spool).is_dir():
        print(
            f"spool {args.spool!r} does not exist — is the serve job "
            "running? (its --spool flag names the directory)",
            file=sys.stderr,
        )
        return 1
    spool = Spool(args.spool)
    rid = spool.submit(
        prompt=prompt,
        prompt_len=args.prompt_len,
        max_new_tokens=args.max_new_tokens,
    )
    if args.no_wait:
        print(rid)
        return 0
    try:
        resp = spool.wait_response(rid, timeout=args.timeout)
    except TimeoutError as e:
        print(str(e), file=sys.stderr)
        return 1
    print(json.dumps(resp))
    return 0 if "error" not in resp else 1


def cmd_bench_control_plane(args) -> int:
    """Control-plane benchmark: supervisor pass latency + store I/O for N
    synthetic jobs, cached vs legacy store plus multi-supervisor sharded
    cells (workloads/ctrlplane_bench)."""
    from pytorch_operator_tpu.workloads import ctrlplane_bench

    argv = ["--jobs", args.jobs, "--passes", str(args.passes)]
    for flag, value in (
        ("--sharded-cells", args.sharded_cells),
        ("--gang-cells", args.gang_cells),
        ("--churn-cells", args.churn_cells),
    ):
        if value is not None:
            argv += [flag, value]
    if args.out:
        argv += ["--out", args.out]
    return ctrlplane_bench.main(argv)


def cmd_bench_data_plane(args) -> int:
    """Data-plane benchmark: checkpoint stall + step throughput across
    {blocking, async, staged} saves x {inline, prefetched} device feeds,
    plus the bursty-producer static-vs-autotuned feed cells
    (workloads/dataplane_bench)."""
    from pytorch_operator_tpu.workloads import dataplane_bench

    argv = [
        "--steps", str(args.steps),
        "--checkpoint-every", str(args.checkpoint_every),
        "--dim", str(args.dim),
        "--feed-steps", str(args.feed_steps),
        "--feed-depth-max", str(args.feed_depth_max),
    ]
    if args.out:
        argv += ["--out", args.out]
    return dataplane_bench.main(argv)


def cmd_bench_serve_plane(args) -> int:
    """Serve-plane benchmark: routed goodput / shed / TTFT across
    replica counts x {healthy, kill_replica, fail_engine_step}, plus
    the zero-router-overhead idle cell (workloads/serveplane_bench)."""
    from pytorch_operator_tpu.workloads import serveplane_bench

    argv = [
        "--replicas", args.replicas,
        "--scenarios", args.scenarios,
        "--rate", str(args.rate),
        "--duration", str(args.duration),
    ]
    if args.smoke:
        argv.append("--smoke")
    if args.out:
        argv += ["--out", args.out]
    return serveplane_bench.main(argv)


def cmd_bench_elastic(args) -> int:
    """Elastic benchmark: resize-in-place vs whole-world-restart recovery
    across real subprocess gangs (workloads/elastic_bench)."""
    from pytorch_operator_tpu.workloads import elastic_bench

    argv = [
        "--gangs", args.gangs,
        "--pre-steps", str(args.pre_steps),
        "--step-time", str(args.step_time),
        "--timeout", str(args.timeout),
    ]
    if args.out:
        argv += ["--out", args.out]
    return elastic_bench.main(argv)


def cmd_manifests(args) -> int:
    # Deploy-manifest generation (SURVEY.md §1 layer 6): the CRD schema is
    # introspected from api/types.py so it cannot drift (api/crdgen.py).
    from pytorch_operator_tpu.api import crdgen

    argv = []
    if args.out_dir:
        argv += ["--out-dir", args.out_dir]
    if args.check:
        argv.append("--check")
    return crdgen.main(argv)


def cmd_verify_invariants(args) -> int:
    """Static invariant checker (analysis/): AST rules over the package,
    gated on zero unsuppressed findings. Tier-1 runs this via
    tests/test_static_analysis.py; the CLI verb is for operators and
    pre-commit use."""
    from pytorch_operator_tpu import analysis

    pkg_root = Path(analysis.__file__).resolve().parent.parent
    root = Path(args.root).resolve() if args.root else pkg_root
    baseline = (
        Path(args.baseline)
        if args.baseline
        else root / "analysis" / "baseline.json"
    )
    try:
        report = analysis.run_verify(root, baseline)
    except analysis.BaselineError as e:
        print(f"verify-invariants: {e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        bl = analysis.Baseline.from_findings(
            report.unsuppressed, justification="TODO: justify or fix"
        )
        bl.save(baseline)
        print(
            f"wrote {len(bl.entries)} entries to {baseline} — edit every "
            "justification before committing",
            file=sys.stderr,
        )
        return 0
    if args.json:
        print(report.to_json())
    else:
        print(report.render_text())
    return report.exit_code()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpujob", description="TPU-native distributed training jobs"
    )
    p.add_argument("--state-dir", default=None, help="supervisor state directory")
    sub = p.add_subparsers(dest="command", required=True)

    def add_ns(sp):
        sp.add_argument("-n", "--namespace", default="default")

    sp = sub.add_parser("run", help="submit a job and supervise to completion")
    sp.add_argument("file")
    sp.add_argument("--timeout", type=float, default=None)
    sp.add_argument("--no-gang", action="store_true", help="disable gang scheduling")
    sp.add_argument(
        "--max-slots", type=int, default=None,
        help="device-slot capacity (a replica requesting N chips/devices "
        "occupies N slots)",
    )
    sp.add_argument(
        "--fault-plan", default=None,
        help="arm a deterministic fault plan (YAML/JSON, faults/) for "
        "this run — failures fire in the supervisor and ride into "
        "replicas via TPUJOB_FAULT_PLAN",
    )
    sp.add_argument(
        "--trace", action="store_true",
        help="record flight-recorder spans (supervisor + every replica) "
        "under <state>/trace/ for `tpujob trace`",
    )
    sp.set_defaults(func=cmd_run)

    sp = sub.add_parser(
        "chaos",
        help="replay a declared failure scenario: run a job under a "
        "fault plan and print the deterministic event-sequence summary; "
        "--record NAME instead reconstructs a plan from a recorded "
        "live failure",
    )
    sp.add_argument(
        "file",
        help="TPUJob spec to run under faults (with --record: the job "
        "NAME whose recorded failure to capture)",
    )
    sp.add_argument(
        "--plan", default=None, help="fault plan file (YAML/JSON)"
    )
    sp.add_argument(
        "--record", action="store_true",
        help="capture the named job's recorded failure timeline as a "
        "replayable fault plan instead of running anything",
    )
    sp.add_argument(
        "--out", default=None,
        help="with --record: write the plan JSON here (default: stdout)",
    )
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("--timeout", type=float, default=None)
    sp.add_argument("--no-gang", action="store_true")
    sp.add_argument("--max-slots", type=int, default=None)
    sp.add_argument(
        "--trace", action="store_true",
        help="record flight-recorder spans during the chaos run "
        "(`tpujob trace` shows the failure timeline)",
    )
    sp.set_defaults(func=cmd_chaos)

    sp = sub.add_parser("submit", help="queue a job for a running supervisor")
    sp.add_argument("file")
    sp.set_defaults(func=cmd_submit)

    sp = sub.add_parser("supervisor", help="run the reconcile daemon")
    sp.add_argument("--interval", type=float, default=0.2)
    sp.add_argument("--no-gang", action="store_true")
    sp.add_argument(
        "--max-slots", type=int, default=None,
        help="device-slot capacity (a replica requesting N chips/devices "
        "occupies N slots)",
    )
    sp.add_argument(
        "--queue-slots",
        default=None,
        dest="queue_slots",
        help="per-queue DEVICE-slot caps, e.g. 'default=4,batch=2' — a "
        "replica requesting N chips/devices occupies N of them (jobs "
        "pick a queue via scheduling_policy.queue; unlisted queues are "
        "unbounded)",
    )
    sp.add_argument(
        "--preempt",
        action="store_true",
        help="allow a held high-priority gang to evict lower-priority "
        "running worlds (they relaunch when capacity frees; their "
        "restart budget is untouched)",
    )
    sp.add_argument(
        "--monitoring-port",
        type=int,
        default=None,
        help="serve /metrics and /healthz on this port (0 = auto)",
    )
    sp.add_argument(
        "--no-leader-elect",
        action="store_true",
        help="skip the leader lease (single-daemon setups)",
    )
    sp.add_argument(
        "--shards",
        type=int,
        default=None,
        help="shard the job space N ways across multiple supervisors "
        "sharing this state dir (per-shard store leases with fencing "
        "tokens; every supervisor must pass the same N). Replaces "
        "leader election: each daemon reconciles only the shards it "
        "holds, and shards rebalance within one lease TTL on "
        "join/death/drain",
    )
    sp.add_argument(
        "--supervisor-id",
        default=None,
        help="identity for shard leases and per-supervisor metrics "
        "(default: <hostname>-<pid>)",
    )
    sp.add_argument(
        "--lease-ttl",
        type=float,
        default=5.0,
        help="shard-lease TTL in seconds: the failover bound — an "
        "orphaned shard is re-claimed within one TTL (default 5)",
    )
    sp.add_argument(
        "--sync-workers-max",
        type=int,
        default=None,
        help="ceiling for the latency-driven steady-pool autoscaler "
        "(grows the reconcile pool when the measured steady-phase "
        "latency climbs, shrinks to the floor on an idle fleet; "
        "default min(8, ncpu); env TPUJOB_SYNC_WORKERS_MAX)",
    )
    sp.add_argument(
        "--standby",
        type=int,
        default=0,
        help="keep N pre-warmed standby processes (interpreter + jax "
        "imports already paid) and hand module-template replicas to "
        "them — cuts schedule-to-first-step latency (0 = off)",
    )
    sp.add_argument(
        "--trace", action="store_true",
        help="record flight-recorder spans (supervisor + every replica) "
        "under <state>/trace/ for `tpujob trace`",
    )
    sp.set_defaults(func=cmd_supervisor)

    sp = sub.add_parser("get", help="list jobs")
    sp.add_argument("name", nargs="?")
    sp.add_argument(
        "--json", action="store_true",
        help="full job objects as JSON (kubectl -o json analog)",
    )
    sp.add_argument(
        "-w", "--watch", action="store_true",
        help="keep watching; re-print the table on any state change",
    )
    add_ns(sp)
    sp.set_defaults(func=cmd_get)

    sp = sub.add_parser("describe", help="show job details and events")
    sp.add_argument("name")
    sp.add_argument(
        "--json", action="store_true",
        help="the full job object as JSON (kubectl -o json analog)",
    )
    add_ns(sp)
    sp.set_defaults(func=cmd_describe)

    sp = sub.add_parser("logs", help="print replica logs")
    sp.add_argument("name")
    sp.add_argument("--replica", default=None, help="e.g. master-0, worker-1")
    sp.add_argument(
        "-f", "--follow", action="store_true",
        help="stream new log output until the job finishes",
    )
    add_ns(sp)
    sp.set_defaults(func=cmd_logs)

    sp = sub.add_parser("delete", help="delete a job")
    sp.add_argument("name")
    sp.add_argument(
        "--purge",
        action="store_true",
        help="also remove the job's checkpoint/status artifacts",
    )
    add_ns(sp)
    sp.set_defaults(func=cmd_delete)

    sp = sub.add_parser("scale", help="elastic resize of a job's workers")
    sp.add_argument("name")
    sp.add_argument("--workers", type=int, required=True)
    add_ns(sp)
    sp.set_defaults(func=cmd_scale)

    sp = sub.add_parser(
        "events", help="merged event log across jobs (kubectl get events)"
    )
    sp.add_argument(
        "name", nargs="?", default=None,
        help="only this job's events (required with --follow)",
    )
    sp.add_argument(
        "--tail", type=int, default=50, help="show the last N events (0 = all)"
    )
    sp.add_argument(
        "-f", "--follow", action="store_true",
        help="tail the job's event sink live (aggregation-aware: a "
        "crash-looping event re-prints with its growing count) until "
        "the job finishes",
    )
    add_ns(sp)
    sp.set_defaults(func=cmd_events)

    sp = sub.add_parser(
        "trace",
        help="merge a job's flight-recorder span files into one "
        "Chrome-trace/Perfetto JSON (record with run/supervisor "
        "--trace or spec.observability.trace)",
    )
    sp.add_argument("name")
    sp.add_argument(
        "--out", default=None,
        help="write the trace JSON here (default: stdout)",
    )
    sp.add_argument(
        "--no-clock-sync", action="store_true", dest="no_clock_sync",
        help="skip the heartbeat-matched per-replica clock corrections "
        "(keep each host's raw timestamps)",
    )
    sp.add_argument(
        "--request", default=None, metavar="RID",
        help="render a clock-aligned text waterfall for one serve "
        "request (enqueue → claim → dispatch → transit → slot wait → "
        "decode → respond) instead of the full trace JSON",
    )
    add_ns(sp)
    sp.set_defaults(func=cmd_trace)

    sp = sub.add_parser(
        "why",
        help="postmortem a job from its recorded artifacts: clock-align "
        "the cross-host timeline, run the anomaly detectors (step-time "
        "regression, feed stall, checkpoint lag, heartbeat silence, "
        "straggler), print findings with evidence",
    )
    sp.add_argument("name")
    sp.add_argument(
        "--window", type=float, default=None,
        help="analyze only the last N seconds of the recorded timeline "
        "(default: everything; the regression baseline is what precedes "
        "the window)",
    )
    sp.add_argument(
        "--out", default=None,
        help="also write the machine-readable JSON report here",
    )
    sp.add_argument(
        "--json", action="store_true",
        help="print the JSON report instead of the terminal rendering",
    )
    add_ns(sp)
    sp.set_defaults(func=cmd_why)

    sp = sub.add_parser(
        "top",
        help="live fleet table: per-job step, steps/s, p50/p99 step "
        "time, checkpoint lag, feed stall, firing alerts",
    )
    sp.add_argument(
        "--once", action="store_true",
        help="print one snapshot and exit (default: refresh loop)",
    )
    sp.add_argument(
        "--interval", type=float, default=2.0,
        help="refresh interval in seconds",
    )
    sp.add_argument(
        "--diff", action="store_true",
        help="print only deltas vs the previous repaint (step-rate "
        "moves, new firing alerts, jobs appearing/finishing) as a "
        "scrolling log instead of repainting the table",
    )
    sp.set_defaults(func=cmd_top)

    sp = sub.add_parser(
        "alerts",
        help="live health-engine alerts (streaming detector rules + "
        "lifecycle): current state per job/rule/replica from the "
        "per-job alert logs",
    )
    sp.add_argument(
        "name", nargs="?", default=None,
        help="only this job's alerts (required with --follow)",
    )
    sp.add_argument(
        "-f", "--follow", action="store_true",
        help="live-tail the job's alert transitions (firing/resolved) "
        "until the job finishes",
    )
    sp.add_argument(
        "--json", action="store_true",
        help="print the raw transition records as JSON",
    )
    add_ns(sp)
    sp.set_defaults(func=cmd_alerts)

    sp = sub.add_parser(
        "remediations",
        help="remediation audit trail: every alert→decision→action→"
        "outcome the closed loop recorded, from the per-job audit logs",
    )
    sp.add_argument(
        "name", nargs="?", default=None,
        help="only this job's remediations (required with --follow)",
    )
    sp.add_argument(
        "-f", "--follow", action="store_true",
        help="live-tail the job's remediation actions until the job "
        "finishes",
    )
    sp.add_argument(
        "--json", action="store_true",
        help="print the raw audit records as JSON",
    )
    add_ns(sp)
    sp.set_defaults(func=cmd_remediations)

    sp = sub.add_parser(
        "apply", help="create or update a job from a spec file (kubectl apply)"
    )
    sp.add_argument("file")
    sp.set_defaults(func=cmd_apply)

    sp = sub.add_parser(
        "suspend", help="suspend a job (tear down replicas, keep the job)"
    )
    sp.add_argument("name")
    add_ns(sp)
    sp.set_defaults(func=cmd_suspend)

    sp = sub.add_parser("resume", help="resume a suspended job")
    sp.add_argument("name")
    add_ns(sp)
    sp.set_defaults(func=cmd_resume)

    sp = sub.add_parser(
        "manifests", help="generate deploy manifests (CRD/RBAC/Deployment)"
    )
    sp.add_argument("--out-dir", default=None, help="default: repo manifests/")
    sp.add_argument("--check", action="store_true", help="verify no drift")
    sp.set_defaults(func=cmd_manifests)

    sp = sub.add_parser("metrics", help="print supervisor metrics")
    sp.set_defaults(func=cmd_metrics)

    sp = sub.add_parser(
        "bench-control-plane",
        help="measure supervisor pass latency + store I/O for N synthetic "
        "jobs (cached vs legacy store); emits a JSON artifact",
    )
    sp.add_argument(
        "--jobs", default="10,100,1000",
        help="comma-separated fleet sizes (default: 10,100,1000)",
    )
    sp.add_argument(
        "--passes", type=int, default=30, help="idle passes per cell"
    )
    sp.add_argument(
        "--sharded-cells", default=None,
        help="multi-supervisor cells as N:S (jobs:supervisors), e.g. "
        "'10000:2,10000:4' (default: 10000:1,10000:2,10000:4; '' "
        "disables)",
    )
    sp.add_argument(
        "--gang-cells", default=None,
        help="wide-gang cells as NxM:S, e.g. '500x16:2' ('' disables)",
    )
    sp.add_argument(
        "--churn-cells", default=None,
        help="marker-heavy churn cells as N:S, e.g. '2000:2' ('' "
        "disables)",
    )
    sp.add_argument(
        "--out", default=None,
        help="write the full artifact here (e.g. BENCH_ctrlplane.json)",
    )
    sp.set_defaults(func=cmd_bench_control_plane)

    sp = sub.add_parser(
        "bench-data-plane",
        help="measure training-step checkpoint stalls + device-feed "
        "overlap ({blocking, async, staged} saves x {inline, prefetched} "
        "feeds, bursty static-vs-autotuned feed cells); emits a JSON "
        "artifact",
    )
    sp.add_argument("--steps", type=int, default=40, help="timed steps/cell")
    sp.add_argument(
        "--checkpoint-every", type=int, default=5, help="save cadence"
    )
    sp.add_argument(
        "--dim", type=int, default=256,
        help="bench model width (state bytes ~ 96*dim^2)",
    )
    sp.add_argument(
        "--feed-steps", type=int, default=60,
        help="fenced steps per bursty feed cell",
    )
    sp.add_argument(
        "--feed-depth-max", type=int, default=8,
        help="depth budget the autotuned feed cell may grow into",
    )
    sp.add_argument(
        "--out", default=None,
        help="write the full artifact here (e.g. BENCH_dataplane.json)",
    )
    sp.set_defaults(func=cmd_bench_data_plane)

    sp = sub.add_parser(
        "bench-serve-plane",
        help="measure routed serving goodput/shed/TTFT across replica "
        "counts x {healthy, kill_replica, fail_engine_step} plus the "
        "zero-router-overhead idle cell; emits a JSON artifact",
    )
    sp.add_argument(
        "--replicas", default="1,2,4",
        help="comma-separated replica counts per scenario",
    )
    sp.add_argument(
        "--scenarios", default="healthy,kill_replica,fail_engine_step",
    )
    sp.add_argument(
        "--rate", type=float, default=85.0,
        help="offered load, requests/s (open-loop Poisson)",
    )
    sp.add_argument(
        "--duration", type=float, default=6.0,
        help="arrival window per cell, seconds",
    )
    sp.add_argument(
        "--smoke", action="store_true",
        help="tiny under-capacity cells — seconds, not minutes",
    )
    sp.add_argument(
        "--out", default=None,
        help="write the full artifact here (e.g. BENCH_serveplane.json)",
    )
    sp.set_defaults(func=cmd_bench_serve_plane)

    sp = sub.add_parser(
        "bench-elastic",
        help="measure resize-in-place vs whole-world-restart recovery "
        "(kill one worker of a real subprocess gang; wall-clock to the "
        "slowest member's first post-recovery step, step loss, rank "
        "audit); emits a JSON artifact",
    )
    sp.add_argument(
        "--gangs", default="2,4,8",
        help="comma-separated WORKER counts per gang (each gang also "
        "has one master)",
    )
    sp.add_argument(
        "--pre-steps", type=int, default=5,
        help="steps every member must reach before the kill",
    )
    sp.add_argument(
        "--step-time", type=float, default=0.02,
        help="per-step sleep of the bench workload, seconds",
    )
    sp.add_argument(
        "--timeout", type=float, default=120.0,
        help="per-phase (warm-up / recovery) timeout, seconds",
    )
    sp.add_argument(
        "--out", default=None,
        help="write the full artifact here (e.g. BENCH_elastic.json)",
    )
    sp.set_defaults(func=cmd_bench_elastic)

    sp = sub.add_parser(
        "verify-invariants",
        help="run the static invariant checker (atomic-state-write, "
        "fenced-store-write, lock-order, swallowed-exception, "
        "retry-discipline, clock-discipline) over the package; exit 1 "
        "on any unsuppressed finding",
    )
    sp.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    sp.add_argument(
        "--baseline", default=None,
        help="baseline file of accepted findings "
        "(default: <root>/analysis/baseline.json)",
    )
    sp.add_argument(
        "--root", default=None,
        help="package root to analyze (default: the installed "
        "pytorch_operator_tpu package)",
    )
    sp.add_argument(
        "--write-baseline", action="store_true",
        help="accept every current unsuppressed finding into the "
        "baseline (justifications must then be edited by hand)",
    )
    sp.set_defaults(func=cmd_verify_invariants)

    sp = sub.add_parser(
        "serve-request",
        help="submit a request to a serving job's spool and print the "
        "response (tokens + TTFT/per-token latency)",
    )
    sp.add_argument(
        "--spool", default=None, help="a serve job's --spool dir directly"
    )
    sp.add_argument(
        "--job", default=None,
        help="a spec.serving job (name or ns/name): submit to its FRONT "
        "spool — the supervisor's router dispatches across replicas",
    )
    add_ns(sp)
    sp.add_argument(
        "--prompt", default=None,
        help="comma-separated token ids (no tokenizer ships here)",
    )
    sp.add_argument(
        "--prompt-len", type=int, default=None,
        help="synthesize a deterministic prompt of this length instead",
    )
    sp.add_argument("--max-new-tokens", type=int, default=64)
    sp.add_argument(
        "--timeout", type=float, default=300.0,
        help="seconds to wait for the response",
    )
    sp.add_argument(
        "--no-wait", action="store_true",
        help="print the request id and exit (poll responses/<id>.json)",
    )
    sp.set_defaults(func=cmd_serve_request)

    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    raise SystemExit(main())
