"""Client: the ``tpujob`` CLI (the kubectl+CRD analog)."""
