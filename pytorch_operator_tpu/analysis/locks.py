"""lock-order: the static lock-acquisition graph.

Two failure shapes are checked across the threaded planes
(``controller/supervisor.py``, ``controller/leases.py``,
``checkpoint/async_writer.py``, ``data/device_prefetch.py`` — plus any
module that defines a lock attribute):

1. **Cyclic acquisition order.** For every ``with self.a: ...
   with self.b:`` nesting (directly, or through one resolvable call
   while ``a`` is held) an edge ``a -> b`` is recorded, keyed by
   (class, attr). A cycle in that graph means two threads can acquire
   the same pair in opposite orders and deadlock.

2. **Blocking under a lock.** A call that can block indefinitely on
   the outside world — ``subprocess.*``, ``Popen``, ``.wait()``,
   ``.join()``, ``select``, ``sleep`` of non-trivial duration — while
   a lock is held starves every other thread that needs the lock (the
   renewal thread missing its TTL is the canonical casualty). Checked
   in the lock-holding function itself and one resolvable call deep —
   deliberately not transitively, so deep by-design orchestration
   (reconciler's per-key spawn pipeline) stays out of scope while a
   direct ``Popen`` under ``self._lock`` is flagged.

Lock identity is name-based: a ``with`` item whose expression source
matches ``/lock|_cv|cond/i`` or resolves to a known lock attribute
(``self.x = threading.Lock()``). ``Condition.wait`` is exempt from the
blocking check — releasing the lock while waiting is its whole point.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from . import callgraph
from .findings import RawFinding
from .rules import ProjectRule, _call_name, _src

_LOCKY = re.compile(r"(lock|_cv\b|cond)", re.IGNORECASE)

# Calls that block on the outside world. Substring match on the dotted
# call name; kept short so lock-protected in-memory work never trips it.
_BLOCKING = (
    "subprocess.",
    "Popen",
    "check_call",
    "check_output",
    "communicate",
    "sleep",
    "select.select",
)
_BLOCKING_ATTRS = {"wait", "join", "communicate"}
# .wait()/.join() receivers that are fine: Condition.wait under its own
# lock, and Event.wait with a timeout is typically a paced poll.
_WAIT_EXEMPT_RECV = re.compile(r"(_cv|cond|event|_ev\b|stop)", re.IGNORECASE)


def _lock_key(mod, item: ast.withitem, caller) -> Optional[str]:
    """Stable identity for an acquired lock, or None if not a lock.

    ``self._lock`` in class C -> ``C._lock`` so the same attribute seen
    from two methods is one node, while unrelated classes' ``_lock``
    attrs stay distinct.
    """
    expr = item.context_expr
    # Condition/Lock used via acquire-helper calls are not `with` items;
    # we only model `with`-scoped acquisition (the repo's idiom).
    src = _src(mod, expr)
    e = expr
    if isinstance(e, ast.Call):  # with self.key_lock(key): ...
        e = e.func
        src = _src(mod, e)
    if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) and (
        e.value.id == "self"
    ):
        if caller is not None and caller.class_name:
            if _LOCKY.search(e.attr) or _is_known_lock_attr(
                mod, caller, e.attr
            ):
                return f"{caller.class_name}.{e.attr}"
        return f"?.{e.attr}" if _LOCKY.search(e.attr) else None
    if _LOCKY.search(src):
        return f"{mod.relpath}:{src}"
    return None


def _is_known_lock_attr(mod, caller, attr: str) -> bool:
    prog = getattr(mod, "_prog", None)
    if prog is None or caller.class_name is None:
        return False
    ci = prog.class_in_module(caller.class_name, caller.module)
    return ci is not None and attr in ci.lock_attrs


def _blocking_calls(mod, fn: ast.AST) -> List[Tuple[int, str]]:
    """(line, description) for every blocking call directly in fn,
    ignoring nested defs."""
    out: List[Tuple[int, str]] = []
    for node in _own_body(fn):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if any(b in name for b in _BLOCKING):
            if name.endswith("sleep") and _tiny_sleep(node):
                continue
            out.append((node.lineno, name))
            continue
        if isinstance(node.func, ast.Attribute) and (
            node.func.attr in _BLOCKING_ATTRS
        ):
            recv = _src(mod, node.func.value)
            if _WAIT_EXEMPT_RECV.search(recv):
                continue
            if node.args or any(k.arg == "timeout" for k in node.keywords):
                continue  # bounded wait
            out.append((node.lineno, f"{recv}.{node.func.attr}()"))
    return out


def _tiny_sleep(node: ast.Call) -> bool:
    if node.args and isinstance(node.args[0], ast.Constant):
        v = node.args[0].value
        return isinstance(v, (int, float)) and v <= 0.2
    return False


def _own_body(fn: ast.AST):
    """Walk a function body without descending into nested defs."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


class LockOrder(ProjectRule):
    id = "lock-order"
    summary = (
        "lock acquisition must be acyclic, and no lock may be held "
        "across blocking I/O or subprocess calls"
    )

    SCOPE_PREFIXES = ("controller/", "checkpoint/", "data/", "serving/", "obs/")

    def run(self, mods) -> Iterator[tuple]:
        in_scope = [
            m for m in mods if m.relpath.startswith(self.SCOPE_PREFIXES)
        ]
        prog = callgraph.build_program(in_scope)
        for m in in_scope:
            m._prog = prog  # for _is_known_lock_attr
        by_rel = {m.relpath: m for m in in_scope}

        # locks each function acquires at its top `with` level, and
        # what happens while held.
        edges: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}
        held_findings: List[tuple] = []

        for (module, qualname), fi in prog.functions.items():
            mod = by_rel[module]
            self._scan_fn(mod, fi, prog, by_rel, edges, held_findings)

        yield from held_findings

        # Cycle detection over the acquisition edges (only class-attr
        # keys — path-keyed locals can't deadlock across threads the
        # same way and would add noise).
        graph: Dict[str, Set[str]] = {}
        where: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for (a, b), sites in edges.items():
            graph.setdefault(a, set()).add(b)
            where[(a, b)] = sites[0]
        for cyc in self._cycles(graph):
            a, b = cyc[0], cyc[1 % len(cyc)]
            module, line = where.get((a, b), ("", 0))
            mod = by_rel.get(module)
            if mod is None:
                continue
            yield mod, RawFinding(
                line,
                "cyclic lock acquisition order: "
                + " -> ".join(cyc + [cyc[0]])
                + " — two threads taking these in opposite orders "
                "deadlock; impose a single global order",
            )

    # ------------------------------------------------------------------
    def _scan_fn(self, mod, fi, prog, by_rel, edges, held_findings):
        """Walk fi recording (outer lock -> inner lock) edges and
        blocking-while-held findings."""

        def walk(node, held: List[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    acquired = []
                    for item in child.items:
                        key = _lock_key(mod, item, fi)
                        if key is None:
                            continue
                        for outer in held:
                            if outer != key:
                                edges.setdefault((outer, key), []).append(
                                    (mod.relpath, child.lineno)
                                )
                        acquired.append(key)
                    if acquired:
                        self._check_held(
                            mod, fi, child, held + acquired, prog, by_rel,
                            held_findings,
                        )
                    walk(child, held + acquired)
                else:
                    walk(child, held)

        walk(fi.node, [])

    def _check_held(
        self, mod, fi, with_node, held, prog, by_rel, held_findings
    ):
        """Blocking calls inside this with-block: direct, plus one
        resolvable call deep."""
        reported: Set[Tuple[str, int]] = set()

        def report(target_mod, line, desc, via=""):
            if (target_mod.relpath, line) in reported:
                return
            reported.add((target_mod.relpath, line))
            suffix = f" (reached via {via})" if via else ""
            held_findings.append(
                (
                    target_mod,
                    RawFinding(
                        line,
                        f"blocking call {desc} while holding "
                        f"{', '.join(held)}{suffix} — a stalled child "
                        "starves every thread waiting on the lock; move "
                        "the blocking work outside the critical section",
                    ),
                )
            )

        # direct blocking calls in the with body
        body_fn = ast.Module(body=with_node.body, type_ignores=[])
        for line, desc in _blocking_calls(mod, body_fn):
            report(mod, line, desc)
        # one level of callees
        for node in _own_body(body_fn):
            if not isinstance(node, ast.Call):
                continue
            for callee in callgraph.resolve_call(node, fi, prog):
                cmod = by_rel.get(callee.module)
                if cmod is None:
                    continue
                for line, desc in _blocking_calls(cmod, callee.node):
                    report(cmod, line, desc, via=f"{fi.qualname} -> "
                           f"{callee.qualname}")

    @staticmethod
    def _cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
        """Simple cycles (as node lists) via DFS; deduplicated by the
        sorted node set so each cycle reports once."""
        out: List[List[str]] = []
        seen_sets: Set[frozenset] = set()

        def dfs(start, node, path, onpath):
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    key = frozenset(path)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        out.append(list(path))
                elif nxt not in onpath and len(path) < 6:
                    path.append(nxt)
                    onpath.add(nxt)
                    dfs(start, nxt, path, onpath)
                    onpath.discard(nxt)
                    path.pop()

        for start in sorted(graph):
            dfs(start, start, [start], {start})
        return out
