"""Baseline: the committed catalog of accepted findings.

``analysis/baseline.json`` is a reviewed artifact, not a dumping
ground: every entry MUST carry a non-empty ``justification`` string
(load refuses entries without one), and an entry whose fingerprint no
longer matches any current finding is reported STALE so it gets
re-justified or deleted rather than silently inherited.

Schema::

    {
      "version": 1,
      "entries": [
        {
          "fingerprint": "0123456789abcdef",
          "rule": "clock-discipline",
          "location": "controller/leases.py:210",   # informational
          "justification": "lease records cross process boundaries; ..."
        },
        ...
      ]
    }

Matching is by fingerprint alone — ``location`` is a human breadcrumb
that may drift as code moves without invalidating the entry.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional

from .findings import Finding


class BaselineError(ValueError):
    """Malformed baseline file (bad JSON, missing justification, ...)."""


@dataclass
class BaselineEntry:
    fingerprint: str
    rule: str
    location: str
    justification: str

    def to_dict(self) -> dict:
        return {
            "fingerprint": self.fingerprint,
            "rule": self.rule,
            "location": self.location,
            "justification": self.justification,
        }


@dataclass
class Baseline:
    entries: List[BaselineEntry] = field(default_factory=list)
    path: Optional[Path] = None

    def by_fingerprint(self) -> Dict[str, BaselineEntry]:
        return {e.fingerprint: e for e in self.entries}

    # ------------------------------------------------------------------
    @classmethod
    def load(cls, path: Path) -> "Baseline":
        try:
            data = json.loads(path.read_text())
        except json.JSONDecodeError as e:
            raise BaselineError(f"{path}: not valid JSON: {e}") from e
        if not isinstance(data, dict) or "entries" not in data:
            raise BaselineError(f"{path}: expected an object with 'entries'")
        entries: List[BaselineEntry] = []
        for i, raw in enumerate(data["entries"]):
            fp = raw.get("fingerprint", "")
            just = raw.get("justification", "")
            if not isinstance(fp, str) or not fp:
                raise BaselineError(
                    f"{path}: entry {i} has no fingerprint"
                )
            if not isinstance(just, str) or not just.strip():
                raise BaselineError(
                    f"{path}: entry {i} ({raw.get('location', fp)}) has "
                    "no justification — every accepted finding must say "
                    "why it is accepted"
                )
            entries.append(
                BaselineEntry(
                    fingerprint=fp,
                    rule=str(raw.get("rule", "")),
                    location=str(raw.get("location", "")),
                    justification=just.strip(),
                )
            )
        return cls(entries=entries, path=path)

    def save(self, path: Path) -> None:
        payload = {
            "version": 1,
            "entries": [
                e.to_dict()
                for e in sorted(
                    self.entries, key=lambda e: (e.location, e.rule)
                )
            ],
        }
        tmp = path.with_name(path.name + ".tmp")
        tmp.write_text(json.dumps(payload, indent=2) + "\n")
        tmp.replace(path)

    # ------------------------------------------------------------------
    def apply(self, findings: List[Finding]) -> "BaselineResult":
        """Split findings into suppressed / unsuppressed and detect
        stale entries."""
        by_fp = self.by_fingerprint()
        suppressed: List[Finding] = []
        unsuppressed: List[Finding] = []
        matched: set = set()
        for f in findings:
            entry = by_fp.get(f.fingerprint)
            if entry is not None:
                matched.add(f.fingerprint)
                suppressed.append(f)
            else:
                unsuppressed.append(f)
        stale = [e for e in self.entries if e.fingerprint not in matched]
        return BaselineResult(suppressed, unsuppressed, stale)

    @classmethod
    def from_findings(
        cls, findings: List[Finding], justification: str
    ) -> "Baseline":
        """A baseline accepting every given finding (used by
        ``--write-baseline``; the operator then edits the per-entry
        justifications before committing)."""
        return cls(
            entries=[
                BaselineEntry(
                    fingerprint=f.fingerprint,
                    rule=f.rule,
                    location=f.location(),
                    justification=justification,
                )
                for f in findings
            ]
        )


@dataclass
class BaselineResult:
    suppressed: List[Finding]
    unsuppressed: List[Finding]
    stale: List[BaselineEntry]
