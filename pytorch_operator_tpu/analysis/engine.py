"""The analysis engine: load sources, run rules, apply waivers and the
baseline, render the report.

The engine reads Python sources ONCE into in-memory
:class:`SourceModule` objects (text + parsed tree + waiver map) and
every rule works off those — the analyzer performs **zero state-dir
I/O** and zero writes anywhere (``--write-baseline`` being the one
explicit, operator-requested exception). ``AnalysisIO`` counts the
reads so the bench lane can pin that contract.
"""

from __future__ import annotations

import ast
import fnmatch
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from .baseline import Baseline, BaselineResult
from .findings import (
    Finding,
    fingerprint_findings,
    find_waiver,
    scan_waivers,
)
from .rules import ProjectRule, Rule, iter_functions, module_rules, project_rules

# Analyzed subtree roots, relative to the package root. Tests and
# benches are excluded: they intentionally simulate the anti-patterns.
DEFAULT_EXCLUDE = (
    "analysis/*",  # the checker's own pattern tables would self-flag
    "_vendor/*",
)


@dataclass
class SourceModule:
    """One parsed source file."""

    relpath: str  # posix, relative to the analysis root
    path: Path
    text: str
    tree: ast.Module
    lines: List[str]
    waivers: Dict[int, str]

    @classmethod
    def load(cls, root: Path, path: Path) -> "SourceModule":
        text = path.read_text()
        lines = text.splitlines()
        return cls(
            relpath=path.relative_to(root).as_posix(),
            path=path,
            text=text,
            tree=ast.parse(text, filename=str(path)),
            lines=lines,
            waivers=scan_waivers(lines),
        )


@dataclass
class AnalysisIO:
    """I/O accounting: the analyzer must only ever READ sources."""

    files_read: int = 0
    files_written: int = 0
    state_dir_touches: int = 0


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)  # all, incl. waived
    result: Optional[BaselineResult] = None
    io: AnalysisIO = field(default_factory=AnalysisIO)
    modules_scanned: int = 0

    @property
    def unsuppressed(self) -> List[Finding]:
        if self.result is not None:
            return self.result.unsuppressed
        return [f for f in self.findings if not f.waived]

    @property
    def stale_entries(self):
        return self.result.stale if self.result is not None else []

    def exit_code(self) -> int:
        return 1 if self.unsuppressed else 0

    # ------------------------------------------------------------------
    def to_json(self) -> str:
        return json.dumps(
            {
                "modules_scanned": self.modules_scanned,
                "total_findings": len(self.findings),
                "waived": sum(1 for f in self.findings if f.waived),
                "suppressed": len(self.result.suppressed)
                if self.result
                else 0,
                "unsuppressed": [f.to_dict() for f in self.unsuppressed],
                "stale_baseline_entries": [
                    e.to_dict() for e in self.stale_entries
                ],
                "io": {
                    "files_read": self.io.files_read,
                    "files_written": self.io.files_written,
                    "state_dir_touches": self.io.state_dir_touches,
                },
            },
            indent=2,
        )

    def render_text(self) -> str:
        out: List[str] = []
        for f in sorted(
            self.unsuppressed, key=lambda f: (f.path, f.line, f.rule)
        ):
            out.append(f"{f.location()}: [{f.rule}] {f.message}")
            out.append(f"    fingerprint: {f.fingerprint}")
        for e in self.stale_entries:
            out.append(
                f"STALE baseline entry [{e.rule}] {e.location} "
                f"({e.fingerprint}): flagged code changed or disappeared "
                "— re-justify or delete the entry"
            )
        waived = sum(1 for f in self.findings if f.waived)
        suppressed = len(self.result.suppressed) if self.result else 0
        out.append(
            f"verify-invariants: {self.modules_scanned} modules, "
            f"{len(self.findings)} findings "
            f"({waived} waived inline, {suppressed} baseline-suppressed, "
            f"{len(self.unsuppressed)} unsuppressed)"
        )
        return "\n".join(out)


# ---------------------------------------------------------------------------


def discover_sources(root: Path, exclude: Sequence[str] = DEFAULT_EXCLUDE):
    out: List[Path] = []
    for path in sorted(root.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        if any(fnmatch.fnmatch(rel, pat) for pat in exclude):
            continue
        out.append(path)
    return out


def _qualname_at(mod: SourceModule, line: int) -> str:
    """Innermost enclosing function qualname for a line ("" = module)."""
    best = ""
    best_span = None
    for qual, fn in iter_functions(mod.tree):
        end = fn.end_lineno or fn.lineno
        if fn.lineno <= line <= end:
            span = end - fn.lineno
            if best_span is None or span <= best_span:
                best, best_span = qual, span
    return best


def analyze(
    root: Path,
    *,
    exclude: Sequence[str] = DEFAULT_EXCLUDE,
    rules: Optional[Sequence[Rule]] = None,
    proj_rules: Optional[Sequence[ProjectRule]] = None,
) -> Report:
    """Run every rule over the package rooted at ``root``."""
    report = Report()
    mods: List[SourceModule] = []
    for path in discover_sources(root, exclude):
        mods.append(SourceModule.load(root, path))
        report.io.files_read += 1
    report.modules_scanned = len(mods)

    findings: List[Finding] = []

    def attach(mod: SourceModule, rule_id: str, raw) -> None:
        f = Finding(
            rule=rule_id,
            path=mod.relpath,
            line=raw.line,
            message=raw.message,
            qualname=_qualname_at(mod, raw.line),
        )
        reason = find_waiver(mod.waivers, raw.line, raw.span)
        if reason is not None:
            f.waived = True
            f.waive_reason = reason
        findings.append(f)

    for rule in rules if rules is not None else module_rules():
        for mod in mods:
            if not rule.scope(mod.relpath):
                continue
            for raw in rule.run(mod):
                attach(mod, rule.id, raw)

    for prule in proj_rules if proj_rules is not None else project_rules():
        for mod, raw in prule.run(mods):
            attach(mod, prule.id, raw)

    fingerprint_findings(
        findings, {m.relpath: m.lines for m in mods}
    )
    report.findings = findings
    return report


def run_verify(
    root: Path,
    baseline_path: Optional[Path] = None,
    *,
    exclude: Sequence[str] = DEFAULT_EXCLUDE,
) -> Report:
    """The full verify-invariants pass: analyze + waivers + baseline."""
    report = analyze(root, exclude=exclude)
    active = [f for f in report.findings if not f.waived]
    if baseline_path is not None and baseline_path.exists():
        bl = Baseline.load(baseline_path)
        report.io.files_read += 1
        report.result = bl.apply(active)
    else:
        report.result = BaselineResult([], active, [])
    return report
