"""The invariant rules.

Each rule mechanizes one contract that previously lived only in
ARCHITECTURE.md prose and review comments. Rules are HEURISTIC on
purpose — they pattern-match the idioms this codebase actually uses
(tmp+``os.replace``, ``O_EXCL`` markers, the shared ``backoff``
schedule, ``events.warning`` emission) and accept that a site the
heuristic cannot prove safe must either be rewritten in the idiom,
carry an inline ``# invariant: waived — reason`` tag, or be justified
in ``analysis/baseline.json``. A checker that guesses too generously
enforces nothing.

Per-module rules (subclass :class:`Rule`):

- ``atomic-state-write``   bare ``open(.., "w")`` / ``write_text`` /
                           ``write_bytes`` / creat-without-``O_EXCL``
                           in the state-bearing planes (controller/,
                           serving/, checkpoint/, obs/). Exempt: tmp-
                           named targets (the tmp+rename discipline),
                           append modes, ``O_EXCL``/``O_APPEND`` opens,
                           and functions that ``flock`` (locked
                           in-place rewrite).
- ``swallowed-exception``  ``except Exception``/``BaseException``/bare
                           handlers that neither re-raise nor call
                           anything that looks like an event/log
                           emission.
- ``retry-discipline``     ``time.sleep`` inside an exception handler
                           inside a loop — a retry loop not on the
                           shared ``backoff.py`` schedule.
- ``clock-discipline``     ``time.time()`` (directly or through a
                           local) in arithmetic/comparison against
                           TTL/deadline/timeout-shaped names — interval
                           math belongs on ``time.monotonic()``.

Project-wide rules (subclass :class:`ProjectRule`, see also
:mod:`.locks`):

- ``fenced-store-write``   job-state persistence reachable from the
                           sharded supervisor path that bypasses the
                           lease-fenced JobStore API, and any cross-
                           module call of JobStore persistence
                           internals.
- ``remediation-discipline`` actuator writes reachable from the
                           remediation engine that bypass the fenced
                           commit: store mutations outside the commit/
                           adopt pair, fleet actuations outside the
                           post-commit effectors, and cross-module
                           calls of engine-private decision internals.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional

from . import callgraph
from .findings import RawFinding

# ---------------------------------------------------------------------------
# infrastructure


class Rule:
    """Per-module rule: ``run(mod)`` yields RawFindings."""

    id: str = ""
    summary: str = ""

    def scope(self, relpath: str) -> bool:
        return True

    def run(self, mod) -> Iterator[RawFinding]:  # pragma: no cover
        raise NotImplementedError


class ProjectRule:
    """Whole-program rule: ``run(mods)`` yields (mod, RawFinding)."""

    id: str = ""
    summary: str = ""

    def run(self, mods) -> Iterator[tuple]:  # pragma: no cover
        raise NotImplementedError


def _src(mod, node: ast.AST) -> str:
    """Best-effort source text of a node (falls back to unparse)."""
    try:
        seg = ast.get_source_segment(mod.text, node)
        if seg is not None:
            return seg
    except Exception:  # invariant: waived — source-segment is cosmetic
        pass
    try:
        return ast.unparse(node)
    except Exception:  # invariant: waived — source-segment is cosmetic
        return ""


def _call_name(node: ast.Call) -> str:
    """Dotted-ish name of the called thing: ``open``, ``os.replace``,
    ``self.events.warning`` -> "self.events.warning"."""
    parts: List[str] = []
    f = node.func
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    return ".".join(reversed(parts))


def iter_functions(tree: ast.Module):
    """Yield (qualname, function node) for every def, nested included."""

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                yield qual, child
                yield from walk(child, f"{qual}.")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.")
            else:
                yield from walk(child, prefix)

    yield from walk(tree, "")


# ---------------------------------------------------------------------------
# atomic-state-write

_PLANES = ("controller/", "serving/", "checkpoint/", "obs/")
_WRITE_MODES = re.compile(r"^[wx]")  # "w", "wb", "w+", "x" (x is O_EXCL-like)


class AtomicStateWrite(Rule):
    id = "atomic-state-write"
    summary = (
        "file writes under the state/artifact root must be atomic: "
        "tmp + os.replace/rename, O_EXCL create, or os.link publication"
    )

    def scope(self, relpath: str) -> bool:
        return relpath.startswith(_PLANES)

    def run(self, mod) -> Iterator[RawFinding]:
        flocky_spans = [
            (fn.lineno, fn.end_lineno)
            for _, fn in iter_functions(mod.tree)
            if any(
                isinstance(n, ast.Call) and _call_name(n).endswith("flock")
                for n in ast.walk(fn)
            )
        ]

        def in_flock_fn(line: int) -> bool:
            return any(a <= line <= b for a, b in flocky_spans)

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            target: Optional[ast.AST] = None
            how = ""
            if name == "open" and node.args:
                mode = self._mode_of(node)
                if mode is None or not _WRITE_MODES.match(mode):
                    continue
                if mode.startswith("x"):
                    continue  # exclusive-create is the atomic idiom
                target, how = node.args[0], f'open(.., "{mode}")'
            elif name == "os.open" and len(node.args) >= 2:
                flags = _src(mod, node.args[1])
                if "O_WRONLY" not in flags and "O_RDWR" not in flags:
                    continue
                if "O_EXCL" in flags or "O_APPEND" in flags:
                    continue
                if in_flock_fn(node.lineno):
                    continue  # locked in-place rewrite (LeaderLease)
                target, how = node.args[0], "os.open without O_EXCL"
            elif isinstance(node.func, ast.Attribute) and node.func.attr in (
                "write_text",
                "write_bytes",
            ):
                target, how = node.func.value, node.func.attr
            else:
                continue
            tsrc = _src(mod, target).lower()
            if "tmp" in tsrc:
                continue  # tmp+rename discipline, first half
            yield RawFinding(
                node.lineno,
                f"bare {how} on {_src(mod, target)!r} — state files must "
                "land via tmp + os.replace, an O_EXCL create, or os.link "
                "(torn/partial content must never be readable at the "
                "real path)",
            )

    @staticmethod
    def _mode_of(node: ast.Call) -> Optional[str]:
        if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
            v = node.args[1].value
            return v if isinstance(v, str) else None
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                v = kw.value.value
                return v if isinstance(v, str) else None
        if len(node.args) < 2:
            return "r"  # default mode: not a write
        return None  # dynamic mode: give it the benefit of the doubt


# ---------------------------------------------------------------------------
# swallowed-exception

_BROAD = {"Exception", "BaseException"}
_EMIT_HINTS = (
    "log",
    "warn",
    "error",
    "exception",
    "print",
    "emit",
    "event",
    "record",
    "report",
    "fail",
    "abort",
)


class SwallowedException(Rule):
    id = "swallowed-exception"
    summary = (
        "broad except handlers must emit an event/log, re-raise, or "
        "carry an explicit waiver"
    )

    @staticmethod
    def _is_broad(handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True  # bare except:
        names = []
        for n in [t] if not isinstance(t, ast.Tuple) else t.elts:
            if isinstance(n, ast.Name):
                names.append(n.id)
            elif isinstance(n, ast.Attribute):
                names.append(n.attr)
        return any(n in _BROAD for n in names)

    @staticmethod
    def _emits(handler: ast.ExceptHandler) -> bool:
        for n in ast.walk(handler):
            if isinstance(n, (ast.Raise, ast.Assert)):
                return True
            if isinstance(n, ast.Call):
                name = _call_name(n).lower()
                if any(h in name for h in _EMIT_HINTS):
                    return True
        return False

    def run(self, mod) -> Iterator[RawFinding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not self._is_broad(handler):
                    continue
                if self._emits(handler):
                    continue
                yield RawFinding(
                    handler.lineno,
                    "broad exception handler swallows the failure "
                    "silently — emit an event/log line, re-raise, or tag "
                    "the site '# invariant: waived — <reason>'",
                    span=(handler.lineno, handler.end_lineno or handler.lineno),
                )


# ---------------------------------------------------------------------------
# retry-discipline


class RetryDiscipline(Rule):
    id = "retry-discipline"
    summary = (
        "retry loops must sleep on the shared backoff.py schedule, "
        "never a bare fixed-interval time.sleep"
    )

    def scope(self, relpath: str) -> bool:
        return relpath != "backoff.py"

    def run(self, mod) -> Iterator[RawFinding]:
        # A sleep is a RETRY sleep when it sits inside an except handler
        # that itself sits inside a loop: the canonical
        # ``while: try: ... except: sleep(FIXED)`` shape that
        # synchronizes a gang into a thundering herd.
        stack: List[ast.AST] = []

        def visit(node):
            if (
                isinstance(node, ast.Call)
                and _call_name(node) in ("time.sleep", "sleep")
                and any(isinstance(a, ast.ExceptHandler) for a in stack)
            ):
                # the handler must be inside a loop
                for i, anc in enumerate(stack):
                    if isinstance(anc, (ast.While, ast.For)) and any(
                        isinstance(b, ast.ExceptHandler)
                        for b in stack[i + 1 :]
                    ):
                        yield RawFinding(
                            node.lineno,
                            "bare time.sleep in a retry loop — use "
                            "backoff.Backoff/retry_call so the schedule "
                            "is jittered, capped, and fault-plan "
                            "deterministic",
                        )
                        break
            stack.append(node)
            for child in ast.iter_child_nodes(node):
                yield from visit(child)
            stack.pop()

        yield from visit(mod.tree)


# ---------------------------------------------------------------------------
# clock-discipline

_SUSPECT = re.compile(
    r"(ttl|deadline|timeout|expir|for_s|clear_s|holdoff|not_before"
    r"|_age|age_|lease|heartbeat|delay)",
    re.IGNORECASE,
)


def _contains_wallclock(node: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Call) and _call_name(n) == "time.time"
        for n in ast.walk(node)
    )


class ClockDiscipline(Rule):
    id = "clock-discipline"
    summary = (
        "TTL/deadline/age math must use time.monotonic(); time.time() "
        "is for cross-process timestamps only"
    )

    def run(self, mod) -> Iterator[RawFinding]:
        for qual, fn in iter_functions(mod.tree):
            yield from self._scan_scope(mod, fn)
        yield from self._scan_scope(mod, mod.tree, module_scope=True)

    def _scan_scope(self, mod, scope, module_scope=False) -> Iterator[RawFinding]:
        # Names assigned (anywhere in this scope) from an expression
        # containing time.time() — one-level local dataflow.
        tainted: set = set()
        for node in self._own_nodes(scope, module_scope):
            if isinstance(node, ast.Assign) and _contains_wallclock(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        tainted.add(tgt.id)
        seen_lines: set = set()
        for node in self._own_nodes(scope, module_scope):
            sides: List[ast.AST] = []
            if isinstance(node, ast.Compare):
                sides = [node.left, *node.comparators]
            elif isinstance(node, ast.BinOp):
                sides = [node.left, node.right]
            elif isinstance(node, ast.Assign):
                # deadline = time.time() + x  (suspect TARGET name)
                if _contains_wallclock(node.value) and any(
                    isinstance(t, ast.Name) and _SUSPECT.search(t.id)
                    for t in node.targets
                ) and node.lineno not in seen_lines:
                    seen_lines.add(node.lineno)
                    yield self._finding(mod, node)
                continue
            else:
                continue
            def is_clocky(side: ast.AST) -> bool:
                if _contains_wallclock(side):
                    return True
                return isinstance(side, ast.Name) and side.id in tainted

            def is_suspect(side: ast.AST) -> bool:
                return bool(_SUSPECT.search(_src(mod, side)))

            if node.lineno in seen_lines:
                continue
            if any(is_clocky(s) for s in sides) and any(
                is_suspect(s) and not is_clocky(s) for s in sides
            ):
                seen_lines.add(node.lineno)
                yield self._finding(mod, node)

    @staticmethod
    def _own_nodes(scope, module_scope: bool):
        """Walk a scope WITHOUT descending into nested defs (each gets
        its own taint set); module scope skips all defs."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            n = stack.pop()
            if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ) or (module_scope and isinstance(n, ast.ClassDef)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def _finding(self, mod, node) -> RawFinding:
        return RawFinding(
            node.lineno,
            f"wall-clock time.time() in duration/deadline math "
            f"({_src(mod, node)[:60]!r}) — a clock step (NTP) stretches "
            "or collapses the interval; use time.monotonic(), or waive "
            "if the value crosses a process boundary",
        )


# ---------------------------------------------------------------------------
# fenced-store-write (project rule)

_STORE_PRIVATE = {
    "_persist",
    "_persist_inner",
    "_atomic_write",
    "_load_all",
    "_rescan_inner",
    "_sweep_stale_tmp",
}
_RAW_PATH_HINTS = ("persist_dir", "_path_for")


class FencedStoreWrite(ProjectRule):
    id = "fenced-store-write"
    summary = (
        "job-state mutations on the supervisor path must go through "
        "the lease-fenced JobStore API, never raw persistence"
    )

    def run(self, mods) -> Iterator[tuple]:
        in_scope = [
            m
            for m in mods
            if m.relpath.startswith(("controller/", "client/"))
        ]
        by_rel = {m.relpath: m for m in in_scope}
        # 1) JobStore persistence internals are store.py-private.
        for mod in in_scope:
            if mod.relpath.endswith("store.py"):
                continue
            for node in ast.walk(mod.tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _STORE_PRIVATE
                ):
                    yield mod, RawFinding(
                        node.lineno,
                        f"call of JobStore-private {node.func.attr}() "
                        "outside store.py — job persistence must route "
                        "through the fenced API (update/add/delete/"
                        "mark_*)",
                    )
        # 2) Raw writes on the supervisor-reachable path.
        prog = callgraph.build_program(in_scope)
        seeds = [
            fi
            for ci in prog.classes.get("Supervisor", ())
            for name, fi in ci.methods.items()
            if name in ("sync_once", "sync_forever", "_shard_tick")
        ]
        if not seeds:
            return
        reach = callgraph.reachable_from(seeds, prog)
        for (module, qualname) in sorted(reach):
            mod = by_rel.get(module)
            if mod is None or module.endswith("store.py"):
                continue
            fi = prog.functions[(module, qualname)]
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                name = _call_name(node)
                target = None
                if name == "open" and node.args:
                    mode = AtomicStateWrite._mode_of(node)
                    if mode is None or not _WRITE_MODES.match(mode):
                        continue
                    target = node.args[0]
                elif isinstance(
                    node.func, ast.Attribute
                ) and node.func.attr in ("write_text", "write_bytes"):
                    target = node.func.value
                else:
                    continue
                tsrc = _src(mod, target)
                if any(h in tsrc for h in _RAW_PATH_HINTS):
                    yield mod, RawFinding(
                        node.lineno,
                        f"raw write to a job-store path ({tsrc!r}) on "
                        f"the supervisor path ({qualname}) — only the "
                        "lease-fenced JobStore API may persist job "
                        "state",
                    )


# ---------------------------------------------------------------------------
# remediation-discipline (project rule)

# The only methods allowed to mutate persisted job state from the
# remediation engine: _commit (the single fenced write an action rides)
# and _adopt (failover healing, which must re-derive — never re-decide).
_REMEDIATION_COMMITTERS = {"_commit", "_adopt"}
# The only methods allowed to touch the fleet: the post-commit effectors.
_REMEDIATION_EFFECTORS = {"_delete_excess_workers", "_deliver"}
# Fleet-mutating calls on the runner/reconciler. list_for_job & friends
# are read-only and deliberately absent.
_FLEET_MUTATORS = {
    "create",
    "delete",
    "delete_many",
    "inject_preempt",
    "inject_kill",
    "restart_world",
    "preempt_world",
}
# Engine-private decision/commit internals: calling these from outside
# the engine would let another module actuate without the audit trail.
_REMEDIATION_PRIVATE = {"_commit", "_append", "_act", "_apply", "_plan", "_adopt"}


class RemediationDiscipline(ProjectRule):
    id = "remediation-discipline"
    summary = (
        "remediation actions must commit through the single lease-"
        "fenced store write before any fleet side effect; actuator "
        "writes that bypass that path break exactly-once"
    )

    def run(self, mods) -> Iterator[tuple]:
        rem = None
        for mod in mods:
            if mod.relpath.endswith("controller/remediation.py"):
                rem = mod
                continue
            # (c) engine-private internals are remediation.py-private:
            # a cross-module call of _commit/_act/... on a remediation
            # receiver is an actuation without the engine's audit path.
            for node in ast.walk(mod.tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REMEDIATION_PRIVATE
                    and "remediation" in _src(mod, node.func.value).lower()
                ):
                    yield mod, RawFinding(
                        node.lineno,
                        f"call of remediation-private {node.func.attr}() "
                        "outside controller/remediation.py — remediation "
                        "must act through evaluate() so every action "
                        "rides the fenced commit + audit trail",
                    )
        if rem is None:
            return
        spans = sorted(
            ((fn.lineno, fn.end_lineno or fn.lineno, qual) for qual, fn in iter_functions(rem.tree)),
            key=lambda t: t[1] - t[0],
        )

        def owner(line: int) -> str:
            # innermost enclosing def (spans sorted narrowest-first)
            for a, b, qual in spans:
                if a <= line <= b:
                    return qual.rsplit(".", 1)[-1]
            return ""

        for node in ast.walk(rem.tree):
            # (a) persisted-state mutations outside the commit/adopt pair
            # — a second store write would give supervisor failover a
            # window to replay the action (exactly-once broken).
            mutation = None
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                name = _call_name(node)
                attr = node.func.attr
                if attr == "touch" or (".store." in f".{name}" and attr in ("update", "add", "delete")):
                    mutation = f"{name}()"
                # (b) fleet actuations outside the post-commit effectors
                # — a pre-commit side effect is unfenced: a deposed
                # supervisor could actuate after losing its lease.
                elif attr in _FLEET_MUTATORS and (
                    "runner" in name or "reconciler" in name
                ):
                    fn = owner(node.lineno)
                    if fn in _REMEDIATION_EFFECTORS or fn.startswith("_effect_"):
                        continue
                    yield rem, RawFinding(
                        node.lineno,
                        f"fleet actuation {name}() outside a post-commit "
                        "effector (_effect_*/_delete_excess_workers/"
                        "_deliver) — side effects must run strictly "
                        "after the fenced commit",
                    )
                    continue
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                if any(
                    isinstance(t, ast.Attribute)
                    and t.attr == "remediation_generation"
                    for t in targets
                ):
                    mutation = "remediation_generation write"
            if mutation is None:
                continue
            fn = owner(node.lineno)
            if fn in _REMEDIATION_COMMITTERS:
                continue
            yield rem, RawFinding(
                node.lineno,
                f"persisted-state mutation ({mutation}) outside "
                "_commit/_adopt — every remediation must ride the one "
                "lease-fenced store write that bumps the generation",
            )


def module_rules() -> List[Rule]:
    return [
        AtomicStateWrite(),
        SwallowedException(),
        RetryDiscipline(),
        ClockDiscipline(),
    ]


def project_rules() -> List[ProjectRule]:
    from .locks import LockOrder

    return [FencedStoreWrite(), LockOrder(), RemediationDiscipline()]
