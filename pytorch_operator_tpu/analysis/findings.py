"""Finding model for the invariant checker.

A finding is (rule, file, line, message) plus a FINGERPRINT — a stable
content hash that survives unrelated edits elsewhere in the file. The
fingerprint is what ``analysis/baseline.json`` suppresses by, so a
baseline entry keeps suppressing its site as surrounding code moves,
and goes STALE (warning) the moment the flagged code itself changes or
disappears — the reviewer re-justifies or deletes it, never inherits
it blindly.

Fingerprint inputs, in order of stability intent:

- rule id (a site may be accepted for one invariant, not all),
- module path relative to the analysis root,
- the enclosing function's qualname (``Class.method`` — so two
  identical lines in different functions don't collide, and a line
  move WITHIN a function doesn't invalidate),
- the flagged source line with all whitespace removed,
- an ordinal among same-(rule, path, qualname, line-text) findings —
  last-resort disambiguation for truly identical sites.

Waivers: a site can be accepted inline instead of via the baseline
with a tag comment the analyzer recognizes::

    except Exception:
        pass  # invariant: waived — telemetry must never kill the step loop

The tag must carry a non-empty reason after the dash. It is honored on
the flagged line, the line directly above it, or (for region-shaped
findings like an ``except`` handler) anywhere in the finding's span.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

WAIVER_RE = re.compile(
    r"#\s*invariant:\s*waived\s*(?:—|–|--|-)\s*(?P<reason>\S.*?)\s*$"
)


@dataclass
class Finding:
    """One rule violation at one site."""

    rule: str
    path: str  # posix path relative to the analysis root
    line: int  # 1-based
    message: str
    qualname: str = ""  # enclosing function ("" = module scope)
    fingerprint: str = ""
    waived: bool = False
    waive_reason: str = ""
    # Baseline suppression is recorded by the engine, not stored here.

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "qualname": self.qualname,
            "fingerprint": self.fingerprint,
            "waived": self.waived,
            "waive_reason": self.waive_reason,
        }


@dataclass
class RawFinding:
    """What a rule emits before the engine attaches fingerprints and
    waiver state. ``span`` widens the waiver search window beyond the
    single flagged line (an ``except`` handler body, a ``with`` block)."""

    line: int
    message: str
    span: Optional[Tuple[int, int]] = None  # inclusive (start, end) lines


def scan_waivers(lines: List[str]) -> Dict[int, str]:
    """line (1-based) -> waiver reason, for every tagged line."""
    out: Dict[int, str] = {}
    for i, text in enumerate(lines, start=1):
        m = WAIVER_RE.search(text)
        if m:
            out[i] = m.group("reason")
    return out


def find_waiver(
    waivers: Dict[int, str],
    line: int,
    span: Optional[Tuple[int, int]] = None,
) -> Optional[str]:
    """The waiver reason covering a finding, or None. Checked: the
    flagged line, the line above it, then every line of ``span``."""
    for cand in (line, line - 1):
        if cand in waivers:
            return waivers[cand]
    if span is not None:
        for cand in range(span[0], span[1] + 1):
            if cand in waivers:
                return waivers[cand]
    return None


def _norm(line_text: str) -> str:
    return "".join(line_text.split())


def fingerprint_findings(
    findings: List[Finding], lines_by_path: Dict[str, List[str]]
) -> None:
    """Attach fingerprints in place. Ordinals are assigned in (path,
    line) order among identical (rule, path, qualname, normalized
    line text) tuples, so the Nth identical site keeps the Nth
    fingerprint as long as the earlier ones survive."""
    seen: Dict[Tuple[str, str, str, str], int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        lines = lines_by_path.get(f.path, [])
        text = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        key = (f.rule, f.path, f.qualname, _norm(text))
        ordinal = seen.get(key, 0)
        seen[key] = ordinal + 1
        h = hashlib.blake2b(
            "|".join((*key, str(ordinal))).encode(), digest_size=8
        ).hexdigest()
        f.fingerprint = h
