"""A deliberately conservative static call graph for the invariant
rules that need reachability (fenced-store-write) or inter-procedural
lock tracking (lock-order).

Resolution is NAME-BASED but narrow — precision beats recall here,
because an over-approximated edge can manufacture a fake lock cycle:

- ``self.foo(...)``          -> method ``foo`` of the enclosing class
                                (same module; single-inheritance base
                                in the same module is followed too)
- ``self.attr.foo(...)``     -> method ``foo`` of the class that
                                ``self.attr = ClassName(...)`` assigned
                                in the SAME class (any method, usually
                                ``__init__``) — the typed-attribute map
- ``foo(...)``               -> module-level function ``foo`` in the
                                same module
- ``ClassName(...)``         -> ``ClassName.__init__`` when the class
                                is in the analyzed set

Anything else (``job.foo()``, imported callables, dynamic dispatch)
resolves to nothing on purpose.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}


@dataclass
class FunctionInfo:
    qualname: str  # "Class.method" or "func"
    module: str  # module relpath
    node: ast.AST  # FunctionDef / AsyncFunctionDef
    class_name: Optional[str] = None


@dataclass
class ClassInfo:
    name: str
    module: str
    bases: List[str] = field(default_factory=list)
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)
    # self.<attr> = ClassName(...)  ->  {attr: ClassName}
    attr_types: Dict[str, str] = field(default_factory=dict)
    # self.<attr> = threading.Lock()/RLock()/Condition()
    lock_attrs: Set[str] = field(default_factory=set)


@dataclass
class Program:
    """The analyzed function/class universe across modules."""

    functions: Dict[Tuple[str, str], FunctionInfo] = field(
        default_factory=dict
    )  # (module, qualname) -> info
    classes: Dict[str, List[ClassInfo]] = field(
        default_factory=dict
    )  # class name -> infos (name collisions possible across modules)
    module_funcs: Dict[Tuple[str, str], FunctionInfo] = field(
        default_factory=dict
    )  # (module, bare name) -> module-level function

    def class_in_module(self, name: str, module: str) -> Optional[ClassInfo]:
        for ci in self.classes.get(name, ()):
            if ci.module == module:
                return ci
        infos = self.classes.get(name, [])
        return infos[0] if len(infos) == 1 else None

    def method_of(self, ci: ClassInfo, name: str) -> Optional[FunctionInfo]:
        """Method lookup with single-level base-class fallback."""
        fi = ci.methods.get(name)
        if fi is not None:
            return fi
        for base in ci.bases:
            bi = self.class_in_module(base, ci.module)
            if bi is not None and name in bi.methods:
                return bi.methods[name]
        return None


def _ctor_name(value: ast.AST) -> Optional[str]:
    """``ClassName(...)`` / ``mod.ClassName(...)`` -> "ClassName"."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def build_program(modules) -> Program:
    """``modules``: iterable of objects with ``.relpath`` and ``.tree``."""
    prog = Program()
    for mod in modules:
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FunctionInfo(node.name, mod.relpath, node)
                prog.functions[(mod.relpath, node.name)] = fi
                prog.module_funcs[(mod.relpath, node.name)] = fi
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(
                    name=node.name,
                    module=mod.relpath,
                    bases=[
                        b.id
                        for b in node.bases
                        if isinstance(b, ast.Name)
                    ],
                )
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        qual = f"{node.name}.{item.name}"
                        fi = FunctionInfo(
                            item.name, mod.relpath, item, node.name
                        )
                        fi.qualname = qual
                        ci.methods[item.name] = fi
                        prog.functions[(mod.relpath, qual)] = fi
                    # self.<attr> = <ctor>() typing + lock attrs, from
                    # every method (locks are usually made in __init__
                    # but lazily-created ones count too).
                for item in ast.walk(node):
                    if not isinstance(item, ast.Assign):
                        continue
                    for tgt in item.targets:
                        if not (
                            isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                        ):
                            continue
                        ctor = _ctor_name(item.value)
                        if ctor is None:
                            continue
                        if ctor in _LOCK_CTORS:
                            ci.lock_attrs.add(tgt.attr)
                        else:
                            ci.attr_types.setdefault(tgt.attr, ctor)
                prog.classes.setdefault(node.name, []).append(ci)
    return prog


def resolve_call(
    call: ast.Call, caller: FunctionInfo, prog: Program
) -> List[FunctionInfo]:
    """The FunctionInfos a call MAY dispatch to (empty when unknown)."""
    f = call.func
    # foo(...) -> same-module function, or ClassName(...) -> __init__
    if isinstance(f, ast.Name):
        fi = prog.module_funcs.get((caller.module, f.id))
        if fi is not None:
            return [fi]
        ci = prog.class_in_module(f.id, caller.module)
        if ci is not None:
            init = prog.method_of(ci, "__init__")
            return [init] if init is not None else []
        return []
    if not isinstance(f, ast.Attribute):
        return []
    recv = f.value
    # self.foo(...)
    if isinstance(recv, ast.Name) and recv.id == "self":
        if caller.class_name is None:
            return []
        ci = prog.class_in_module(caller.class_name, caller.module)
        if ci is None:
            return []
        fi = prog.method_of(ci, f.attr)
        return [fi] if fi is not None else []
    # self.attr.foo(...) via the typed-attribute map
    if (
        isinstance(recv, ast.Attribute)
        and isinstance(recv.value, ast.Name)
        and recv.value.id == "self"
        and caller.class_name is not None
    ):
        ci = prog.class_in_module(caller.class_name, caller.module)
        if ci is None:
            return []
        tname = ci.attr_types.get(recv.attr)
        if tname is None:
            return []
        ti = prog.class_in_module(tname, caller.module) or (
            prog.classes.get(tname, [None])[0]
        )
        if ti is None:
            return []
        fi = prog.method_of(ti, f.attr)
        return [fi] if fi is not None else []
    return []


def reachable_from(
    seeds: List[FunctionInfo], prog: Program
) -> Set[Tuple[str, str]]:
    """Transitive closure of (module, qualname) over resolve_call."""
    seen: Set[Tuple[str, str]] = set()
    stack = list(seeds)
    while stack:
        fi = stack.pop()
        key = (fi.module, fi.qualname)
        if key in seen:
            continue
        seen.add(key)
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                for callee in resolve_call(node, fi, prog):
                    if (callee.module, callee.qualname) not in seen:
                        stack.append(callee)
    return seen
