"""tpujob's project-native invariant checker (``tpujob
verify-invariants``): stdlib-``ast`` static analysis that mechanizes
the correctness contracts the control/data/serve planes were reviewed
against. See :mod:`.rules` for the rule catalog and ARCHITECTURE.md
("Static analysis & invariant catalog") for the operator view.
"""

from .baseline import Baseline, BaselineEntry, BaselineError, BaselineResult
from .engine import (
    AnalysisIO,
    Report,
    SourceModule,
    analyze,
    discover_sources,
    run_verify,
)
from .findings import Finding, RawFinding, WAIVER_RE, scan_waivers

__all__ = [
    "AnalysisIO",
    "Baseline",
    "BaselineEntry",
    "BaselineError",
    "BaselineResult",
    "Finding",
    "RawFinding",
    "Report",
    "SourceModule",
    "WAIVER_RE",
    "analyze",
    "discover_sources",
    "run_verify",
    "scan_waivers",
]
