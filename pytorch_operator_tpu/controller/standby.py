"""Pre-warmed standby replicas — the schedule-to-first-step accelerator.

BASELINE.md's latency breakdown puts a ~5s floor under even a warm
(compile-cached) job start: process spawn + ``import jax`` (and friends)
+ backend init, all paid serially before the workload's first line runs.
The reference has no analog (kubelet image pulls / container starts are
its version of this cost, and it never attacks them); this is TPU-native
performance work on the BASELINE.json:2 north-star metric.

Design: the supervisor keeps N **standby** processes that have already
paid the interpreter + heavy-import cost (jax/flax/optax/numpy — NO
device client: standbys must not contend with live jobs for the TPU, per
BASELINE.md's contention note; the client is acquired lazily after
assignment). ``SubprocessRunner.create`` hands a job to a ready standby
instead of spawning cold:

1. runner writes ``<id>.assign.json`` (atomic tmp+rename) into the pool
   dir and waits briefly for the claim ack;
2. the standby (polling) renames it to ``<id>.assign.claimed``, applies
   the injected env wholesale, re-applies the jax options whose env vars
   were already consumed at import (config.update), redirects
   stdout/stderr onto the replica's log file, and runs the template
   module in-process via ``runpy`` as ``__main__``;
3. on completion it writes the exit-capture file (same protocol as the
   cold path's sh wrapper) and exits with the workload's code.

One job per standby — the process dies with its job and the pool
replenishes on the next sync pass, so replica isolation semantics are
unchanged: the handle's pid IS the workload's pid, signals/kill
escalation/adoption all behave exactly as for cold spawns. Only
``module`` templates are eligible (exec'ing an arbitrary ``command``
argv would discard the warm imports); anything else falls back to a cold
spawn, as does an assignment whose ack times out (standby died between
readiness check and claim).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Dict, Optional, Tuple

# jax options whose environment variables are read ONCE at import time:
# the standby imported jax long before the job's env existed, so these
# must be re-applied through jax.config after the env lands.
_JAX_ENV_CONFIG = (
    ("JAX_COMPILATION_CACHE_DIR", "jax_compilation_cache_dir"),
    ("JAX_PLATFORMS", "jax_platforms"),
    (
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
        "jax_persistent_cache_min_compile_time_secs",
    ),
)


def _coerce(cfg_key: str, raw: str):
    """jax.config options are typed; env vars are strings."""
    if cfg_key == "jax_persistent_cache_min_compile_time_secs":
        return float(raw)
    return raw


# ---- the standby process ----


def _preimport() -> None:
    """Pay the heavy imports up front. Deliberately NO jax.devices() /
    backend creation — device acquisition stays lazy (contention)."""
    import numpy  # noqa: F401
    import jax  # noqa: F401
    import flax.linen  # noqa: F401
    import optax  # noqa: F401


def _run_assignment(spec: dict) -> int:
    """Become the replica: env, log redirect, cwd, run the module."""
    import runpy
    import traceback

    env = spec.get("env") or {}
    os.environ.clear()
    os.environ.update(env)
    # PYTHONPATH was consumed by the interpreter at standby startup; the
    # job's entries must land on sys.path too, or a module that imports
    # fine on the cold path ImportErrors on the warm one.
    for entry in reversed(env.get("PYTHONPATH", "").split(os.pathsep)):
        if entry and entry not in sys.path:
            sys.path.insert(0, entry)
    import jax

    for env_key, cfg_key in _JAX_ENV_CONFIG:
        if env.get(env_key):
            try:
                jax.config.update(cfg_key, _coerce(cfg_key, env[env_key]))
            except Exception:
                # invariant: waived — unknown option on this jax version; the env-var route still applies it
                pass
    # Route all output to the replica's log file (kubectl-logs analog) —
    # fd-level dup2 so subprocesses and C extensions follow too.
    log_fd = os.open(
        spec["log_path"], os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
    )
    sys.stdout.flush()
    sys.stderr.flush()
    os.dup2(log_fd, 1)
    os.dup2(log_fd, 2)
    os.close(log_fd)
    if spec.get("cwd"):
        os.chdir(spec["cwd"])
    sys.argv = [spec["module"]] + list(spec.get("args") or [])
    code = 0
    try:
        runpy.run_module(spec["module"], run_name="__main__", alter_sys=True)
    except SystemExit as e:
        if isinstance(e.code, int):
            code = e.code
        elif e.code is not None:
            print(e.code, file=sys.stderr)
            code = 1
    except BaseException:
        traceback.print_exc()
        code = 1
    sys.stdout.flush()
    sys.stderr.flush()
    # Exit-capture protocol (same file the cold path's sh wrapper writes).
    try:
        ef = spec["exit_path"]
        tmp = ef + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(code))
        os.replace(tmp, ef)
    except OSError:
        pass
    return code


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--dir", required=True, help="pool directory")
    p.add_argument("--id", required=True, help="this standby's id")
    p.add_argument(
        "--parent", type=int, default=None,
        help="supervisor pid: exit when reparented away from it",
    )
    args = p.parse_args(argv)
    pool = Path(args.dir)
    assign = pool / f"{args.id}.assign.json"
    claimed = pool / f"{args.id}.assign.claimed"
    _preimport()
    ready_tmp = pool / f"{args.id}.ready.tmp"
    ready_tmp.write_text(str(os.getpid()))
    ready_tmp.replace(pool / f"{args.id}.ready")
    while True:
        # Orphan guards: a supervisor that died without shutdown() (crash,
        # SIGKILL) must not leak a 50 Hz poll loop pinning jax-sized RSS
        # forever. Reparenting away from the RECORDED parent pid (not a
        # bare ppid==1 test, which would misfire when the supervisor
        # itself is pid 1 in a container) or the pool dir vanishing both
        # mean the pool is gone.
        if not pool.is_dir() or (
            args.parent is not None and os.getppid() != args.parent
        ):
            return 0
        if assign.exists():
            try:
                spec = json.loads(assign.read_text())
            except (OSError, ValueError):
                # invariant: waived — 10ms paced re-read of an assign file caught mid-rename, not a retry loop
                time.sleep(0.01)
                continue
            try:
                assign.replace(claimed)  # the ack the runner waits on
            except OSError:
                return 0  # pool dir torn down underneath us
            return _run_assignment(spec)
        time.sleep(0.02)


# ---- the supervisor-side pool ----


class StandbyPool:
    """Spawn/track/assign standby processes (supervisor side).

    Thread-safe; ``replenish()`` is called from the runner's sync pass.
    Standbys consume no scheduler slots — they hold no devices.
    """

    ACK_TIMEOUT_S = 2.0

    def __init__(self, state_dir: Path, size: int):
        self.dir = Path(state_dir) / "standby"
        self.dir.mkdir(parents=True, exist_ok=True)
        self.log_dir = Path(state_dir) / "logs"
        self.log_dir.mkdir(parents=True, exist_ok=True)
        # Crash-loop backoff: a standby that dies before ever reaching
        # READY (broken env, jax ImportError) must not re-pay a full
        # interpreter+jax import every sync pass forever.
        self._fail_streak = 0
        self._not_before = 0.0
        self.size = size
        self._procs: Dict[str, subprocess.Popen] = {}
        self._counter = 0
        self._lock = threading.Lock()

    def _files(self, sid: str):
        return [
            self.dir / f"{sid}{suffix}"
            for suffix in (".ready", ".assign.json", ".assign.claimed")
        ]

    def _spawn_one(self) -> bool:
        sid = f"s{os.getpid()}-{self._counter}"
        self._counter += 1
        env = dict(os.environ)
        pkg_root = str(Path(__file__).resolve().parents[2])
        parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
        if pkg_root not in parts:
            parts.insert(0, pkg_root)
        env["PYTHONPATH"] = os.pathsep.join(parts)
        env["PYTHONUNBUFFERED"] = "1"
        log_f = open(self.log_dir / f"standby-{sid}.log", "ab")
        try:
            proc = subprocess.Popen(
                [
                    sys.executable, "-m",
                    "pytorch_operator_tpu.controller.standby",
                    "--dir", str(self.dir), "--id", sid,
                    "--parent", str(os.getpid()),
                ],
                env=env,
                stdout=log_f,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        except OSError:
            log_f.close()
            return False
        log_f.close()  # the child owns the fd now
        self._procs[sid] = proc
        return True

    def set_size(self, size: int) -> None:
        """Retarget the pool (takes effect on the next replenish; shrink
        does not kill live standbys). size=0 pauses replenishment — e.g.
        while a latency measurement must not share the host core with a
        fresh standby's import burst."""
        with self._lock:
            self.size = size

    def replenish(self) -> None:
        """Reap dead standbys, top the pool back up to ``size``.

        Crash-looping standbys back off exponentially (up to 60s between
        spawn attempts): each reap of a standby that died before ever
        reaching READY doubles the wait; any standby reaching READY
        resets it. Dead standbys' log files are rotated into ONE
        ``standby-last-failure.log`` (nonzero exits) or deleted (clean
        exits) — a long-lived daemon must not grow logs/ unboundedly.
        """
        with self._lock:
            for sid, proc in list(self._procs.items()):
                if proc.poll() is not None:
                    self._procs.pop(sid)
                    was_ready = (self.dir / f"{sid}.ready").exists()
                    for f in self._files(sid):
                        f.unlink(missing_ok=True)
                    log = self.log_dir / f"standby-{sid}.log"
                    if proc.returncode != 0:
                        # Keep exactly one failure log for diagnosis.
                        try:
                            log.replace(self.log_dir / "standby-last-failure.log")
                        except OSError:
                            log.unlink(missing_ok=True)
                    else:
                        log.unlink(missing_ok=True)
                    if not was_ready:
                        self._fail_streak += 1
                        delay = min(60.0, 2.0 ** min(self._fail_streak, 6))
                        # monotonic: an NTP step must not collapse the
                        # crash-loop holdoff (respawn storm) or stretch
                        # it (pool stays empty for minutes).
                        self._not_before = time.monotonic() + delay
                        print(
                            f"[standby] {sid} died (exit {proc.returncode}) "
                            f"before READY — backing off {delay:.0f}s "
                            f"(see logs/standby-last-failure.log)",
                            file=sys.stderr,
                        )
            if any(
                (self.dir / f"{sid}.ready").exists() for sid in self._procs
            ):
                self._fail_streak = 0
            if time.monotonic() < self._not_before:
                return
            # Bounded: a persistent spawn failure (fork limit, ENOMEM)
            # must not busy-loop under the pool lock — try once per
            # missing slot, retry on the next sync pass.
            for _ in range(max(self.size - len(self._procs), 0)):
                if not self._spawn_one():
                    break

    def ready_count(self) -> int:
        with self._lock:
            return sum(
                1
                for sid, proc in self._procs.items()
                if proc.poll() is None and (self.dir / f"{sid}.ready").exists()
            )

    def take(self) -> Optional[Tuple[str, subprocess.Popen]]:
        """Pop a ready, live standby (or None). The caller MUST follow
        with assign() or kill()."""
        with self._lock:
            for sid, proc in list(self._procs.items()):
                if proc.poll() is None and (self.dir / f"{sid}.ready").exists():
                    self._procs.pop(sid)
                    # Reaching READY proves the spawn path works — reset
                    # the crash-loop backoff here too, not only when a
                    # replenish pass happens to observe the ready marker
                    # (a standby claimed between passes, or a pool that
                    # drains to empty, would otherwise leave a stale
                    # streak that jumps one later pre-READY death
                    # straight to the capped backoff).
                    self._fail_streak = 0
                    self._not_before = 0.0
                    return sid, proc
        return None

    def assign(self, sid: str, proc: subprocess.Popen, spec: dict) -> bool:
        """Hand a job spec to a taken standby; True once the standby
        acked the claim. On timeout (it died under us) the standby is
        killed and False returned — the caller cold-spawns instead."""
        tmp = self.dir / f"{sid}.assign.json.tmp"
        target = self.dir / f"{sid}.assign.json"
        claimed = self.dir / f"{sid}.assign.claimed"
        try:
            tmp.write_text(json.dumps(spec))
            tmp.replace(target)
        except OSError:
            self.kill(sid, proc)
            return False
        # monotonic: the ACK window is a within-process budget; a clock
        # step here would either kill a healthy standby mid-claim or
        # stall assignment on a dead one.
        deadline = time.monotonic() + self.ACK_TIMEOUT_S
        while time.monotonic() < deadline:
            if claimed.exists():
                claimed.unlink(missing_ok=True)
                # The sid leaves the pool here: drop its ready marker AND
                # its pre-handoff log (output goes to the replica's own
                # log from the claim's dup2 onward) so a long-lived
                # daemon doesn't leak files per warm job.
                (self.dir / f"{sid}.ready").unlink(missing_ok=True)
                (self.log_dir / f"standby-{sid}.log").unlink(missing_ok=True)
                return True
            if proc.poll() is not None:
                break
            time.sleep(0.01)
        self.kill(sid, proc)
        target.unlink(missing_ok=True)
        return False

    def kill(self, sid: str, proc: subprocess.Popen) -> None:
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, 9)
            except (ProcessLookupError, PermissionError):
                pass
        for f in self._files(sid):
            f.unlink(missing_ok=True)
        (self.log_dir / f"standby-{sid}.log").unlink(missing_ok=True)

    def shutdown(self) -> None:
        """Kill every idle standby (assigned ones became job replicas and
        belong to the runner's normal teardown path)."""
        with self._lock:
            for sid, proc in list(self._procs.items()):
                self.kill(sid, proc)
            self._procs.clear()


if __name__ == "__main__":
    sys.exit(main())
