"""Replica process runners — the pod-control analog.

Reference: pod creation/deletion via ``podControl`` and the kubelet actually
running containers (SURVEY.md §3.2–3.3). Locally a *replica* is an OS
process. Two runners share one interface:

- :class:`SubprocessRunner` — the real thing: ``subprocess.Popen`` with
  injected env, per-replica log files, termination with escalation.
- :class:`FakeRunner` — the fake-clientset analog (SURVEY.md §4): records
  create/delete actions, and tests drive phases by hand
  (``set_phase(name, FAILED, exit_code=137)``) — no processes involved.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional

from ..api.types import ProcessTemplate, ReplicaPhase, ReplicaType
from .store import key_to_fs


def replica_name(job_key: str, rtype: ReplicaType, index: int) -> str:
    """Canonical replica name: ``<ns>/<job>-<type>-<index>`` (pod-name analog)."""
    return f"{job_key}-{rtype.value.lower()}-{index}"


def _proc_stat(pid: int):
    """(start_ticks, state, pgrp) from ``/proc/<pid>/stat``, or None if gone.

    The comm field (2) may contain spaces/parens, so split after the LAST
    ``)``. start_ticks (field 22) uniquely stamps a pid incarnation —
    the guard against pid reuse when adopting persisted records.
    """
    try:
        # Binary read: comm is arbitrary bytes (prctl PR_SET_NAME), so a
        # text-mode open could raise UnicodeDecodeError on a host process
        # we merely scanned past.
        with open(f"/proc/{pid}/stat", "rb") as f:
            raw = f.read()
    except OSError:
        return None
    rest = raw[raw.rfind(b")") + 2 :].split()
    return int(rest[19]), rest[0].decode("ascii"), int(rest[2])


def _pid_alive(pid: Optional[int], start_ticks: Optional[int]) -> bool:
    """Is this exact process incarnation still running (zombies count as
    dead — an orphan reparented to a non-reaping pid 1 stays 'Z')?"""
    if pid is None:
        return False
    stat = _proc_stat(pid)
    if stat is None or stat[1] == "Z":
        return False
    return start_ticks is None or stat[0] == start_ticks


def _group_members_alive(pgid: int) -> bool:
    """Any non-zombie process left in this process group? The exit-capture
    wrapper dies instantly on SIGTERM, so the wrapper's own exit proves
    nothing about the replica underneath — liveness and termination must be
    judged on the whole group. (A pid number stays allocated while it is a
    live pgid, so members found here are ours, not a pid-reuse stranger —
    up to the unavoidable full-wraparound edge once the group empties.)"""
    return pgid in _live_pgids()


def _live_pgids() -> set:
    """One /proc pass: the set of process groups with a non-zombie member."""
    out = set()
    for d in os.listdir("/proc"):
        if not d.isdigit():
            continue
        stat = _proc_stat(int(d))
        if stat is not None and stat[1] != "Z":
            out.add(stat[2])
    return out


def _replica_alive(
    pid: Optional[int], start_ticks: Optional[int], live_pgids: Optional[set] = None
) -> bool:
    """Replica liveness = wrapper pid alive OR any group member alive (a
    TERM-trapping replica can outlive its wrapper).

    Ordering matters for the pid-reuse guard: a LIVE pid with mismatched
    start ticks proves the pid was recycled to a stranger (our whole group
    must have emptied for the kernel to free the number), so the group
    check applies only when the wrapper pid itself is dead/zombie.
    ``live_pgids`` lets a caller amortize the /proc pass over many replicas.
    """
    if pid is None:
        return False
    stat = _proc_stat(pid)
    if stat is not None and stat[1] != "Z":
        return start_ticks is None or stat[0] == start_ticks
    if live_pgids is not None:
        return pid in live_pgids
    return _group_members_alive(pid)


# Wrapper that records the replica's exit code to a file the supervisor can
# read after a restart (the pod-status analog: exit codes survive the
# controller). The child runs in the wrapper's process group; a group
# signal that kills the wrapper too (SIGKILL preemption) leaves no file,
# which adoption classifies as a signal death (137, retryable).
_EXIT_CAPTURE_SH = (
    'ef="$1"; shift; "$@"; rc=$?; '
    'printf %s "$rc" > "$ef.tmp" && mv -f "$ef.tmp" "$ef"; exit "$rc"'
)


def replica_slots(template: ProcessTemplate) -> int:
    """Scheduling weight of one replica in device slots (reference: pods
    request resource QUANTITIES — ``google.com/tpu: N`` — and the
    scheduler sums them; a replica asking for 4 chips occupies 4 slots of
    ``--max-slots`` capacity). Minimum 1: even a device-less control
    process occupies a scheduling slot."""
    r = template.resources
    return max(1, r.tpu_chips, r.cpu_devices)


def normalize_exit_code(code: Optional[int]) -> Optional[int]:
    """Map Popen's signal encoding (-N) to the container convention (128+N)
    the ExitCode restart policy is defined against — so SIGKILL surfaces as
    137 (retryable), matching the reference's pod-level semantics."""
    if code is not None and code < 0:
        return 128 - code
    return code


@dataclass
class ReplicaHandle:
    """Tracking record for one replica process (pod-object analog)."""

    name: str
    job_key: str
    replica_type: ReplicaType
    index: int
    phase: ReplicaPhase = ReplicaPhase.PENDING
    exit_code: Optional[int] = None
    pid: Optional[int] = None
    created_at: float = 0.0
    finished_at: Optional[float] = None
    log_path: Optional[str] = None
    slots: int = 1  # device-slot weight (replica_slots of the template)

    def is_active(self) -> bool:
        return self.phase in (ReplicaPhase.PENDING, ReplicaPhase.RUNNING)

    def is_finished(self) -> bool:
        return self.phase in (ReplicaPhase.SUCCEEDED, ReplicaPhase.FAILED)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "job_key": self.job_key,
            "replica_type": self.replica_type.value,
            "index": self.index,
            "phase": self.phase.value,
            "exit_code": self.exit_code,
            "pid": self.pid,
            "created_at": self.created_at,
            "finished_at": self.finished_at,
            "log_path": self.log_path,
            "slots": self.slots,
        }


class ProcessRunner:
    """Interface both runners implement."""

    def create(
        self,
        job_key: str,
        rtype: ReplicaType,
        index: int,
        template: ProcessTemplate,
        env: Dict[str, str],
    ) -> ReplicaHandle:
        raise NotImplementedError

    def delete(self, name: str, grace_seconds: float = 5.0) -> None:
        raise NotImplementedError

    def delete_many(self, names: List[str], grace_seconds: float = 5.0) -> None:
        """Tear down several replicas; runners with a real kill-escalation
        wait override this to share one escalation across the batch."""
        for name in names:
            self.delete(name, grace_seconds)

    def sync(self) -> None:
        """Poll live processes and update phases (informer-refresh analog)."""

    def list_for_job(self, job_key: str) -> List[ReplicaHandle]:
        raise NotImplementedError

    def get(self, name: str) -> Optional[ReplicaHandle]:
        raise NotImplementedError

    def remove_record(self, name: str) -> None:
        """Forget a finished replica's record (pod object deletion analog)."""
        raise NotImplementedError

    def schedulable_slots(self) -> Optional[int]:
        """Free scheduling slots, or None for unlimited (gang admission input)."""
        return None

    def rescan(self, key_filter=None) -> None:
        """Adopt state left by another incarnation (hot-standby takeover);
        no-op for runners without persistence. ``key_filter`` (job key →
        bool) limits adoption to owned jobs — a SHARDED supervisor must
        not start tracking (and counting against its capacity) replicas
        another shard owner reconciles."""

    def take_changed_keys(self) -> Optional[set]:
        """Job keys whose replica set changed (create/delete/phase
        transition/kill) since the last call, consumed. Returns None
        when this runner does not track changes — callers must then
        assume EVERYTHING changed (disables the supervisor's steady
        fast path, never its correctness)."""
        return None

    def forget_job(self, job_key: str) -> None:
        """Drop in-memory tracking of a job's replicas WITHOUT touching
        the processes or their persisted records — the shard hand-off
        primitive: the releasing supervisor forgets, the new owner
        adopts via ``rescan``."""

    def capacity_slots(self) -> Optional[int]:
        """Total device-slot capacity, or None for unbounded."""
        return None

    def list_all(self) -> List[ReplicaHandle]:
        """Every tracked replica handle (all jobs)."""
        raise NotImplementedError

    def set_slots(self, name: str, slots: int) -> None:
        """Correct a replica's device-slot weight (template is the source
        of truth; records from pre-weight supervisors need healing)."""
        h = self.get(name)
        if h is not None:
            h.slots = slots

    def inject_kill(self, name: str) -> None:
        """Fault-injection site (faults/): make this replica die as if
        the host preempted it — an abrupt SIGKILL-style death, NOT a
        graceful delete (the record survives so the reconciler walks the
        real failure-classification path: exit 137, retryable)."""

    def inject_preempt(self, name: str) -> None:
        """Fault-injection site (faults/ ``preempt_replica``): a
        SIGTERM-with-grace death, distinct from :meth:`inject_kill`'s
        abrupt SIGKILL — models a managed eviction (exit 143, retryable).
        Runners without real signals fall back to kill semantics."""
        self.inject_kill(name)

    def standby_ready(self) -> int:
        """Warm standby processes ready for promotion (hot spares);
        0 for runners without a pool."""
        return 0

    def set_standby_target(self, n: int) -> None:
        """Size the warm-standby pool (lazily created on first nonzero
        target); no-op for runners without one."""


class FakeRunner(ProcessRunner):
    """In-memory runner for controller tests (fake clientset analog).

    Created replicas start PENDING; tests move them with :meth:`set_phase`.
    Every create/delete is appended to :attr:`actions` for assertions, and
    the env each replica was created with is kept in :attr:`envs`.
    """

    def __init__(self, capacity: Optional[int] = None):
        self.handles: Dict[str, ReplicaHandle] = {}
        # Warm-standby model for hot-spare tests: a plain counter (set
        # directly or via set_standby_target) that standby_ready returns.
        self.standby = 0
        # Per-job handle index: list_for_job is the reconciler's hottest
        # read (every sync of every job), and a flat scan of ALL handles
        # made a pass O(jobs x replicas) in pure bookkeeping.
        self._by_job: Dict[str, Dict[str, ReplicaHandle]] = {}
        self.envs: Dict[str, Dict[str, str]] = {}
        self.templates: Dict[str, ProcessTemplate] = {}
        self.actions: List[tuple] = []
        self.capacity = capacity  # None = unlimited
        # Same thread-safety contract as SubprocessRunner: per-key reconcile
        # locks serialize same-key access, but different keys hit the shared
        # dicts concurrently (tests/test_stress.py).
        self._lock = threading.RLock()
        # Job keys with replica-set changes since the last drain — feeds
        # the supervisor's steady fast path.
        self._changed_keys: set = set()

    def create(self, job_key, rtype, index, template, env):
        from .. import faults

        name = replica_name(job_key, rtype, index)
        with self._lock:
            if name in self.handles:
                raise RuntimeError(f"duplicate create for {name}")
            env = faults.thread_env(dict(env))
            inj = faults.active()
            if inj is not None and inj.spawn_should_fail(rtype.value, index):
                h = ReplicaHandle(
                    name=name,
                    job_key=job_key,
                    replica_type=rtype,
                    index=index,
                    phase=ReplicaPhase.FAILED,
                    exit_code=128 + 9,  # launch casualty: retryable
                    created_at=time.time(),
                    finished_at=time.time(),
                    slots=replica_slots(template),
                )
            else:
                h = ReplicaHandle(
                    name=name,
                    job_key=job_key,
                    replica_type=rtype,
                    index=index,
                    phase=ReplicaPhase.PENDING,
                    created_at=time.time(),
                    slots=replica_slots(template),
                )
            self.handles[name] = h
            self._by_job.setdefault(job_key, {})[name] = h
            self.envs[name] = dict(env)
            self.templates[name] = template
            self.actions.append(("create", name))
            self._changed_keys.add(job_key)
            return h

    def _index_pop(self, name: str) -> Optional[ReplicaHandle]:
        h = self.handles.pop(name, None)
        if h is not None:
            per_job = self._by_job.get(h.job_key)
            if per_job is not None:
                per_job.pop(name, None)
                if not per_job:
                    self._by_job.pop(h.job_key, None)
        return h

    def delete(self, name, grace_seconds: float = 5.0):
        with self._lock:
            self.actions.append(("delete", name))
            h = self._index_pop(name)
            if h is not None:
                self.envs.pop(name, None)
                self.templates.pop(name, None)
                self._changed_keys.add(h.job_key)

    def sync(self):
        pass

    def take_changed_keys(self):
        with self._lock:
            out, self._changed_keys = self._changed_keys, set()
            return out

    def forget_job(self, job_key):
        with self._lock:
            for name in list(self._by_job.get(job_key, {})):
                self._index_pop(name)
                self.envs.pop(name, None)
                self.templates.pop(name, None)

    def list_for_job(self, job_key):
        with self._lock:
            return list(self._by_job.get(job_key, {}).values())

    def get(self, name):
        with self._lock:
            return self.handles.get(name)

    def remove_record(self, name):
        with self._lock:
            h = self._index_pop(name)
            if h is not None:
                self._changed_keys.add(h.job_key)

    def schedulable_slots(self):
        with self._lock:
            if self.capacity is None:
                return None
            used = sum(h.slots for h in self.handles.values() if h.is_active())
            return max(0, self.capacity - used)

    def capacity_slots(self):
        return self.capacity

    def list_all(self):
        with self._lock:
            return list(self.handles.values())

    def inject_kill(self, name: str) -> None:
        with self._lock:
            h = self.handles.get(name)
            if h is not None and h.is_active():
                h.phase = ReplicaPhase.FAILED
                h.exit_code = 137  # signal death, retryable
                h.finished_at = time.time()
                self._changed_keys.add(h.job_key)

    def inject_preempt(self, name: str) -> None:
        with self._lock:
            h = self.handles.get(name)
            if h is not None and h.is_active():
                h.phase = ReplicaPhase.FAILED
                h.exit_code = 143  # SIGTERM death, retryable
                h.finished_at = time.time()
                self._changed_keys.add(h.job_key)

    def standby_ready(self) -> int:
        return self.standby

    def set_standby_target(self, n: int) -> None:
        # Tests model the pool as an instantly-warm counter.
        self.standby = max(0, int(n))

    # --- test helpers ---

    def set_phase(self, name: str, phase: ReplicaPhase, exit_code: Optional[int] = None):
        with self._lock:
            h = self.handles[name]
            h.phase = phase
            if exit_code is not None:
                h.exit_code = exit_code
            if phase in (ReplicaPhase.SUCCEEDED, ReplicaPhase.FAILED):
                h.finished_at = time.time()
            self._changed_keys.add(h.job_key)

    def set_all_running(self, job_key: str):
        with self._lock:
            for h in self.list_for_job(job_key):
                if h.phase == ReplicaPhase.PENDING:
                    h.phase = ReplicaPhase.RUNNING
                    self._changed_keys.add(job_key)


class SubprocessRunner(ProcessRunner):
    """Real runner: replicas are local OS processes.

    stdout+stderr of each replica goes to
    ``<state_dir>/logs/<ns>_<job>-<type>-<index>.log`` (kubectl-logs analog).
    ``max_slots`` bounds concurrently active DEVICE SLOTS — the "cluster
    capacity" gang admission checks against; each replica occupies
    ``replica_slots(template)`` of it (a 4-chip replica weighs 4).
    """

    def __init__(
        self,
        state_dir: Path,
        max_slots: Optional[int] = None,
        standby: int = 0,
    ):
        self.state_dir = Path(state_dir)
        self.log_dir = self.state_dir / "logs"
        self.log_dir.mkdir(parents=True, exist_ok=True)
        # Replica records persist here so a restarted supervisor re-adopts
        # live replicas instead of double-creating the world (reference:
        # pods live in the API server; a controller restart lists + claims
        # them, SURVEY.md §3.2 "label-claim + adoption").
        self.replica_dir = self.state_dir / "replicas"
        self.replica_dir.mkdir(parents=True, exist_ok=True)
        self.max_slots = max_slots
        # Pre-warmed standby processes (controller/standby.py): create()
        # hands module-template jobs to one instead of spawning cold,
        # cutting schedule-to-first-step by the interpreter+import tax.
        self._standby_pool = None
        if standby > 0:
            from .standby import StandbyPool

            self._standby_pool = StandbyPool(self.state_dir, standby)
            self._standby_pool.replenish()
        self.handles: Dict[str, ReplicaHandle] = {}
        # Per-job handle index (see FakeRunner._by_job): keeps
        # list_for_job O(own replicas) instead of O(all replicas).
        self._by_job: Dict[str, Dict[str, ReplicaHandle]] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._log_files: Dict[str, object] = {}
        # Replicas adopted from a previous incarnation: polled via /proc
        # (they are not our children, so no Popen/waitpid).
        self._adopted: Dict[str, int] = {}  # name -> pid
        self._pid_starts: Dict[str, Optional[int]] = {}
        # Standby-run replicas have NO sh wrapper: the handle's pid IS the
        # workload, so "wrapper dead but group alive" does NOT mean the
        # replica survives — liveness for these is pid-only (persisted in
        # the record for adoption across supervisor restarts).
        self._wrapperless: set = set()
        # Job keys with replica-set changes since the last drain (steady
        # fast path), and reaped-but-untracked Popen objects left by
        # forget_job (a disowned child must still be wait()ed or it
        # lingers as a zombie until this process exits).
        self._changed_keys: set = set()
        self._disowned: List[subprocess.Popen] = []
        self._lock = threading.RLock()
        self._load_records()

    # ---- persistence + adoption ----

    def _record_path(self, name: str) -> Path:
        return self.replica_dir / (key_to_fs(name) + ".json")

    def _exit_path(self, name: str) -> Path:
        return self.replica_dir / (key_to_fs(name) + ".exit")

    def _save(self, h: ReplicaHandle, only_if_tracked: bool = False) -> None:
        """``only_if_tracked``: phase-update saves must not resurrect a
        record another incarnation's delete() just unlinked (shared state
        dir) — a stale FAILED record would be adopted by the next start."""
        if only_if_tracked and not self._record_path(h.name).exists():
            return
        rec = h.to_dict()
        rec["pid_start"] = self._pid_starts.get(h.name)
        rec["wrapperless"] = h.name in self._wrapperless
        tmp = self._record_path(h.name).with_suffix(".json.tmp")
        tmp.write_text(json.dumps(rec))
        tmp.replace(self._record_path(h.name))

    def _forget_files(self, name: str) -> None:
        for p in (self._record_path(name), self._exit_path(name)):
            try:
                p.unlink()
            except OSError:
                pass

    def _read_exit_file(self, name: str) -> Optional[int]:
        try:
            return int(self._exit_path(name).read_text().strip())
        except (OSError, ValueError):
            return None

    def _index_add(self, h: ReplicaHandle) -> None:
        self.handles[h.name] = h
        self._by_job.setdefault(h.job_key, {})[h.name] = h
        self._changed_keys.add(h.job_key)

    def _index_pop(self, name: str) -> Optional[ReplicaHandle]:
        h = self.handles.pop(name, None)
        if h is not None:
            per_job = self._by_job.get(h.job_key)
            if per_job is not None:
                per_job.pop(name, None)
                if not per_job:
                    self._by_job.pop(h.job_key, None)
            self._changed_keys.add(h.job_key)
        return h

    def rescan(self, key_filter=None) -> None:
        """Adopt the worlds another incarnation left behind — the
        hot-standby takeover step. The standby's startup snapshot (taken
        while the old leader was still mutating records) is DISCARDED for
        every replica that is not this runner's own live child: the disk
        records the dead leader wrote are strictly fresher (it may have
        restarted replicas under new pids since we loaded). Own children
        (``self._procs``) keep their live Popen state. ``key_filter``
        (sharded takeover) adopts only owned jobs' records."""
        with self._lock:
            for name in list(self.handles):
                if name not in self._procs:
                    self._index_pop(name)
                    self._adopted.pop(name, None)
                    self._pid_starts.pop(name, None)
            self._load_records(
                persist_classification=True, key_filter=key_filter
            )

    def take_changed_keys(self):
        with self._lock:
            out, self._changed_keys = self._changed_keys, set()
            return out

    def forget_job(self, job_key):
        """Shard hand-off: stop tracking this job's replicas. Processes
        and persisted records are untouched (the new owner adopts both);
        our OWN live children move to a reap list so they cannot
        zombify if they exit before this process does."""
        with self._lock:
            for name in list(self._by_job.get(job_key, {})):
                self._index_pop(name)
                proc = self._procs.pop(name, None)
                if proc is not None:
                    self._disowned.append(proc)
                f = self._log_files.pop(name, None)
                if f is not None:
                    f.close()
                self._adopted.pop(name, None)
                self._pid_starts.pop(name, None)
                self._wrapperless.discard(name)

    def _load_records(
        self, persist_classification: bool = False, key_filter=None
    ) -> None:
        """Adopt persisted replicas: live pids (same /proc start time) come
        back RUNNING; dead ones get their exit code from the exit-capture
        file, or 137 (signal death, retryable) if none was written.

        Already-tracked names are skipped (this runner's live knowledge
        wins over its own earlier records). ``persist_classification`` is
        False at construction: a daemon may be a mere STANDBY whose leader
        still owns these records — classifying dead replicas must not
        write state to disk until this incarnation holds the lease
        (rescan) or actively reconciles (sync)."""
        for rec_file in sorted(self.replica_dir.glob("*.json")):
            try:
                rec = json.loads(rec_file.read_text())
                if rec.get("name") in self.handles:
                    continue
                if key_filter is not None and not key_filter(
                    rec.get("job_key", "")
                ):
                    continue
                h = ReplicaHandle(
                    name=rec["name"],
                    job_key=rec["job_key"],
                    replica_type=ReplicaType(rec["replica_type"]),
                    index=rec["index"],
                    phase=ReplicaPhase(rec["phase"]),
                    exit_code=rec.get("exit_code"),
                    pid=rec.get("pid"),
                    created_at=rec.get("created_at", 0.0),
                    finished_at=rec.get("finished_at"),
                    log_path=rec.get("log_path"),
                    slots=int(rec.get("slots", 1)),
                )
            except Exception as e:
                # A corrupt/foreign-schema record must not brick every
                # supervisor start; quarantine it — loudly, so an
                # operator learns replicas went untracked — and move on.
                print(
                    f"[tpujob] quarantining corrupt replica record "
                    f"{rec_file.name}: {e}",
                    file=sys.stderr,
                )
                try:
                    rec_file.replace(rec_file.with_suffix(".json.corrupt"))
                except OSError:
                    pass  # invariant: waived — quarantine rename is best-effort; the parse failure was already reported
                continue
            pid_start = rec.get("pid_start")
            self._pid_starts[h.name] = pid_start
            if rec.get("wrapperless"):
                self._wrapperless.add(h.name)
            if h.is_active():
                # Exit-capture file first: the wrapper writes it when the
                # replica's MAIN process exits, so its presence means done
                # even if a stray background child keeps the group alive.
                alive = (
                    _pid_alive(h.pid, pid_start)
                    if h.name in self._wrapperless
                    else _replica_alive(h.pid, pid_start)
                )
                if self._read_exit_file(h.name) is not None:
                    self._finish_dead_adopted(h, save=persist_classification)
                elif alive:
                    h.phase = ReplicaPhase.RUNNING
                    self._adopted[h.name] = h.pid
                else:
                    self._finish_dead_adopted(h, save=persist_classification)
            self._index_add(h)

    def _finish_dead_adopted(self, h: ReplicaHandle, save: bool = True) -> None:
        """Classify a replica found dead without a waitpid: exit-capture file
        if written, else 137 (group signal killed the wrapper too —
        the preemption case, retryable under ExitCode policy).
        ``save=False`` keeps the classification in memory only (a standby
        must not write records another incarnation owns)."""
        code = self._read_exit_file(h.name)
        h.exit_code = 137 if code is None else code
        h.phase = (
            ReplicaPhase.SUCCEEDED if h.exit_code == 0 else ReplicaPhase.FAILED
        )
        h.finished_at = time.time()
        self._changed_keys.add(h.job_key)
        if save:
            self._save(h, only_if_tracked=True)

    def _argv(self, template: ProcessTemplate, exit_path: Path) -> List[str]:
        if template.command:
            argv = list(template.command)
        else:
            argv = [sys.executable, "-m", template.module]
        argv += list(template.args)
        return ["/bin/sh", "-c", _EXIT_CAPTURE_SH, "sh", str(exit_path)] + argv

    def create(self, job_key, rtype, index, template, env):
        from .. import faults

        name = replica_name(job_key, rtype, index)
        with self._lock:
            if name in self.handles and self.handles[name].is_active():
                raise RuntimeError(f"duplicate create for live replica {name}")
            log_path = self.log_dir / (key_to_fs(name) + ".log")
            full_env = dict(os.environ)
            full_env.update(template.env)
            full_env.update(env)
            # Chaos threading: an armed fault plan rides into the replica
            # (worker-side faults fire inside the subprocess itself).
            faults.thread_env(full_env)
            # Replicas must import this package regardless of cwd, and the
            # inherited PYTHONPATH must be PRESERVED (site customizations —
            # e.g. the TPU PJRT plugin registration — live there).
            pkg_root = str(Path(__file__).resolve().parents[2])
            parts = [p for p in full_env.get("PYTHONPATH", "").split(os.pathsep) if p]
            if pkg_root not in parts:
                parts.insert(0, pkg_root)
            full_env["PYTHONPATH"] = os.pathsep.join(parts)
            self._forget_files(name)  # stale record/exit file of a prior run
        # Pre-warmed path: hand the job to a ready standby (module
        # templates only — exec'ing a command argv would discard the warm
        # imports). OUTSIDE the handle lock: assign() can block up to its
        # ack timeout when a standby dies mid-handoff, and sync/delete/
        # list must not freeze for that. Per-key reconcile serialization
        # already prevents same-name concurrent creates; the handle is
        # installed under the lock below. Ack failure falls through to
        # the cold spawn.
        if self._standby_pool is not None and template.module:
            taken = self._standby_pool.take()
            if taken is not None:
                sid, proc = taken
                ok = self._standby_pool.assign(
                    sid,
                    proc,
                    {
                        "module": template.module,
                        "args": list(template.args),
                        "env": full_env,
                        "cwd": template.working_dir or None,
                        "log_path": str(log_path),
                        "exit_path": str(self._exit_path(name)),
                    },
                )
                if ok:
                    with self._lock:
                        h = ReplicaHandle(
                            name=name,
                            job_key=job_key,
                            replica_type=rtype,
                            index=index,
                            phase=ReplicaPhase.RUNNING,
                            pid=proc.pid,
                            created_at=time.time(),
                            log_path=str(log_path),
                            slots=replica_slots(template),
                        )
                        self._index_add(h)
                        self._procs[name] = proc
                        stat = _proc_stat(proc.pid)
                        self._pid_starts[name] = stat[0] if stat else None
                        self._wrapperless.add(name)
                        self._save(h)
                        return h
        with self._lock:
            log_f = open(log_path, "ab")
            try:
                inj = faults.active()
                if inj is not None and inj.spawn_should_fail(
                    rtype.value, index
                ):
                    raise OSError("injected spawn failure (fault plan)")
                proc = subprocess.Popen(
                    self._argv(template, self._exit_path(name)),
                    env=full_env,
                    cwd=template.working_dir or None,
                    stdout=log_f,
                    stderr=subprocess.STDOUT,
                    start_new_session=True,  # isolate signals from supervisor
                )
            except OSError as e:
                log_f.write(f"[tpujob] failed to launch: {e}\n".encode())
                log_f.close()
                h = ReplicaHandle(
                    name=name,
                    job_key=job_key,
                    replica_type=rtype,
                    index=index,
                    phase=ReplicaPhase.FAILED,
                    exit_code=127,
                    created_at=time.time(),
                    finished_at=time.time(),
                    log_path=str(log_path),
                    slots=replica_slots(template),
                )
                self._index_add(h)
                self._save(h)
                return h
            h = ReplicaHandle(
                name=name,
                job_key=job_key,
                replica_type=rtype,
                index=index,
                phase=ReplicaPhase.RUNNING,
                pid=proc.pid,
                created_at=time.time(),
                log_path=str(log_path),
                slots=replica_slots(template),
            )
            self._index_add(h)
            self._procs[name] = proc
            self._log_files[name] = log_f
            stat = _proc_stat(proc.pid)
            self._pid_starts[name] = stat[0] if stat else None
            self._save(h)
            return h

    def sync(self):
        if self._standby_pool is not None:
            # Outside the handle lock: replenish spawns processes.
            self._standby_pool.replenish()
        with self._lock:
            # Reap children disowned by a shard hand-off (forget_job):
            # still our OS children until they exit, never our replicas.
            if self._disowned:
                self._disowned = [
                    p for p in self._disowned if p.poll() is None
                ]
            for name, proc in list(self._procs.items()):
                code = proc.poll()
                if code is None:
                    continue
                self._procs.pop(name)
                f = self._log_files.pop(name, None)
                if f is not None:
                    f.close()
                h = self.handles[name]
                file_code = self._read_exit_file(name)
                if (
                    code < 0
                    and file_code is None
                    and name not in self._wrapperless
                    and _group_members_alive(proc.pid)
                ):
                    # The wrapper was killed by a signal but the replica's
                    # group survives (TERM-trapping replica, stray kill of
                    # the sh): the replica is NOT dead — demote to
                    # adopted-style group tracking. (A wrapper that EXITS
                    # has waited for its child, so exit ⇒ replica done; an
                    # exit file means the main child finished first.)
                    self._adopted[name] = proc.pid
                    continue
                h.exit_code = (
                    file_code if file_code is not None else normalize_exit_code(code)
                )
                h.phase = (
                    ReplicaPhase.SUCCEEDED
                    if h.exit_code == 0
                    else ReplicaPhase.FAILED
                )
                h.finished_at = time.time()
                self._changed_keys.add(h.job_key)
                self._save(h, only_if_tracked=True)
            # Adopted replicas (previous incarnation's children): when the
            # exit-capture file exists the replica's main process is done
            # (stray group survivors don't keep it RUNNING); otherwise poll
            # /proc — one pass amortized over all adopted names. A dead
            # group with no exit file means a group signal killed the
            # wrapper too (preemption) → 137.
            live_pgids = _live_pgids() if self._adopted else None
            for name, pid in list(self._adopted.items()):
                alive = (
                    _pid_alive(pid, self._pid_starts.get(name))
                    if name in self._wrapperless
                    else _replica_alive(pid, self._pid_starts.get(name), live_pgids)
                )
                if self._read_exit_file(name) is None and alive:
                    continue
                self._adopted.pop(name)
                self._finish_dead_adopted(self.handles[name])

    def inject_kill(self, name: str) -> None:
        """Abrupt group SIGKILL — the preemption model. The handle and
        exit-capture file stay untouched: sync() finds the group dead
        with no exit file and classifies 137 (retryable), exactly like a
        real host preemption."""
        with self._lock:
            h = self.handles.get(name)
            pid = h.pid if h is not None else None
        if pid is None:
            return
        start = self._pid_starts.get(name)
        stat = _proc_stat(pid)
        if stat is not None and start is not None and stat[0] != start:
            return  # pid reused by a stranger — never signal it
        try:
            os.killpg(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    def inject_preempt(self, name: str) -> None:
        """Graceful preemption — group SIGTERM, no escalation wait (the
        sync pass must not block on a TERM-trapping replica). A default
        handler dies with 143 (retryable ≥128); the reconciler walks the
        same failure-classification path as a real managed eviction."""
        with self._lock:
            h = self.handles.get(name)
            pid = h.pid if h is not None else None
        if pid is None:
            return
        start = self._pid_starts.get(name)
        stat = _proc_stat(pid)
        if stat is not None and start is not None and stat[0] != start:
            return  # pid reused by a stranger — never signal it
        try:
            os.killpg(pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass

    def standby_ready(self) -> int:
        with self._lock:
            pool = self._standby_pool
        return pool.ready_count() if pool is not None else 0

    def set_standby_target(self, n: int) -> None:
        """Grow/shrink the warm pool; lazily creates it when hot spares
        first demand one (constructor ``standby=0`` stays the default)."""
        n = max(0, int(n))
        with self._lock:
            pool = self._standby_pool
            if pool is None:
                if n <= 0:
                    return
                from .standby import StandbyPool

                pool = StandbyPool(self.state_dir, n)
                self._standby_pool = pool
            else:
                pool.set_size(n)
        pool.replenish()

    def delete(self, name, grace_seconds: float = 5.0):
        self.delete_many([name], grace_seconds)

    def delete_many(self, names, grace_seconds: float = 5.0):
        """Tear down a batch of replicas with ONE shared TERM→KILL
        escalation: every group is signaled up front, then a single
        /proc-scan loop waits for all of them together. A TERM-trapping
        multi-replica world therefore costs ~grace+2s for the whole batch,
        not per replica — the reconcile loop (which calls this serially
        for suspends/preemptions) must not stall for minutes while other
        jobs wait to be synced."""
        pending = []  # (name, handle, pgid, wrapper Popen or None)
        # One /proc snapshot covers the whole signaling phase (groups only
        # lose members, so a group empty here stays empty); the wait loop
        # below re-scans fresh each tick.
        live_pgids = _live_pgids() if names else set()
        for name in names:
            with self._lock:
                proc = self._procs.get(name)
                h = self.handles.get(name)
                adopted_pid = self._adopted.get(name)
            if proc is not None:
                if proc.poll() is None or proc.pid in live_pgids:
                    # SIGTERM the whole group. proc is the exit-capture
                    # wrapper, which dies on TERM even when the replica
                    # traps it; if the wrapper pre-deceased the replica
                    # (stray kill, OOM) the survivors still get the
                    # graceful signal before the shared escalation.
                    try:
                        os.killpg(proc.pid, signal.SIGTERM)
                    except (ProcessLookupError, PermissionError):
                        pass
                pending.append((name, h, proc.pid, proc))
            elif adopted_pid is not None:
                # Adopted replica: not our child — poll /proc for
                # termination instead of waitpid, same TERM→KILL path.
                if self._term_group(name, adopted_pid, live_pgids):
                    pending.append((name, h, adopted_pid, None))
            elif h is not None and h.pid is not None:
                # Neither our child nor adopted-live: a replica already
                # classified finished. Its wrapper is gone, but a TERM-
                # trapping descendant may survive — reap group members.
                if self._term_group(name, h.pid, live_pgids):
                    pending.append((name, h, h.pid, None))
        self._ensure_groups_dead([p[2] for p in pending], grace_seconds)
        for name, h, pgid, proc in pending:
            if proc is not None:
                # Group is dead (or just SIGKILLed), so the wrapper is at
                # worst a zombie — reap it.
                proc.wait()
        for name in names:
            with self._lock:
                h = self.handles.get(name)
                proc = self._procs.pop(name, None)
                if proc is not None and h is not None:
                    h.exit_code = normalize_exit_code(proc.returncode)
                    h.phase = (
                        ReplicaPhase.FAILED
                        if proc.returncode
                        else ReplicaPhase.SUCCEEDED
                    )
                    h.finished_at = time.time()
                f = self._log_files.pop(name, None)
                if f is not None:
                    f.close()
                self._adopted.pop(name, None)
                self._pid_starts.pop(name, None)
                self.handles.pop(name, None)
                self._forget_files(name)

    def _term_group(self, name: str, pid: int, live_pgids=None) -> bool:
        """SIGTERM a replica's process group we hold no Popen for — adopted
        replicas AND group survivors of already-finished wrappers (the name
        is the group id; pid-reuse strangers are never signaled). Returns
        whether a signal was sent (i.e. the group needs a death-wait).
        ``live_pgids`` lets a batch caller amortize the /proc pass."""
        members_alive = (
            pid in live_pgids if live_pgids is not None else _group_members_alive(pid)
        )
        start = self._pid_starts.get(name)
        stat = _proc_stat(pid)
        if (
            stat is not None
            and stat[1] != "Z"
            and start is not None
            and stat[0] != start
        ):
            return False  # pid reused by a stranger — never signal it
        if not _pid_alive(pid, start) and not members_alive:
            # Wrapper gone and no surviving group members (a pid stays
            # allocated while it is a live pgid, so members ⇒ ours).
            return False
        try:
            os.killpg(pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return False
        return True

    def _ensure_groups_dead(self, pgids, grace_seconds: float) -> None:
        """Wait until every member of every listed process group has
        exited, escalating to group SIGKILLs when the grace budget runs
        out. One /proc scan per tick covers the whole batch."""
        waiting = set(pgids)
        if not waiting:
            return
        # monotonic: a clock step during teardown must not skip the
        # grace period (SIGKILL lands on a checkpoint-flushing child) or
        # extend it indefinitely.
        deadline = time.monotonic() + grace_seconds
        while waiting and time.monotonic() < deadline:
            waiting &= _live_pgids()
            if not waiting:
                return
            time.sleep(0.05)
        for pgid in list(waiting):
            try:
                os.killpg(pgid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                waiting.discard(pgid)
        kill_deadline = time.monotonic() + 2.0
        while waiting and time.monotonic() < kill_deadline:
            waiting &= _live_pgids()
            time.sleep(0.05)

    def list_for_job(self, job_key):
        with self._lock:
            return [h for h in self.handles.values() if h.job_key == job_key]

    def get(self, name):
        with self._lock:
            return self.handles.get(name)

    def remove_record(self, name):
        with self._lock:
            if name in self._procs or name in self._adopted:
                raise RuntimeError(f"cannot remove record of live replica {name}")
            self.handles.pop(name, None)
            self._pid_starts.pop(name, None)
            self._wrapperless.discard(name)
            self._forget_files(name)

    def set_slots(self, name, slots):
        """Heal a stale weight AND persist it — an in-memory-only heal
        would re-open the overcommit window on every supervisor restart."""
        with self._lock:
            h = self.handles.get(name)
            if h is not None and h.slots != slots:
                h.slots = slots
                self._save(h, only_if_tracked=True)

    def schedulable_slots(self):
        if self.max_slots is None:
            return None
        with self._lock:
            used = sum(h.slots for h in self.handles.values() if h.is_active())
        return max(0, self.max_slots - used)

    def capacity_slots(self):
        return self.max_slots

    def list_all(self):
        with self._lock:
            return list(self.handles.values())

    def shutdown(self):
        """Terminate replicas THIS incarnation spawned (supervisor exit).

        Adopted replicas are spared: they are another incarnation's world
        (possibly a live daemon sharing the state dir with a foreground
        ``tpujob run``), and the reference's controller shutdown never kills
        pods it merely adopted — job-scoped ``delete()`` remains the only
        path that tears them down.
        """
        with self._lock:
            names = list(self._procs.keys())
        self.delete_many(names, grace_seconds=2.0)
        if self._standby_pool is not None:
            self._standby_pool.shutdown()  # idle standbys die with us
