"""Replica process runners — the pod-control analog.

Reference: pod creation/deletion via ``podControl`` and the kubelet actually
running containers (SURVEY.md §3.2–3.3). Locally a *replica* is an OS
process. Two runners share one interface:

- :class:`SubprocessRunner` — the real thing: ``subprocess.Popen`` with
  injected env, per-replica log files, termination with escalation.
- :class:`FakeRunner` — the fake-clientset analog (SURVEY.md §4): records
  create/delete actions, and tests drive phases by hand
  (``set_phase(name, FAILED, exit_code=137)``) — no processes involved.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional

from ..api.types import ProcessTemplate, ReplicaPhase, ReplicaType


def replica_name(job_key: str, rtype: ReplicaType, index: int) -> str:
    """Canonical replica name: ``<ns>/<job>-<type>-<index>`` (pod-name analog)."""
    return f"{job_key}-{rtype.value.lower()}-{index}"


def normalize_exit_code(code: Optional[int]) -> Optional[int]:
    """Map Popen's signal encoding (-N) to the container convention (128+N)
    the ExitCode restart policy is defined against — so SIGKILL surfaces as
    137 (retryable), matching the reference's pod-level semantics."""
    if code is not None and code < 0:
        return 128 - code
    return code


@dataclass
class ReplicaHandle:
    """Tracking record for one replica process (pod-object analog)."""

    name: str
    job_key: str
    replica_type: ReplicaType
    index: int
    phase: ReplicaPhase = ReplicaPhase.PENDING
    exit_code: Optional[int] = None
    pid: Optional[int] = None
    created_at: float = 0.0
    finished_at: Optional[float] = None
    log_path: Optional[str] = None

    def is_active(self) -> bool:
        return self.phase in (ReplicaPhase.PENDING, ReplicaPhase.RUNNING)

    def is_finished(self) -> bool:
        return self.phase in (ReplicaPhase.SUCCEEDED, ReplicaPhase.FAILED)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "job_key": self.job_key,
            "replica_type": self.replica_type.value,
            "index": self.index,
            "phase": self.phase.value,
            "exit_code": self.exit_code,
            "pid": self.pid,
            "created_at": self.created_at,
            "finished_at": self.finished_at,
            "log_path": self.log_path,
        }


class ProcessRunner:
    """Interface both runners implement."""

    def create(
        self,
        job_key: str,
        rtype: ReplicaType,
        index: int,
        template: ProcessTemplate,
        env: Dict[str, str],
    ) -> ReplicaHandle:
        raise NotImplementedError

    def delete(self, name: str, grace_seconds: float = 5.0) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        """Poll live processes and update phases (informer-refresh analog)."""

    def list_for_job(self, job_key: str) -> List[ReplicaHandle]:
        raise NotImplementedError

    def get(self, name: str) -> Optional[ReplicaHandle]:
        raise NotImplementedError

    def remove_record(self, name: str) -> None:
        """Forget a finished replica's record (pod object deletion analog)."""
        raise NotImplementedError

    def schedulable_slots(self) -> Optional[int]:
        """Free scheduling slots, or None for unlimited (gang admission input)."""
        return None


class FakeRunner(ProcessRunner):
    """In-memory runner for controller tests (fake clientset analog).

    Created replicas start PENDING; tests move them with :meth:`set_phase`.
    Every create/delete is appended to :attr:`actions` for assertions, and
    the env each replica was created with is kept in :attr:`envs`.
    """

    def __init__(self, capacity: Optional[int] = None):
        self.handles: Dict[str, ReplicaHandle] = {}
        self.envs: Dict[str, Dict[str, str]] = {}
        self.templates: Dict[str, ProcessTemplate] = {}
        self.actions: List[tuple] = []
        self.capacity = capacity  # None = unlimited
        # Same thread-safety contract as SubprocessRunner: per-key reconcile
        # locks serialize same-key access, but different keys hit the shared
        # dicts concurrently (tests/test_stress.py).
        self._lock = threading.RLock()

    def create(self, job_key, rtype, index, template, env):
        name = replica_name(job_key, rtype, index)
        with self._lock:
            if name in self.handles:
                raise RuntimeError(f"duplicate create for {name}")
            h = ReplicaHandle(
                name=name,
                job_key=job_key,
                replica_type=rtype,
                index=index,
                phase=ReplicaPhase.PENDING,
                created_at=time.time(),
            )
            self.handles[name] = h
            self.envs[name] = dict(env)
            self.templates[name] = template
            self.actions.append(("create", name))
            return h

    def delete(self, name, grace_seconds: float = 5.0):
        with self._lock:
            self.actions.append(("delete", name))
            h = self.handles.pop(name, None)
            if h is not None:
                self.envs.pop(name, None)
                self.templates.pop(name, None)

    def sync(self):
        pass

    def list_for_job(self, job_key):
        with self._lock:
            return [h for h in self.handles.values() if h.job_key == job_key]

    def get(self, name):
        with self._lock:
            return self.handles.get(name)

    def remove_record(self, name):
        with self._lock:
            self.handles.pop(name, None)

    def schedulable_slots(self):
        with self._lock:
            if self.capacity is None:
                return None
            used = sum(1 for h in self.handles.values() if h.is_active())
            return max(0, self.capacity - used)

    # --- test helpers ---

    def set_phase(self, name: str, phase: ReplicaPhase, exit_code: Optional[int] = None):
        with self._lock:
            h = self.handles[name]
            h.phase = phase
            if exit_code is not None:
                h.exit_code = exit_code
            if phase in (ReplicaPhase.SUCCEEDED, ReplicaPhase.FAILED):
                h.finished_at = time.time()

    def set_all_running(self, job_key: str):
        with self._lock:
            for h in self.list_for_job(job_key):
                if h.phase == ReplicaPhase.PENDING:
                    h.phase = ReplicaPhase.RUNNING


class SubprocessRunner(ProcessRunner):
    """Real runner: replicas are local OS processes.

    stdout+stderr of each replica goes to
    ``<state_dir>/logs/<ns>_<job>-<type>-<index>.log`` (kubectl-logs analog).
    ``max_slots`` bounds concurrently active replicas — the "cluster
    capacity" that gang admission checks against.
    """

    def __init__(self, state_dir: Path, max_slots: Optional[int] = None):
        self.state_dir = Path(state_dir)
        self.log_dir = self.state_dir / "logs"
        self.log_dir.mkdir(parents=True, exist_ok=True)
        self.max_slots = max_slots
        self.handles: Dict[str, ReplicaHandle] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._log_files: Dict[str, object] = {}
        self._lock = threading.RLock()

    def _argv(self, template: ProcessTemplate) -> List[str]:
        if template.command:
            argv = list(template.command)
        else:
            argv = [sys.executable, "-m", template.module]
        return argv + list(template.args)

    def create(self, job_key, rtype, index, template, env):
        name = replica_name(job_key, rtype, index)
        with self._lock:
            if name in self.handles and self.handles[name].is_active():
                raise RuntimeError(f"duplicate create for live replica {name}")
            log_path = self.log_dir / (name.replace("/", "_") + ".log")
            full_env = dict(os.environ)
            full_env.update(template.env)
            full_env.update(env)
            # Replicas must import this package regardless of cwd, and the
            # inherited PYTHONPATH must be PRESERVED (site customizations —
            # e.g. the TPU PJRT plugin registration — live there).
            pkg_root = str(Path(__file__).resolve().parents[2])
            parts = [p for p in full_env.get("PYTHONPATH", "").split(os.pathsep) if p]
            if pkg_root not in parts:
                parts.insert(0, pkg_root)
            full_env["PYTHONPATH"] = os.pathsep.join(parts)
            log_f = open(log_path, "ab")
            try:
                proc = subprocess.Popen(
                    self._argv(template),
                    env=full_env,
                    cwd=template.working_dir or None,
                    stdout=log_f,
                    stderr=subprocess.STDOUT,
                    start_new_session=True,  # isolate signals from supervisor
                )
            except OSError as e:
                log_f.write(f"[tpujob] failed to launch: {e}\n".encode())
                log_f.close()
                h = ReplicaHandle(
                    name=name,
                    job_key=job_key,
                    replica_type=rtype,
                    index=index,
                    phase=ReplicaPhase.FAILED,
                    exit_code=127,
                    created_at=time.time(),
                    finished_at=time.time(),
                    log_path=str(log_path),
                )
                self.handles[name] = h
                return h
            h = ReplicaHandle(
                name=name,
                job_key=job_key,
                replica_type=rtype,
                index=index,
                phase=ReplicaPhase.RUNNING,
                pid=proc.pid,
                created_at=time.time(),
                log_path=str(log_path),
            )
            self.handles[name] = h
            self._procs[name] = proc
            self._log_files[name] = log_f
            return h

    def sync(self):
        with self._lock:
            for name, proc in list(self._procs.items()):
                code = proc.poll()
                if code is None:
                    continue
                h = self.handles[name]
                h.exit_code = normalize_exit_code(code)
                h.phase = (
                    ReplicaPhase.SUCCEEDED if code == 0 else ReplicaPhase.FAILED
                )
                h.finished_at = time.time()
                self._procs.pop(name)
                f = self._log_files.pop(name, None)
                if f is not None:
                    f.close()

    def delete(self, name, grace_seconds: float = 5.0):
        with self._lock:
            proc = self._procs.get(name)
            h = self.handles.get(name)
        if proc is not None and proc.poll() is None:
            # SIGTERM the whole process group, escalate to SIGKILL.
            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                proc.wait(timeout=grace_seconds)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                proc.wait()
        with self._lock:
            proc = self._procs.pop(name, None)
            if proc is not None and h is not None:
                h.exit_code = normalize_exit_code(proc.returncode)
                h.phase = ReplicaPhase.FAILED if proc.returncode else ReplicaPhase.SUCCEEDED
                h.finished_at = time.time()
            f = self._log_files.pop(name, None)
            if f is not None:
                f.close()
            self.handles.pop(name, None)

    def list_for_job(self, job_key):
        with self._lock:
            return [h for h in self.handles.values() if h.job_key == job_key]

    def get(self, name):
        with self._lock:
            return self.handles.get(name)

    def remove_record(self, name):
        with self._lock:
            if name in self._procs:
                raise RuntimeError(f"cannot remove record of live replica {name}")
            self.handles.pop(name, None)

    def schedulable_slots(self):
        if self.max_slots is None:
            return None
        with self._lock:
            used = sum(1 for h in self.handles.values() if h.is_active())
        return max(0, self.max_slots - used)

    def shutdown(self):
        """Terminate everything (supervisor exit)."""
        with self._lock:
            names = list(self._procs.keys())
        for name in names:
            self.delete(name, grace_seconds=2.0)
