"""Leases for supervisors sharing one state dir.

Two regimes live here:

- :class:`LeaderLease` — exclusive leadership (reference:
  ``leaderelection.RunOrDie``, SURVEY.md §2 "Entrypoint/CLI", §3.1): ONE
  active reconciler per state dir, enforced by an ``fcntl.flock`` on
  ``<state-dir>/leader.lock``. The OS releases the lock when the holder
  dies, which gives the standby automatic fail-over. This is the default
  single-supervisor path and is unchanged.

- :class:`ShardLease` / :class:`ShardManager` — job-space sharding: N
  ``tpujob supervisor`` daemons against one state dir, each holding
  per-shard lease FILES (``<state-dir>/leases/shard-*.lease``) with
  renew/expiry and monotonic FENCING TOKENS, so every job (hash of its
  key → shard) has exactly one reconciler and shards rebalance within
  one lease TTL when a supervisor joins, dies, or is drained. File-based
  rather than flock-based on purpose: the lease must be observable and
  stealable across hosts sharing the state dir, and the exactly-once
  takeover arbitration reuses the claim-by-rename discipline the marker
  machinery proved out (tests/test_store_cache.py::TestMarkerExactlyOnce).

Lease state machine (one shard)::

      (no file)──claim──▶ HELD(holder=A, token=t)
          ▲                   │ renew (while now < expires): expires += ttl
          │                   │ release: holder="", token kept   ──▶ RELEASED
          │                   ▼ expiry (holder died / stopped renewing)
      bootstrap           EXPIRED ──steal (claim file arbitrates)──▶
                                    HELD(holder=B, token=t+1)

    A's next renew after the steal reads token t+1 ≠ t and is REJECTED
    (fencing): A drops the shard without ever writing, so a stale holder
    can never double-reconcile a job the new owner already claimed.
"""

from __future__ import annotations

import errno
import fcntl
import json
import math
import os
import socket
import threading
import time
import zlib
from pathlib import Path
from typing import Dict, List, Optional, Set


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else


class LeaderLease:
    """An exclusive, crash-released lease on a state directory."""

    def __init__(self, state_dir: Path, identity: Optional[str] = None):
        self.path = Path(state_dir) / "leader.lock"
        self.identity = identity or f"{socket.gethostname()}_{os.getpid()}"
        self._fd: Optional[int] = None

    def acquire(self, blocking: bool = True, timeout: Optional[float] = None) -> bool:
        """Take the lease. Returns False iff non-blocking/timed-out and held
        elsewhere. Re-acquiring a held lease is a no-op returning True."""
        if self._fd is not None:
            return True
        self.path.parent.mkdir(parents=True, exist_ok=True)
        fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o644)
        # Monotonic deadline: a wall-clock step (NTP) must not stretch or
        # collapse the timeout.
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                flags = fcntl.LOCK_EX
                if not blocking or deadline is not None:
                    flags |= fcntl.LOCK_NB
                fcntl.flock(fd, flags)
                break
            except OSError as e:
                if e.errno not in (errno.EWOULDBLOCK, errno.EAGAIN):
                    # Not contention — e.g. flock unsupported on this fs.
                    os.close(fd)
                    raise
                if not blocking or (
                    deadline is not None and time.monotonic() >= deadline
                ):
                    os.close(fd)
                    return False
                # invariant: waived — 50ms flock contention poll, deadline-bounded above; no herd (one writer wins)
                time.sleep(0.05)
        # Record the holder for observability (healthz, error messages).
        # Any failure here must release + close the locked fd: leaking it
        # with self._fd unset would self-deadlock every retry in this
        # process (same-process fds conflict under flock) and block every
        # standby forever.
        try:
            os.ftruncate(fd, 0)
            os.pwrite(
                fd,
                json.dumps(
                    {"holder": self.identity, "pid": os.getpid(), "acquired": time.time()}
                ).encode(),
                0,
            )
        except OSError:
            try:
                fcntl.flock(fd, fcntl.LOCK_UN)
            finally:
                os.close(fd)
            raise
        self._fd = fd
        return True

    def release(self) -> None:
        if self._fd is None:
            return
        # Clear the holder record BEFORE unlocking so observers never read
        # our identity as the leader after we stepped down. (Crash release
        # skips this — holder() handles that via the pid liveness check.)
        try:
            os.ftruncate(self._fd, 0)
        except OSError:
            pass
        fcntl.flock(self._fd, fcntl.LOCK_UN)
        os.close(self._fd)
        self._fd = None

    def is_held(self) -> bool:
        return self._fd is not None

    def holder(self) -> Optional[str]:
        """Best-effort identity of the current holder (None if unheld).

        Deliberately LOCK-FREE: a flock probe (shared or exclusive) would
        momentarily contend with a real ``acquire`` attempt, making a
        concurrent standby's election spuriously fail just because
        someone asked who the leader is. Instead read the holder record
        and judge liveness by pid: the OS releases a dead holder's lock,
        and a dead pid means the record is stale.
        """
        if self._fd is not None:
            return self.identity
        try:
            content = self.path.read_text()
        except OSError:
            return None
        if not content.strip():
            return None
        try:
            rec = json.loads(content)
        except ValueError:
            return "<unknown>"
        pid = rec.get("pid")
        if isinstance(pid, int) and not _pid_alive(pid):
            return None  # crash-released: lock gone, record stale
        return rec.get("holder")

    def __enter__(self) -> "LeaderLease":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


# ---- job-space sharding ----

# Event-sink pseudo key the supervisor records shard hand-offs under:
# one bounded global log (NOT one event per job — a 5000-job shard
# hand-off must not write 5000 sink files), which `tpujob why` filters
# by the job's shard to cite an ownership change.
SHARD_EVENT_KEY = "_system/shards"


def default_identity() -> str:
    return f"{socket.gethostname()}-{os.getpid()}"


def shard_of_key(key: str, num_shards: int, pin: Optional[int] = None) -> int:
    """Job key → shard. Stable hash (crc32 — cheap, deterministic across
    processes and runs, unlike ``hash()``); ``pin`` is the optional
    ``scheduling_policy.shard`` override that co-locates related jobs
    (a wide gang's feeders) on one reconciler."""
    if pin is not None:
        return pin % num_shards
    return zlib.crc32(key.encode()) % num_shards


class ShardLeaseLost(Exception):
    """Raised by no one by default — exported for callers that want to
    treat a mid-pass fencing rejection as exceptional."""


class ShardLease:
    """One shard's lease file: ``{holder, token, expires}`` JSON.

    The fencing ``token`` increments on every OWNERSHIP change (claim of
    a free/expired/released lease), never on renewal — a holder whose
    recorded token no longer matches the file has been superseded and
    must treat every pending write as rejected.

    Takeover arbitration: a ``.claim`` file created with ``O_EXCL``
    decides WHO may rewrite an expired/free lease (two simultaneous
    joiners race the create; exactly one wins — the same exactly-once
    property the marker rename-claim provides). Stale claims (a claimant
    crashed mid-takeover) are swept after ``ttl``.
    """

    def __init__(
        self, leases_dir: Path, shard_id: int, identity: str, ttl: float = 5.0
    ):
        self.dir = Path(leases_dir)
        self.shard_id = shard_id
        self.identity = identity
        self.ttl = ttl
        self.path = self.dir / f"shard-{shard_id:05d}.lease"
        # In-memory view while held; token 0 = not held.
        self.token = 0
        self.expires = 0.0
        # Whose EXPIRED lease the last successful acquire stole (None
        # for a free/released claim) — feeds the hand-off event so a
        # postmortem (and `tpujob chaos --record`) can name the dead
        # supervisor.
        self.takeover_from: Optional[str] = None

    # -- on-disk record --

    def read(self) -> Optional[dict]:
        try:
            rec = json.loads(self.path.read_text())
            return rec if isinstance(rec, dict) else None
        except (OSError, ValueError):
            return None

    def _write(self, rec: dict) -> None:
        tmp = self.path.with_name(f"{self.path.name}.{os.getpid()}.tmp")
        tmp.write_text(json.dumps(rec))
        tmp.replace(self.path)

    def _record(self, holder: str, token: int, expires: float, now: float) -> dict:
        return {
            "shard": self.shard_id,
            "holder": holder,
            "token": token,
            "expires": expires,
            "renewed": now,
        }

    # -- protocol --

    def held(self, now: Optional[float] = None, margin: float = 0.0) -> bool:
        """Whether THIS process may act as the shard's owner right now.
        ``margin`` guards long passes: a reconcile admitted with less
        than ``margin`` seconds of lease left could outlive the lease."""
        now = time.time() if now is None else now
        return self.token > 0 and now + margin < self.expires

    def try_acquire(self, now: Optional[float] = None) -> bool:
        """Claim the shard if it is free, released, expired, or already
        ours on disk (same-identity daemon restart). Returns False when
        it is validly held elsewhere or a rival holds the takeover claim."""
        now = time.time() if now is None else now
        rec = self.read()
        if rec is not None:
            holder = rec.get("holder") or ""
            try:
                expires = float(rec.get("expires", 0.0))
                rec_token = int(rec.get("token", 0))
            except (TypeError, ValueError):
                expires, rec_token = 0.0, 0
            if holder == self.identity and now < expires:
                # Our own surviving lease (daemon restart, same identity).
                self.token, self.expires = rec_token, expires
                return True
            if holder and now < expires:
                return False  # validly held elsewhere
        claim = self.path.with_suffix(".claim")
        try:
            fd = os.open(claim, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
        except FileExistsError:
            # A rival is mid-takeover. Sweep only a STALE claim (claimant
            # crashed between claim and lease write); back off otherwise.
            try:
                if time.time() - claim.stat().st_mtime > max(self.ttl, 2.0):
                    claim.unlink(missing_ok=True)
            except OSError:
                pass
            return False
        except OSError:
            return False
        try:
            os.write(fd, self.identity.encode())
            os.close(fd)
            # Re-read UNDER the claim: the lease may have been renewed (or
            # stolen) between our first read and the claim create.
            rec = self.read()
            token = 0
            self.takeover_from = None
            if rec is not None:
                holder = rec.get("holder") or ""
                try:
                    expires = float(rec.get("expires", 0.0))
                    token = int(rec.get("token", 0))
                except (TypeError, ValueError):
                    expires, token = 0.0, 0
                if holder and holder != self.identity and now < expires:
                    return False
                if holder and holder != self.identity:
                    self.takeover_from = holder  # stole an expired lease
            token += 1  # fencing: every ownership change bumps it
            self._write(self._record(self.identity, token, now + self.ttl, now))
            self.token, self.expires = token, now + self.ttl
            return True
        finally:
            claim.unlink(missing_ok=True)

    def renew(self, now: Optional[float] = None) -> bool:
        """Extend a held lease. Returns False — and drops the in-memory
        hold — when the lease expired (a renewal after expiry must go
        through the contended acquire path, not quietly overwrite a
        stealer) or the on-disk token/holder no longer matches (fencing
        rejection of this now-stale holder)."""
        now = time.time() if now is None else now
        if self.token <= 0:
            return False
        if now >= self.expires:
            self.token, self.expires = 0, 0.0
            return False
        rec = self.read()
        try:
            disk_expires = float(rec.get("expires", 0.0)) if rec else 0.0
        except (TypeError, ValueError):
            disk_expires = 0.0
        if (
            rec is None
            or (rec.get("holder") or "") != self.identity
            or int(rec.get("token", -1)) != self.token
            or disk_expires <= now
        ):
            # Fencing: someone else owns a newer incarnation of this
            # lease, or the DISK record expired under us (drop_lease
            # fault, external tampering) while our in-memory view was
            # still valid. Either way a rival may already be mid-steal
            # — never renew-over it; drop and re-contend.
            self.token, self.expires = 0, 0.0
            return False
        self._write(self._record(self.identity, self.token, now + self.ttl, now))
        self.expires = now + self.ttl
        return True

    def release(self, now: Optional[float] = None) -> None:
        """Voluntary hand-back (drain/rebalance): the record keeps the
        token (monotonicity survives release→claim cycles) with holder
        cleared and expiry zeroed, so a claimant takes it immediately."""
        now = time.time() if now is None else now
        if self.token <= 0:
            return
        rec = self.read()
        if (
            rec is not None
            and (rec.get("holder") or "") == self.identity
            and int(rec.get("token", -1)) == self.token
        ):
            self._write(self._record("", self.token, 0.0, now))
        self.token, self.expires = 0, 0.0

    def force_expire(self) -> None:
        """Chaos hook (``drop_lease`` fault): rewrite the ON-DISK record
        as expired without touching the in-memory hold — the holder
        keeps believing it owns the shard until its next renew is
        fencing-rejected, which is exactly the stale-holder scenario the
        token exists to contain."""
        rec = self.read()
        if rec is not None:
            rec["expires"] = 0.0
            self._write(rec)


class ShardIOCounters:
    """Lease-layer I/O accounting for the control-plane bench: idle
    steady-state cost is O(owned shards / ttl), never O(jobs)."""

    __slots__ = ("renews", "claims", "releases", "guard_skips")

    def __init__(self) -> None:
        self.renews = 0
        self.claims = 0
        self.releases = 0
        # Reconciles REFUSED because the shard lease was no longer valid
        # at admission time — each one is a double-reconcile that did
        # not happen.
        self.guard_skips = 0

    def snapshot(self) -> dict:
        return {
            "renews": self.renews,
            "claims": self.claims,
            "releases": self.releases,
            "guard_skips": self.guard_skips,
        }


class ShardManager:
    """One supervisor's view of the sharded job space.

    ``tick()`` once per sync pass: heartbeat our presence, renew owned
    leases (at half-TTL cadence — idle lease I/O is O(shards/ttl), not
    O(passes)), release down to the fair share when members joined, and
    claim up to it when shards are free/expired. Fair share =
    ``ceil(num_shards / live_members)``, so a join rebalances within
    ~one tick and a death is absorbed as soon as the dead supervisor's
    leases expire — both within one lease TTL.

    Renewal additionally runs on a BACKGROUND thread (``auto_renew``,
    the k8s leader-election pattern): a reconcile pass that takes
    longer than the TTL — a 10k-job launch pass, a slow disk — must not
    cost the supervisor its shards mid-pass. The thread only renews and
    heartbeats presence (idempotent, guarded by one lock shared with
    ``tick``); membership changes stay on the pass cadence. Tests that
    need deterministic renewal interleavings pass ``auto_renew=False``
    and drive ``tick(now)`` with a synthetic clock.
    """

    # Presence files older than this many TTLs are swept.
    _PRESENCE_SWEEP_TTLS = 3.0

    def __init__(
        self,
        state_dir: Path,
        num_shards: int,
        identity: Optional[str] = None,
        ttl: float = 5.0,
        auto_renew: bool = True,
    ):
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        self.state_dir = Path(state_dir)
        self.leases_dir = self.state_dir / "leases"
        self.members_dir = self.leases_dir / "members"
        self.members_dir.mkdir(parents=True, exist_ok=True)
        self.identity = identity or default_identity()
        self.ttl = float(ttl)
        self.num_shards = self._pin_config(num_shards)
        self.leases: Dict[int, ShardLease] = {
            i: ShardLease(self.leases_dir, i, self.identity, self.ttl)
            for i in range(self.num_shards)
        }
        self.owned: Set[int] = set()
        self._last_presence = 0.0
        self._last_orphan_scan = 0.0
        self.io = ShardIOCounters()
        self.auto_renew = auto_renew
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._renew_thread: Optional[threading.Thread] = None
        # Shards the renewal thread lost (fencing rejection) — surfaced
        # through the next tick() so the owner can emit events/cleanup.
        self._lost_async: List[int] = []

    def _ensure_renew_thread(self) -> None:
        if (
            not self.auto_renew
            or self._stop.is_set()
            or (self._renew_thread is not None and self._renew_thread.is_alive())
        ):
            return
        t = threading.Thread(
            target=self._renew_loop,
            name="tpujob-shard-renew",
            daemon=True,
        )
        self._renew_thread = t
        t.start()

    def _renew_loop(self) -> None:
        while not self._stop.wait(self.ttl / 3.0):
            now = time.time()
            with self._lock:
                self._write_presence(now)
                self._last_presence = now
                self._renew_owned(now)

    def _renew_owned(self, now: float) -> None:
        """Renew every owned lease nearing half-TTL; record losses.
        Caller holds the lock."""
        for i in sorted(self.owned):
            lease = self.leases[i]
            if now >= lease.expires - self.ttl / 2.0:
                self.io.renews += 1
                if not lease.renew(now):
                    self.owned.discard(i)
                    self._lost_async.append(i)

    def halt(self) -> None:
        """Crash semantics (kill_supervisor in-process): stop renewing
        WITHOUT releasing anything — the leases must expire and be
        stolen, exactly as if the process died."""
        self._stop.set()

    def _pin_config(self, num_shards: int) -> int:
        """First supervisor pins the shard count for the state dir;
        joiners must agree (a split-brain shard map would assign one job
        two owners). O_EXCL create, read-back on conflict."""
        cfg = self.leases_dir / "config.json"
        try:
            fd = os.open(cfg, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
            os.write(fd, json.dumps({"num_shards": num_shards}).encode())
            os.close(fd)
            return num_shards
        except FileExistsError:
            pass
        try:
            pinned = int(json.loads(cfg.read_text())["num_shards"])
        except (OSError, ValueError, KeyError, TypeError):
            return num_shards
        if pinned != num_shards:
            raise ValueError(
                f"state dir is sharded {pinned} ways; --shards {num_shards} "
                "does not match (every supervisor on one state dir must "
                "agree on the shard count)"
            )
        return pinned

    # -- membership --

    def _presence_path(self, identity: Optional[str] = None) -> Path:
        import re as _re

        safe = _re.sub(r"[^A-Za-z0-9._-]", "_", identity or self.identity)
        return self.members_dir / (safe + ".json")

    def _write_presence(self, now: float) -> None:
        p = self._presence_path()
        tmp = p.with_name(f"{p.name}.{os.getpid()}.tmp")
        tmp.write_text(json.dumps({"identity": self.identity, "ts": now}))
        tmp.replace(p)

    def live_members(self, now: Optional[float] = None) -> List[str]:
        """Identities with a fresh presence heartbeat (self included even
        before the first write). Stale presence files are swept."""
        now = time.time() if now is None else now
        out = {self.identity}
        try:
            entries = list(os.scandir(self.members_dir))
        except OSError:
            return sorted(out)
        for e in entries:
            if not e.name.endswith(".json"):
                continue
            try:
                rec = json.loads(Path(e.path).read_text())
                ident = str(rec["identity"])
                ts = float(rec["ts"])
            except (OSError, ValueError, KeyError, TypeError):
                continue
            if now - ts <= self.ttl:
                out.add(ident)
            elif now - ts > self._PRESENCE_SWEEP_TTLS * self.ttl:
                Path(e.path).unlink(missing_ok=True)
        return sorted(out)

    def fair_share(self, members: int) -> int:
        return math.ceil(self.num_shards / max(1, members))

    def _pref(self, shard_id: int) -> int:
        """Deterministic per-identity shard preference: different
        supervisors walk the claimable shards in different orders, so
        simultaneous joiners mostly avoid contending on the same claim
        file (collisions are still resolved exactly-once by O_EXCL)."""
        return zlib.crc32(f"{shard_id}:{self.identity}".encode())

    # -- the per-pass step --

    def tick(self, now: Optional[float] = None) -> dict:
        """Returns ``{"acquired": [...], "released": [...], "lost":
        [...], "members": int}`` — the supervisor turns acquisitions
        into store reloads / runner adoption and hand-off events."""
        now = time.time() if now is None else now
        self._ensure_renew_thread()
        acquired: List[int] = []
        released: List[int] = []
        with self._lock:
            if now - self._last_presence >= self.ttl / 3.0:
                self._write_presence(now)
                self._last_presence = now
            members = self.live_members(now)
            fair = self.fair_share(len(members))
            # Renew what we own (backup path; the renewal thread keeps
            # this a no-op while it runs), THEN drain losses — renewal
            # fencing rejections from this very tick must surface now,
            # not one pass late.
            self._renew_owned(now)
            lost, self._lost_async = self._lost_async, []
            # Release down to fair share (a joiner appeared): hand back
            # the shards we are LEAST preferred for, deterministically.
            if len(self.owned) > fair:
                keep = sorted(self.owned, key=self._pref)[:fair]
                for i in sorted(self.owned - set(keep)):
                    self.io.releases += 1
                    self.leases[i].release(now)
                    self.owned.discard(i)
                    released.append(i)
            # Claim up to fair share (bootstrap, member death, releases).
            if len(self.owned) < fair:
                for i in sorted(range(self.num_shards), key=self._pref):
                    if len(self.owned) >= fair:
                        break
                    if i in self.owned:
                        continue
                    self.io.claims += 1
                    if self.leases[i].try_acquire(now):
                        self.owned.add(i)
                        acquired.append(i)
            # Orphan rescue, BEYOND fair share: a shard whose holder
            # stopped renewing (death, drop_lease) must be re-claimed
            # within one TTL of its last renewal — not whenever the dead
            # member's presence ages out. Over-claiming rebalances back
            # down on later ticks. Throttled: O(num_shards) tiny reads
            # at most every ttl/3, never per pass.
            if now - self._last_orphan_scan >= self.ttl / 3.0:
                self._last_orphan_scan = now
                for i in range(self.num_shards):
                    if i in self.owned:
                        continue
                    lease = self.leases[i]
                    rec = lease.read()
                    if rec is None or not rec.get("holder"):
                        continue  # free/released: fair-share territory
                    try:
                        expires = float(rec.get("expires", 0.0))
                    except (TypeError, ValueError):
                        expires = 0.0
                    if now < expires:
                        continue
                    self.io.claims += 1
                    if lease.try_acquire(now):
                        self.owned.add(i)
                        acquired.append(i)
        return {
            "acquired": acquired,
            "released": released,
            "lost": lost,
            "members": len(members),
        }

    # -- ownership queries --

    def shard_of(self, key: str, pin: Optional[int] = None) -> int:
        return shard_of_key(key, self.num_shards, pin)

    def owns_shard(
        self, shard_id: int, now: Optional[float] = None, margin: float = 0.0
    ) -> bool:
        return shard_id in self.owned and self.leases[shard_id].held(
            now, margin
        )

    def owns_key(
        self,
        key: str,
        now: Optional[float] = None,
        pin: Optional[int] = None,
        margin: float = 0.0,
    ) -> bool:
        return self.owns_shard(self.shard_of(key, pin), now, margin)

    def owner_of(self, shard_id: int) -> Optional[str]:
        """Best-effort on-disk owner (observer surfaces: top, healthz)."""
        rec = self.leases[shard_id].read()
        if rec is None:
            return None
        holder = rec.get("holder") or ""
        try:
            expires = float(rec.get("expires", 0.0))
        except (TypeError, ValueError):
            return None
        return holder if holder and time.time() < expires else None

    def drain(self, now: Optional[float] = None) -> List[int]:
        """Voluntary shutdown: release every lease and withdraw presence
        so the survivors rebalance immediately instead of waiting out
        the TTL."""
        now = time.time() if now is None else now
        self._stop.set()
        with self._lock:
            dropped = sorted(self.owned)
            for i in dropped:
                self.io.releases += 1
                self.leases[i].release(now)
            self.owned.clear()
            self._presence_path().unlink(missing_ok=True)
        return dropped

    def inject_drop(self, target: str = "*") -> List[int]:
        """Chaos hook (``drop_lease``): force-expire the on-disk lease of
        the targeted owned shard(s) without updating in-memory state —
        this process becomes the stale holder whose next renew must be
        fencing-rejected."""
        with self._lock:
            doomed = sorted(
                i
                for i in self.owned
                if target in ("*", str(i))
            )
            for i in doomed:
                self.leases[i].force_expire()
        return doomed


def read_shard_config(state_dir) -> Optional[int]:
    """The state dir's pinned shard count, or None when the control
    plane has never run sharded (observer surfaces: `tpujob top`,
    `tpujob why`)."""
    try:
        return int(
            json.loads(
                (Path(state_dir) / "leases" / "config.json").read_text()
            )["num_shards"]
        )
    except (OSError, ValueError, KeyError, TypeError):
        return None


def read_shard_owners(state_dir) -> Dict[int, str]:
    """Best-effort {shard: live holder} snapshot from the lease files."""
    leases_dir = Path(state_dir) / "leases"
    now = time.time()
    out: Dict[int, str] = {}
    try:
        entries = list(os.scandir(leases_dir))
    except OSError:
        return out
    for e in entries:
        if not (e.name.startswith("shard-") and e.name.endswith(".lease")):
            continue
        try:
            rec = json.loads(Path(e.path).read_text())
            shard = int(rec["shard"])
            holder = rec.get("holder") or ""
            expires = float(rec.get("expires", 0.0))
        except (OSError, ValueError, KeyError, TypeError):
            continue
        if holder and now < expires:
            out[shard] = holder
    return out
