"""Leader election for supervisors sharing one state dir.

Reference: the operator runs ``leaderelection.RunOrDie`` so that replicated
operator Deployments have exactly one active reconciler (SURVEY.md §2
"Entrypoint/CLI", §3.1 startup stack). The failure mode it prevents maps
1:1 here: two ``tpujob supervisor`` daemons pointed at the same state dir
would both claim jobs and double-spawn replica worlds.

Rebuild: an ``fcntl.flock`` lease on ``<state-dir>/leader.lock``. The OS
releases the lock when the holder dies (crash included), which gives the
standby automatic fail-over — the same property the k8s lease renewal loop
provides, minus the clock-skew caveats, since this is a single-host lock.
"""

from __future__ import annotations

import errno
import fcntl
import json
import os
import socket
import time
from pathlib import Path
from typing import Optional


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else


class LeaderLease:
    """An exclusive, crash-released lease on a state directory."""

    def __init__(self, state_dir: Path, identity: Optional[str] = None):
        self.path = Path(state_dir) / "leader.lock"
        self.identity = identity or f"{socket.gethostname()}_{os.getpid()}"
        self._fd: Optional[int] = None

    def acquire(self, blocking: bool = True, timeout: Optional[float] = None) -> bool:
        """Take the lease. Returns False iff non-blocking/timed-out and held
        elsewhere. Re-acquiring a held lease is a no-op returning True."""
        if self._fd is not None:
            return True
        self.path.parent.mkdir(parents=True, exist_ok=True)
        fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o644)
        # Monotonic deadline: a wall-clock step (NTP) must not stretch or
        # collapse the timeout.
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                flags = fcntl.LOCK_EX
                if not blocking or deadline is not None:
                    flags |= fcntl.LOCK_NB
                fcntl.flock(fd, flags)
                break
            except OSError as e:
                if e.errno not in (errno.EWOULDBLOCK, errno.EAGAIN):
                    # Not contention — e.g. flock unsupported on this fs.
                    os.close(fd)
                    raise
                if not blocking or (
                    deadline is not None and time.monotonic() >= deadline
                ):
                    os.close(fd)
                    return False
                time.sleep(0.05)
        # Record the holder for observability (healthz, error messages).
        # Any failure here must release + close the locked fd: leaking it
        # with self._fd unset would self-deadlock every retry in this
        # process (same-process fds conflict under flock) and block every
        # standby forever.
        try:
            os.ftruncate(fd, 0)
            os.pwrite(
                fd,
                json.dumps(
                    {"holder": self.identity, "pid": os.getpid(), "acquired": time.time()}
                ).encode(),
                0,
            )
        except OSError:
            try:
                fcntl.flock(fd, fcntl.LOCK_UN)
            finally:
                os.close(fd)
            raise
        self._fd = fd
        return True

    def release(self) -> None:
        if self._fd is None:
            return
        # Clear the holder record BEFORE unlocking so observers never read
        # our identity as the leader after we stepped down. (Crash release
        # skips this — holder() handles that via the pid liveness check.)
        try:
            os.ftruncate(self._fd, 0)
        except OSError:
            pass
        fcntl.flock(self._fd, fcntl.LOCK_UN)
        os.close(self._fd)
        self._fd = None

    def is_held(self) -> bool:
        return self._fd is not None

    def holder(self) -> Optional[str]:
        """Best-effort identity of the current holder (None if unheld).

        Deliberately LOCK-FREE: a flock probe (shared or exclusive) would
        momentarily contend with a real ``acquire`` attempt, making a
        concurrent standby's election spuriously fail just because
        someone asked who the leader is. Instead read the holder record
        and judge liveness by pid: the OS releases a dead holder's lock,
        and a dead pid means the record is stale.
        """
        if self._fd is not None:
            return self.identity
        try:
            content = self.path.read_text()
        except OSError:
            return None
        if not content.strip():
            return None
        try:
            rec = json.loads(content)
        except ValueError:
            return "<unknown>"
        pid = rec.get("pid")
        if isinstance(pid, int) and not _pid_alive(pid):
            return None  # crash-released: lock gone, record stale
        return rec.get("holder")

    def __enter__(self) -> "LeaderLease":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()
